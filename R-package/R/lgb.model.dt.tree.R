# Model-to-table flattening (role of reference
# R-package/R/lgb.model.dt.tree.R).
#
# Parses the LightGBM v4 model text directly (the same per-tree
# split_feature= / threshold= / left_child= ... lines the reference
# writes, ref: src/io/gbdt_model_text.cpp SaveModelToString), so no
# framework call and no JSON dependency is needed. Returns a base
# data.frame (the reference returns a data.table; the column contract
# is the same).

.lgb_tree_blocks <- function(model_str) {
  lines <- strsplit(model_str, "\n")[[1]]
  starts <- grep("^Tree=", lines)
  ends <- c(starts[-1] - 1L, length(lines))
  lapply(seq_along(starts), function(i) lines[starts[i]:ends[i]])
}

.lgb_tree_field <- function(block, key, as = as.numeric) {
  ln <- grep(paste0("^", key, "="), block, value = TRUE)
  if (length(ln) == 0) return(NULL)
  txt <- sub(paste0("^", key, "="), "", ln[1])
  if (nchar(trimws(txt)) == 0) return(as(character(0)))
  as(strsplit(trimws(txt), " +")[[1]])
}

#' Flatten a model into one row per node
#'
#' @param model an lgb.Booster.
#' @return data.frame with the reference's column contract:
#'   tree_index, depth, split_index, split_feature, node_parent,
#'   leaf_index, leaf_parent, split_gain, threshold, decision_type,
#'   default_left, internal_value, internal_count, leaf_value,
#'   leaf_count. Internal-node rows carry NA in the leaf columns and
#'   vice versa.
lgb.model.dt.tree <- function(model) {
  if (!inherits(model, "lgb.Booster")) stop("not an lgb.Booster")
  lines <- strsplit(model$model_str, "\n")[[1]]
  fn_line <- grep("^feature_names=", lines, value = TRUE)
  feat_names <- if (length(fn_line))
    strsplit(sub("^feature_names=", "", fn_line[1]), " ")[[1]]
  else character(0)
  .feat <- function(idx) {
    # split_feature indices are 0-based original feature ids
    out <- as.character(idx)
    have <- idx + 1L <= length(feat_names) & idx >= 0L
    out[have] <- feat_names[idx[have] + 1L]
    out
  }

  rows <- list()
  blocks <- .lgb_tree_blocks(model$model_str)
  for (ti in seq_along(blocks)) {
    b <- blocks[[ti]]
    num_leaves <- .lgb_tree_field(b, "num_leaves", as.integer)
    leaf_value <- .lgb_tree_field(b, "leaf_value")
    leaf_count <- .lgb_tree_field(b, "leaf_count", as.integer)
    if (is.null(num_leaves) || num_leaves <= 1L) {
      # stump: a single leaf, no internal nodes
      rows[[length(rows) + 1L]] <- data.frame(
        tree_index = ti - 1L, depth = 0L, split_index = NA_integer_,
        split_feature = NA_character_, node_parent = NA_integer_,
        leaf_index = 0L, leaf_parent = NA_integer_,
        split_gain = NA_real_, threshold = NA_real_,
        decision_type = NA_character_, default_left = NA,
        internal_value = NA_real_, internal_count = NA_integer_,
        leaf_value = if (length(leaf_value)) leaf_value[1] else 0.0,
        leaf_count = if (length(leaf_count)) leaf_count[1] else NA_integer_,
        stringsAsFactors = FALSE)
      next
    }
    split_feature <- .lgb_tree_field(b, "split_feature", as.integer)
    split_gain <- .lgb_tree_field(b, "split_gain")
    threshold <- .lgb_tree_field(b, "threshold")
    decision_type <- .lgb_tree_field(b, "decision_type", as.integer)
    left_child <- .lgb_tree_field(b, "left_child", as.integer)
    right_child <- .lgb_tree_field(b, "right_child", as.integer)
    internal_value <- .lgb_tree_field(b, "internal_value")
    internal_count <- .lgb_tree_field(b, "internal_count", as.integer)
    n_internal <- length(split_feature)

    # parents and depths via the child arrays (negative child ids are
    # -(leaf_index) - 1, the reference's encoding)
    node_parent <- rep(NA_integer_, n_internal)
    leaf_parent <- rep(NA_integer_, num_leaves)
    depth_internal <- rep(0L, n_internal)
    depth_leaf <- rep(0L, num_leaves)
    for (s in seq_len(n_internal)) {
      for (child in c(left_child[s], right_child[s])) {
        if (child >= 0L) {
          node_parent[child + 1L] <- s - 1L
          depth_internal[child + 1L] <- depth_internal[s] + 1L
        } else {
          li <- -child        # leaf index + 1
          leaf_parent[li] <- s - 1L
          depth_leaf[li] <- depth_internal[s] + 1L
        }
      }
    }
    # decision_type bit 2 is the default-left flag
    # (ref: include/LightGBM/tree.h kDefaultLeftMask)
    default_left <- bitwAnd(decision_type, 2L) > 0L

    rows[[length(rows) + 1L]] <- data.frame(
      tree_index = ti - 1L, depth = depth_internal,
      split_index = seq_len(n_internal) - 1L,
      split_feature = .feat(split_feature),
      node_parent = node_parent, leaf_index = NA_integer_,
      leaf_parent = NA_integer_, split_gain = split_gain,
      threshold = threshold,
      decision_type = ifelse(bitwAnd(decision_type, 1L) > 0L,
                             "==", "<="),
      default_left = default_left, internal_value = internal_value,
      internal_count = internal_count, leaf_value = NA_real_,
      leaf_count = NA_integer_, stringsAsFactors = FALSE)
    rows[[length(rows) + 1L]] <- data.frame(
      tree_index = ti - 1L, depth = depth_leaf,
      split_index = NA_integer_, split_feature = NA_character_,
      node_parent = NA_integer_,
      leaf_index = seq_len(num_leaves) - 1L, leaf_parent = leaf_parent,
      split_gain = NA_real_, threshold = NA_real_,
      decision_type = NA_character_, default_left = NA,
      internal_value = NA_real_, internal_count = NA_integer_,
      leaf_value = leaf_value[seq_len(num_leaves)],
      leaf_count = if (length(leaf_count) >= num_leaves)
        leaf_count[seq_len(num_leaves)] else NA_integer_,
      stringsAsFactors = FALSE)
  }
  do.call(rbind, rows)
}
