# Importance / interpretation plots (role of reference
# R-package/R/lgb.plot.importance.R and lgb.plot.interpretation.R).
#
# Base-graphics horizontal barplots — the reference's layout (top-N
# features, measure on x, names on y) without a graphics dependency.

#' Plot feature importance
#'
#' @param tree_imp output of lgb.importance.
#' @param top_n number of features to show.
#' @param measure "Gain" or "Frequency".
#' @param left_margin left margin (lines) for feature names.
#' @return the plotted subset, invisibly.
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain",
                                left_margin = 10L) {
  if (!measure %in% names(tree_imp))
    stop("measure must be one of: ",
         paste(setdiff(names(tree_imp), "Feature"), collapse = ", "))
  d <- tree_imp[order(-tree_imp[[measure]]), , drop = FALSE]
  d <- utils::head(d, as.integer(top_n))
  d <- d[rev(seq_len(nrow(d))), , drop = FALSE]  # largest on top
  old <- graphics::par(mar = c(4, left_margin, 2, 1))
  on.exit(graphics::par(old))
  graphics::barplot(d[[measure]], names.arg = d$Feature, horiz = TRUE,
                    las = 1, xlab = measure,
                    main = "Feature importance")
  invisible(d)
}

#' Plot per-row feature contributions
#'
#' @param tree_interpretation one element of lgb.interprete's output.
#' @param top_n number of features to show (bias excluded).
#' @param left_margin left margin (lines) for feature names.
#' @return the plotted subset, invisibly.
lgb.plot.interpretation <- function(tree_interpretation, top_n = 10L,
                                    left_margin = 10L) {
  d <- tree_interpretation[tree_interpretation$Feature != "<bias>", ,
                           drop = FALSE]
  d <- utils::head(d[order(-abs(d$Contribution)), , drop = FALSE],
                   as.integer(top_n))
  d <- d[rev(seq_len(nrow(d))), , drop = FALSE]
  old <- graphics::par(mar = c(4, left_margin, 2, 1))
  on.exit(graphics::par(old))
  graphics::barplot(d$Contribution, names.arg = d$Feature, horiz = TRUE,
                    las = 1, xlab = "Contribution",
                    main = "Feature contribution",
                    col = ifelse(d$Contribution >= 0,
                                 "steelblue", "firebrick"))
  invisible(d)
}
