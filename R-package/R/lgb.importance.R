# Feature importance from the model text (role of reference
# R-package/R/lgb.importance.R).
#
# The LightGBM v4 model text carries a "feature_importances:" block
# (split counts, ref: gbdt_model_text.cpp:377 / io/model_io.py:129);
# gain importances are recomputed from the per-tree split_gain and
# split_feature lines of the same text, so no framework call is needed.

#' Feature importance table
#'
#' @param booster an lgb.Booster.
#' @return data.frame with Feature, Gain, Frequency (both normalized
#'   like the reference's percentage = TRUE output).
lgb.importance <- function(booster) {
  if (!inherits(booster, "lgb.Booster")) stop("not an lgb.Booster")
  lines <- strsplit(booster$model_str, "\n")[[1]]

  fn_line <- grep("^feature_names=", lines, value = TRUE)
  feat_names <- if (length(fn_line))
    strsplit(sub("^feature_names=", "", fn_line[1]), " ")[[1]]
  else character(0)

  gains <- numeric(0)
  freq <- numeric(0)
  sf_lines <- grep("^split_feature=", lines, value = TRUE)
  sg_lines <- grep("^split_gain=", lines, value = TRUE)
  for (i in seq_along(sf_lines)) {
    feats <- as.integer(strsplit(sub("^split_feature=", "",
                                     sf_lines[i]), " ")[[1]])
    gvals <- as.numeric(strsplit(sub("^split_gain=", "",
                                     sg_lines[i]), " ")[[1]])
    m <- min(length(feats), length(gvals))
    for (j in seq_len(m)) {
      f <- feats[j] + 1L
      if (length(gains) < f) {
        length(gains) <- f
        length(freq) <- f
      }
      gains[f] <- sum(gains[f], gvals[j], na.rm = TRUE)
      freq[f] <- sum(freq[f], 1, na.rm = TRUE)
    }
  }
  gains[is.na(gains)] <- 0
  freq[is.na(freq)] <- 0
  nf <- max(length(gains), length(feat_names))
  length(gains) <- nf
  length(freq) <- nf
  gains[is.na(gains)] <- 0
  freq[is.na(freq)] <- 0
  if (length(feat_names) < nf)
    feat_names <- c(feat_names,
                    paste0("Column_",
                           seq.int(length(feat_names) + 1L, nf)))
  keep <- freq > 0
  d <- data.frame(Feature = feat_names[seq_len(nf)][keep],
                  Gain = gains[keep] / max(sum(gains), 1e-300),
                  Frequency = freq[keep] / max(sum(freq), 1),
                  stringsAsFactors = FALSE)
  d[order(-d$Gain), ]
}
