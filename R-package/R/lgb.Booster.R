# Minimal lgb.Booster (role of reference R-package/R/lgb.Booster.R).
#
# The booster is the LightGBM v4 model text -- the portable contract
# shared by the reference, this framework's Python API, its native C
# serving library and this R layer.

#' Load a model from a LightGBM model text file
lgb.load <- function(filename) {
  if (!file.exists(filename)) stop("model file not found: ", filename)
  bst <- list(model_file = filename,
              model_str = paste(readLines(filename), collapse = "\n"))
  class(bst) <- "lgb.Booster"
  bst
}

#' Save a booster's model text to a file
lgb.save <- function(booster, filename) {
  if (!inherits(booster, "lgb.Booster")) stop("not an lgb.Booster")
  writeLines(booster$model_str, filename)
  invisible(filename)
}

#' Dump the model structure as a JSON string
lgb.dump <- function(booster) {
  if (!inherits(booster, "lgb.Booster")) stop("not an lgb.Booster")
  out <- tempfile(fileext = ".json")
  f <- .lgb_booster_file(booster)
  code <- paste0(
    "import json, lightgbm_tpu as lgb;",
    "json.dump(lgb.Booster(model_file=", deparse(f), ").dump_model(),",
    "open(", deparse(out), ", 'w'))")
  rc <- system2(.lgb_python(), c("-c", shQuote(code)))
  if (rc != 0) stop("model dump failed (rc=", rc, ")")
  paste(readLines(out), collapse = "\n")
}

.lgb_booster_file <- function(booster) {
  if (file.exists(booster$model_file)) return(booster$model_file)
  f <- tempfile(fileext = ".txt")
  writeLines(booster$model_str, f)
  f
}

#' Predict with an lgb.Booster
#'
#' @param object the booster.
#' @param newdata numeric matrix / data.frame, or a path to a data file.
#' @param rawscore return raw margins instead of transformed scores.
#' @param predleaf return per-tree leaf indices.
#' @param predcontrib return SHAP feature contributions.
predict.lgb.Booster <- function(object, newdata, rawscore = FALSE,
                                predleaf = FALSE, predcontrib = FALSE,
                                ...) {
  if (is.character(newdata)) {
    data_file <- newdata
  } else {
    # prediction files follow the training layout: label column first
    # (dropped by the parser), features after -- prepend a dummy label
    mat <- as.matrix(newdata)
    data_file <- tempfile(fileext = ".csv")
    utils::write.table(cbind(0, mat), data_file, sep = ",",
                       row.names = FALSE, col.names = FALSE)
  }
  out <- tempfile(fileext = ".txt")
  lines <- c("task = predict",
             paste0("data = ", data_file),
             paste0("input_model = ", .lgb_booster_file(object)),
             paste0("output_result = ", out),
             "header = false")
  if (rawscore) lines <- c(lines, "predict_raw_score = true")
  if (predleaf) lines <- c(lines, "predict_leaf_index = true")
  if (predcontrib) lines <- c(lines, "predict_contrib = true")
  .lgb_cli(lines)
  res <- utils::read.table(out, sep = "\t", header = FALSE)
  if (ncol(res) == 1) res[[1]] else as.matrix(res)
}

print.lgb.Booster <- function(x, ...) {
  n_tree <- length(grep("^Tree=", strsplit(x$model_str, "\n")[[1]]))
  cat("lgb.Booster (lightgbm-tpu):", n_tree, "trees\n")
  invisible(x)
}
