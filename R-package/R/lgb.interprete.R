# Per-prediction interpretation (role of reference
# R-package/R/lgb.interprete.R).
#
# Built on predict(predcontrib = TRUE) — the framework's TreeSHAP
# contributions (last column is the bias/expected value, matching the
# reference's contribution layout). One data.frame per requested row,
# features sorted by |contribution| descending, bias row last — the
# reference's tree_interpretation shape.

#' Per-row feature contributions
#'
#' @param model an lgb.Booster.
#' @param data numeric matrix / data.frame of rows to explain.
#' @param idxset 1-based row indices of `data` to interpret (default:
#'   all rows).
#' @return list of data.frames (Feature, Contribution), one per row in
#'   `idxset`, sorted by absolute contribution; the intercept appears
#'   as Feature = "<bias>".
lgb.interprete <- function(model, data, idxset = NULL) {
  if (!inherits(model, "lgb.Booster")) stop("not an lgb.Booster")
  mat <- as.matrix(data)
  if (is.null(idxset)) idxset <- seq_len(nrow(mat))
  idxset <- as.integer(idxset)
  if (any(idxset < 1L | idxset > nrow(mat)))
    stop("idxset out of range")
  contrib <- predict.lgb.Booster(model, mat[idxset, , drop = FALSE],
                                 predcontrib = TRUE)
  contrib <- as.matrix(contrib)

  lines <- strsplit(model$model_str, "\n")[[1]]
  fn_line <- grep("^feature_names=", lines, value = TRUE)
  feat_names <- if (length(fn_line))
    strsplit(sub("^feature_names=", "", fn_line[1]), " ")[[1]]
  else paste0("Column_", seq_len(ncol(contrib) - 1L))
  n_feat <- ncol(contrib) - 1L
  if (length(feat_names) < n_feat)
    feat_names <- c(feat_names,
                    paste0("Column_",
                           seq.int(length(feat_names) + 1L, n_feat)))

  lapply(seq_along(idxset), function(i) {
    vals <- as.numeric(contrib[i, seq_len(n_feat)])
    ord <- order(-abs(vals))
    data.frame(
      Feature = c(feat_names[seq_len(n_feat)][ord], "<bias>"),
      Contribution = c(vals[ord], as.numeric(contrib[i, n_feat + 1L])),
      stringsAsFactors = FALSE)
  })
}
