# CLI-backed k-fold cross-validation (role of reference
# R-package/R/lgb.cv.R).
#
# Folds are materialized as train/valid CSV pairs and each fold trains
# through the framework CLI with per-iteration metric printing
# (metric_freq=1); the per-fold eval curves are parsed from the CLI's
# "[i]  valid_0's metric: value" lines (callback.py log_evaluation
# format, ref: callback.py:109) and aggregated into mean/stdv curves.
# Early stopping is applied in R on the AGGREGATED mean curve — the
# reference's CV semantics (one decision for all folds), not per-fold.

.lgb_parse_eval <- function(lines) {
  # "[LightGBM-TPU] [Info] [12]\tvalid_1's l2: 0.0234" (the logger
  # prefixes log_evaluation's "[i]\tname's metric: value" lines)
  hits <- regmatches(lines,
                     regexec("\\[(\\d+)\\]\\s+valid_\\d+'s ([^:]+): ([-0-9.eE+naif]+)",
                             lines))
  hits <- Filter(function(h) length(h) == 4, hits)
  if (length(hits) == 0) {
    return(data.frame(iter = integer(), metric = character(),
                      value = numeric(), stringsAsFactors = FALSE))
  }
  pick <- function(i) vapply(hits, function(h) h[i], character(1))
  data.frame(
    iter = as.integer(pick(2)),
    metric = trimws(pick(3)),
    value = as.numeric(pick(4)),
    stringsAsFactors = FALSE)
}

.lgb_metric_higher_better <- function(metric) {
  grepl("^(auc|average_precision|ndcg|map|r2)", metric)
}

#' k-fold cross validation
#'
#' @param params named list of training parameters.
#' @param data an lgb.Dataset built from matrix data.
#' @param nrounds number of boosting iterations per fold.
#' @param nfold number of folds.
#' @param early_stopping_rounds patience on the aggregated mean metric
#'   (the first metric parsed); NULL disables.
#' @param seed fold-assignment RNG seed.
#' @param verbose verbosity for the underlying CLI runs.
#' @param callbacks list of callback functions (lgb.cb.*) replayed over
#'   the aggregated per-iteration eval records (see callback.R for the
#'   replay contract).
#' @return list with record_evals (per-metric eval_mean/eval_stdv),
#'   best_iter, best_score and the per-fold booster model files.
lgb.cv <- function(params = list(), data, nrounds = 100L, nfold = 5L,
                   early_stopping_rounds = NULL, seed = 0L,
                   verbose = -1L, callbacks = list()) {
  if (!inherits(data, "lgb.Dataset")) stop("data must be an lgb.Dataset")
  if (!isTRUE(data$owned))
    stop("lgb.cv needs an lgb.Dataset built from matrix data ",
         "(file-backed datasets have unknown row structure)")
  if (file.exists(paste0(data$file, ".query")))
    stop("lgb.cv does not support grouped (ranking) data yet")
  rows <- readLines(data$file)
  n <- length(rows)
  if (nfold < 2L || n < nfold) stop("bad nfold for ", n, " rows")
  weights <- if (file.exists(paste0(data$file, ".weight")))
    readLines(paste0(data$file, ".weight")) else NULL

  set.seed(seed)
  fold_id <- sample(rep_len(seq_len(nfold), n))
  curves <- list()   # fold -> data.frame(iter, metric, value)
  boosters <- character(nfold)
  for (k in seq_len(nfold)) {
    tr <- which(fold_id != k)
    va <- which(fold_id == k)
    trf <- tempfile(fileext = ".csv")
    vaf <- tempfile(fileext = ".csv")
    writeLines(rows[tr], trf)
    writeLines(rows[va], vaf)
    if (!is.null(weights)) {
      writeLines(weights[tr], paste0(trf, ".weight"))
      writeLines(weights[va], paste0(vaf, ".weight"))
    }
    model_file <- tempfile(fileext = ".txt")
    conf <- tempfile(fileext = ".conf")
    writeLines(c("task = train",
                 paste0("data = ", trf),
                 paste0("valid = ", vaf),
                 paste0("num_iterations = ", as.integer(nrounds)),
                 paste0("output_model = ", model_file),
                 "metric_freq = 1",
                 # eval lines are what lgb.cv parses — verbosity >= 1
                 # keeps log_evaluation's output flowing
                 paste0("verbosity = ", max(as.integer(verbose), 1L)),
                 .lgb_param_lines(data$params),
                 .lgb_param_lines(params)), conf)
    out <- suppressWarnings(system2(
      .lgb_python(), c("-m", "lightgbm_tpu.cli", paste0("config=", conf)),
      stdout = TRUE, stderr = TRUE))
    status <- attr(out, "status")
    if (!is.null(status) && status != 0)
      stop("lgb.cv fold ", k, " failed:\n",
           paste(utils::tail(out, 10), collapse = "\n"))
    curves[[k]] <- .lgb_parse_eval(out)
    boosters[k] <- model_file
  }

  metrics <- unique(unlist(lapply(curves, function(d) d$metric)))
  if (length(metrics) == 0)
    stop("lgb.cv: no eval lines parsed from the CLI output")
  record_evals <- list(valid = list())
  for (m in metrics) {
    per_fold <- lapply(curves, function(d) {
      d <- d[d$metric == m, ]
      d$value[order(d$iter)]
    })
    iters <- min(vapply(per_fold, length, integer(1)))
    mat <- vapply(per_fold, function(v) v[seq_len(iters)],
                  numeric(iters))
    if (iters == 1) mat <- matrix(mat, nrow = 1)
    record_evals$valid[[m]] <- list(
      eval_mean = rowMeans(mat),
      eval_stdv = apply(mat, 1, stats::sd))
  }

  # callback replay over the aggregated curves (record / print /
  # early-stop — the reference's cb_* chain, applied to the mean
  # curve: one decision for all folds)
  m0 <- metrics[[1]]
  mean_curve <- record_evals$valid[[m0]]$eval_mean
  hib <- .lgb_metric_higher_better(m0)
  chain <- callbacks
  if (!is.null(early_stopping_rounds))
    chain <- c(chain,
               list(lgb.cb.early.stop(early_stopping_rounds,
                                      verbose = verbose >= 1L)))
  curve_rows <- do.call(rbind, lapply(metrics, function(m) {
    r <- record_evals$valid[[m]]
    data.frame(iter = seq_along(r$eval_mean), metric = m,
               value = r$eval_mean, stdv = r$eval_stdv,
               data_name = "valid", stringsAsFactors = FALSE)
  }))
  # the FIRST metric must lead each iteration group (early stop keys
  # on eval_list[[1]])
  curve_rows <- curve_rows[order(curve_rows$iter,
                                 match(curve_rows$metric, metrics)), ]
  env <- .lgb_replay_callbacks(curve_rows, chain)
  best_iter <- if (env$best_iter > 0L) env$best_iter
               else if (hib) which.max(mean_curve)
               else which.min(mean_curve)
  if (isTRUE(env$met_early_stop)) {
    kept <- env$iteration
    record_evals$valid <- lapply(record_evals$valid, function(r)
      list(eval_mean = r$eval_mean[seq_len(kept)],
           eval_stdv = r$eval_stdv[seq_len(kept)]))
    mean_curve <- mean_curve[seq_len(kept)]
  }

  structure(list(record_evals = record_evals,
                 best_iter = as.integer(best_iter),
                 best_score = mean_curve[best_iter],
                 metric = m0,
                 booster_files = boosters),
            class = "lgb.CVBooster")
}

print.lgb.CVBooster <- function(x, ...) {
  cat("lgb.CVBooster:", length(x$booster_files), "folds, best_iter =",
      x$best_iter, paste0("(", x$metric, " = ",
                          signif(x$best_score, 6), ")\n"))
  invisible(x)
}
