# Minimal lgb.train (role of reference R-package/R/lgb.train.R:44).
#
# Drives the framework's CLI (python -m lightgbm_tpu.cli) with a
# generated config file -- the same train task the reference CLI runs --
# and wraps the resulting LightGBM v4 model.txt in an lgb.Booster.

.lgb_python <- function() {
  Sys.getenv("LIGHTGBM_TPU_PYTHON", unset = "python3")
}

.lgb_cli <- function(conf_lines) {
  conf <- tempfile(fileext = ".conf")
  writeLines(conf_lines, conf)
  rc <- system2(.lgb_python(), c("-m", "lightgbm_tpu.cli",
                                 paste0("config=", conf)))
  if (rc != 0) stop("lightgbm_tpu CLI failed (rc=", rc, ")")
  invisible(NULL)
}

.lgb_param_lines <- function(params) {
  vapply(names(params), function(k) {
    v <- params[[k]]
    if (is.logical(v)) v <- ifelse(v, "true", "false")
    paste0(k, " = ", paste(v, collapse = ","))
  }, character(1))
}

#' Train a gradient-boosted model
#'
#' @param params named list of training parameters (reference names and
#'   aliases all work -- the config registry resolves them).
#' @param data an lgb.Dataset.
#' @param nrounds number of boosting iterations.
#' @param valids named list of lgb.Dataset objects for evaluation.
#' @param early_stopping_rounds optional early-stopping patience.
#' @param verbose verbosity passed through.
#' @return an lgb.Booster.
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), early_stopping_rounds = NULL,
                      verbose = 1L) {
  if (!inherits(data, "lgb.Dataset")) stop("data must be an lgb.Dataset")
  model_file <- tempfile(fileext = ".txt")
  lines <- c("task = train",
             paste0("data = ", data$file),
             paste0("num_iterations = ", as.integer(nrounds)),
             paste0("output_model = ", model_file),
             paste0("verbosity = ", as.integer(verbose)),
             .lgb_param_lines(data$params),
             .lgb_param_lines(params))
  if (length(valids) > 0) {
    vfiles <- vapply(valids, function(v) v$file, character(1))
    lines <- c(lines, paste0("valid = ", paste(vfiles, collapse = ",")))
  }
  if (!is.null(early_stopping_rounds))
    lines <- c(lines, paste0("early_stopping_round = ",
                             as.integer(early_stopping_rounds)))
  .lgb_cli(lines)
  lgb.load(model_file)
}

#' Simplified training entry point (role of reference lightgbm.R)
lightgbm <- function(data, label = NULL, params = list(),
                     nrounds = 100L, ...) {
  ds <- if (inherits(data, "lgb.Dataset")) data
        else lgb.Dataset(data, label = label)
  lgb.train(params = params, data = ds, nrounds = nrounds, ...)
}
