# Minimal lgb.Dataset (role of reference R-package/R/lgb.Dataset.R).
#
# The dataset is materialized as a CSV file with the label in the first
# column -- the framework CLI's native ingestion format (header=false,
# label column 0). Weights / groups ride along as the reference's
# .weight / .query sidecar files (io/file_loader.py picks them up by
# path convention).

#' Construct a dataset for lgb.train
#'
#' @param data numeric matrix or data.frame of features, or a path to an
#'   existing CSV/TSV/LibSVM file (used as-is).
#' @param label numeric vector of targets (ignored when `data` is a path).
#' @param weight optional per-row weights.
#' @param group optional query sizes for ranking.
#' @param params named list of dataset parameters (e.g. max_bin),
#'   forwarded to the trainer config.
lgb.Dataset <- function(data, label = NULL, weight = NULL, group = NULL,
                        params = list()) {
  ds <- list(params = params)
  if (is.character(data)) {
    ds$file <- data
    ds$owned <- FALSE
  } else {
    if (is.null(label)) stop("lgb.Dataset: label is required for matrix data")
    mat <- as.matrix(data)
    if (nrow(mat) != length(label))
      stop("lgb.Dataset: nrow(data) != length(label)")
    f <- tempfile(fileext = ".csv")
    utils::write.table(cbind(label, mat), f, sep = ",",
                       row.names = FALSE, col.names = FALSE)
    if (!is.null(weight))
      writeLines(as.character(weight), paste0(f, ".weight"))
    if (!is.null(group))
      writeLines(as.character(group), paste0(f, ".query"))
    ds$file <- f
    ds$owned <- TRUE
    ds$ncol <- ncol(mat)
  }
  class(ds) <- "lgb.Dataset"
  ds
}
