# Evaluation callbacks (role of reference R-package/R/callback.R).
#
# The reference's callbacks run live inside the C++ training loop. This
# layer trains through the framework CLI, so callbacks run as a REPLAY:
# the CLI's per-iteration eval lines are parsed (.lgb_parse_eval) and
# then streamed, iteration by iteration, through the callback chain
# with the same env contract the reference uses (iteration,
# eval_list, best_iter, best_score, met_early_stop). Semantics for
# record / print / early-stop match; anything needing to MUTATE
# training mid-flight (e.g. reset_parameter) is out of scope and
# documented as such.

#' Print evaluation callback
#' @param period print every `period` iterations.
lgb.cb.print.evaluation <- function(period = 1L) {
  cb <- function(env) {
    i <- env$iteration
    if (period > 0L && (i - 1L) %% period == 0L && length(env$eval_list)) {
      msg <- paste(vapply(env$eval_list, function(e)
        sprintf("%s's %s: %g%s", e$data_name, e$name, e$value,
                if (!is.null(e$stdv)) sprintf(" + %g", e$stdv) else ""),
        character(1)), collapse = "  ")
      cat(sprintf("[%d]  %s\n", i, msg))
    }
    env
  }
  attr(cb, "name") <- "cb_print_evaluation"
  cb
}

#' Record evaluation callback — fills env$record_evals like the
#' reference's cb_record_evaluation.
lgb.cb.record.evaluation <- function() {
  cb <- function(env) {
    for (e in env$eval_list) {
      dn <- e$data_name
      if (is.null(env$record_evals[[dn]]))
        env$record_evals[[dn]] <- list()
      rec <- env$record_evals[[dn]][[e$name]]
      if (is.null(rec)) rec <- list(eval = numeric(0),
                                    eval_err = numeric(0))
      rec$eval <- c(rec$eval, e$value)
      if (!is.null(e$stdv)) rec$eval_err <- c(rec$eval_err, e$stdv)
      env$record_evals[[dn]][[e$name]] <- rec
    }
    env
  }
  attr(cb, "name") <- "cb_record_evaluation"
  cb
}

#' Early-stopping callback on the FIRST eval entry (the reference's
#' aggregated-CV decision; ref callback.R cb_early_stop).
#' @param stopping_rounds patience in iterations.
#' @param verbose print the stop decision.
lgb.cb.early.stop <- function(stopping_rounds, verbose = TRUE) {
  stopping_rounds <- as.integer(stopping_rounds)
  cb <- function(env) {
    if (length(env$eval_list) == 0) return(env)
    e <- env$eval_list[[1]]
    hib <- .lgb_metric_higher_better(e$name)
    better <- is.null(env$best_score) ||
      (hib && e$value > env$best_score) ||
      (!hib && e$value < env$best_score)
    if (better) {
      env$best_score <- e$value
      env$best_iter <- env$iteration
    } else if (env$iteration - env$best_iter >= stopping_rounds) {
      env$met_early_stop <- TRUE
      if (verbose)
        cat(sprintf(
          "Early stopping, best iteration is: [%d]  %s's %s: %g\n",
          env$best_iter, e$data_name, e$name, env$best_score))
    }
    env
  }
  attr(cb, "name") <- "cb_early_stop"
  cb
}

# Replay a parsed eval curve set through a callback chain.
# curves: data.frame(iter, metric, value[, stdv]) with data_name column.
.lgb_replay_callbacks <- function(curves, callbacks) {
  env <- list(iteration = 0L, eval_list = list(),
              record_evals = list(), best_iter = 0L,
              best_score = NULL, met_early_stop = FALSE)
  if (nrow(curves) == 0) return(env)
  for (i in sort(unique(curves$iter))) {
    rows <- curves[curves$iter == i, , drop = FALSE]
    env$iteration <- as.integer(i)
    env$eval_list <- lapply(seq_len(nrow(rows)), function(r) {
      e <- list(data_name = if ("data_name" %in% names(rows))
                  rows$data_name[r] else "valid",
                name = rows$metric[r], value = rows$value[r])
      if ("stdv" %in% names(rows)) e$stdv <- rows$stdv[r]
      e
    })
    for (cb in callbacks) env <- cb(env)
    if (isTRUE(env$met_early_stop)) break
  }
  env
}
