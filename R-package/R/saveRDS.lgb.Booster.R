# RDS round-trip helpers (role of reference
# R-package/R/saveRDS.lgb.Booster.R and readRDS.lgb.Booster.R).
#
# The reference needs these because its Booster holds an external
# pointer that must be re-materialized from the raw model string on
# load. This layer's booster is already a plain R list carrying
# model_str, so base saveRDS would work — the wrappers exist for API
# parity and to guarantee the serialized object is self-contained
# (model_str present, stale temp-file path dropped) and re-classed on
# read.

#' Save an lgb.Booster to an RDS file
#'
#' @param object the booster.
#' @param file path to write.
#' @param ... passed through to base::saveRDS.
saveRDS.lgb.Booster <- function(object, file, ...) {
  if (!inherits(object, "lgb.Booster")) stop("not an lgb.Booster")
  if (is.null(object$model_str) || !nzchar(object$model_str))
    stop("booster has no model_str; cannot serialize")
  # the temp model file will not exist in the next session — keep only
  # the self-contained string
  object$model_file <- NULL
  saveRDS(object, file = file, ...)
  invisible(file)
}

#' Read an lgb.Booster from an RDS file
#'
#' @param file path written by saveRDS.lgb.Booster (or base saveRDS of
#'   a booster).
#' @param ... passed through to base::readRDS.
#' @return an lgb.Booster.
readRDS.lgb.Booster <- function(file, ...) {
  obj <- readRDS(file = file, ...)
  if (is.null(obj$model_str) || !nzchar(obj$model_str))
    stop("RDS file does not contain a serialized lgb.Booster")
  # re-materialize a model file lazily on first use (.lgb_booster_file)
  obj$model_file <- obj$model_file %||% tempfile(fileext = ".txt")
  if (!file.exists(obj$model_file))
    writeLines(obj$model_str, obj$model_file)
  class(obj) <- "lgb.Booster"
  obj
}

`%||%` <- function(a, b) if (is.null(a)) b else a
