# Eval-record accessor (role of reference
# R-package/R/lgb.Booster.R lgb.get.eval.result).

#' Extract a recorded evaluation curve
#'
#' @param modelfit an lgb.CVBooster (from lgb.cv) or a callback replay
#'   env carrying record_evals.
#' @param data_name evaluation dataset name (e.g. "valid").
#' @param eval_name metric name (e.g. "l2", "auc").
#' @param iters optional iteration subset (1-based).
#' @param is_err return the stdv/error series instead of the mean.
#' @return numeric vector of metric values.
lgb.get.eval.result <- function(modelfit, data_name, eval_name,
                                iters = NULL, is_err = FALSE) {
  rec <- modelfit$record_evals
  if (is.null(rec)) stop("no record_evals in this object")
  dn <- rec[[data_name]]
  if (is.null(dn))
    stop("data_name not found; available: ",
         paste(names(rec), collapse = ", "))
  entry <- dn[[eval_name]]
  if (is.null(entry))
    stop("eval_name not found; available: ",
         paste(names(dn), collapse = ", "))
  # lgb.cv stores eval_mean/eval_stdv; replay envs store eval/eval_err
  series <- if (is_err) entry$eval_stdv %||% entry$eval_err
            else entry$eval_mean %||% entry$eval
  if (is.null(series)) stop("requested series not recorded")
  if (!is.null(iters)) series <- series[as.integer(iters)]
  series
}
