# k-fold CV mirroring the reference's R-package/demo/cross_validation.R.
# Run from the repo root:
#   Rscript R-package/demo/cross_validation.R

invisible(lapply(list.files("R-package/R", full.names = TRUE), source))

set.seed(1)
n <- 600
X <- matrix(rnorm(n * 6), n, 6)
y <- as.numeric(X[, 1] - 0.5 * X[, 2] * X[, 3] + rnorm(n) * 0.1 > 0)

ds <- lgb.Dataset(X, label = y)
cv <- lgb.cv(list(objective = "binary", num_leaves = 15,
                  metric = "binary_logloss", device_type = "cpu"),
             ds, nrounds = 25, nfold = 3,
             early_stopping_rounds = 10)
print(cv)
stopifnot(cv$best_iter >= 1,
          length(cv$record_evals$valid$binary_logloss$eval_mean) >= 1)
cat("cross_validation OK\n")
