# Basic walkthrough mirroring examples/binary_classification (role of
# reference R-package/demo/basic_walkthrough.R).
#
# Run from the repo root after `python examples/generate_data.py`:
#   Rscript R-package/demo/basic_walkthrough.R

invisible(lapply(list.files("R-package/R", full.names = TRUE), source))

train_file <- "examples/binary_classification/binary.train"
test_file <- "examples/binary_classification/binary.test"
if (!file.exists(train_file))
  stop("run `python examples/generate_data.py` first")

# file-backed datasets are used as-is by the CLI (label-first TSV)
dtrain <- lgb.Dataset(train_file)
dtest <- lgb.Dataset(test_file)

params <- list(objective = "binary", num_leaves = 63,
               learning_rate = 0.1, metric = "binary_logloss,auc",
               device_type = "cpu")

bst <- lgb.train(params, dtrain, nrounds = 30, valids = list(test = dtest),
                 early_stopping_rounds = 20)
print(bst)

# predictions: probability, raw margin, SHAP contributions
p <- predict(bst, test_file)
cat("mean predicted probability:", mean(p), "\n")
raw <- predict(bst, test_file, rawscore = TRUE)
contrib <- predict(bst, test_file, predcontrib = TRUE)
cat("contrib columns (F+1):", ncol(contrib), "\n")
# contributions sum to the raw margin (TreeSHAP local accuracy)
stopifnot(max(abs(rowSums(contrib) - raw)) < 1e-4)

# model round-trip
f <- tempfile(fileext = ".txt")
lgb.save(bst, f)
bst2 <- lgb.load(f)
p2 <- predict(bst2, test_file)
stopifnot(identical(p, p2))

# importance table from the model text
imp <- lgb.importance(bst)
print(utils::head(imp, 5))
cat("basic_walkthrough OK\n")
