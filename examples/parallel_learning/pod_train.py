"""Pod-scale distributed training walkthrough (one process per host).

The SPMD translation of the reference's parallel-learning guide
(ref: docs/Parallel-Learning-Guide.rst:58+ — build a machine list, pick
ports, start N copies): here every host runs THIS script unchanged; the
launcher contract (LGBM_TPU_* env vars, or TPU-pod auto-detection with
no env at all) wires the world, and the global mesh spans every host's
chips. Collectives ride ICI/DCN via XLA — no machine list, no ports.

Launch examples:

  # TPU pod (GKE/QR): just run it on every host — zero config
  python pod_train.py

  # any generic launcher (SLURM, mpirun, k8s): set the env contract
  LGBM_TPU_COORDINATOR=host0:8476 LGBM_TPU_NUM_PROCESSES=4 \
  LGBM_TPU_PROCESS_ID=$RANK python pod_train.py

  # localhost rehearsal without hardware (2 procs x 2 virtual devices)
  python -c "from lightgbm_tpu.distributed import launch_local; \
             print(launch_local(['python', 'pod_train.py'], 2, \
                                cpu_devices_per_process=2))"

Each process loads ITS OWN row shard (per-rank file or slice — the
reference's pre-partitioned-data convention) and `tree_learner=data`
makes histograms global through psum.
"""
import os
import sys

# runnable straight from a repo checkout (drop when pip-installed)
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

from lightgbm_tpu.distributed import init_from_env  # noqa: E402

rank = init_from_env()          # must precede any other jax use

import numpy as np              # noqa: E402

import lightgbm_tpu as lgb      # noqa: E402
from lightgbm_tpu.distributed import num_processes  # noqa: E402


def load_data():
    """The GLOBAL training table, loaded identically on every host.

    Multi-host contract (SPMD): every process passes the same global
    arrays; jax then places only each device's ROW SHARD into its HBM
    (host RAM holds the full table during ingest — the device memory,
    not the host copy, is what scales with the pod). The reference's
    pre_partition per-machine-file mode (each host reads only its rows)
    is not yet wired through the binning sync and is the documented gap
    here. Synthetic data keeps the walkthrough runnable anywhere."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(40_000, 16)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2] * X[:, 3] > 0)
    return X, y.astype(np.float32)


def main() -> None:
    world = num_processes()
    X, y = load_data()
    bst = lgb.train(
        {"objective": "binary", "tree_learner": "data",
         "num_leaves": 63, "learning_rate": 0.1, "verbose": -1,
         # bit-identical across world sizes: exact int32 histogram
         # accumulation under the global scales
         "use_quantized_grad": True, "stochastic_rounding": False,
         "deterministic": True, "seed": 7},
        lgb.Dataset(X, label=y), num_boost_round=30)
    if rank == 0:
        bst.save_model("pod_model.txt")
        pred = bst.predict(X)
        acc = float(np.mean((pred > 0.5) == y))
        print(f"[pod_train] world={world} train-shard acc={acc:.4f} "
              "model -> pod_model.txt", flush=True)


if __name__ == "__main__":
    main()
