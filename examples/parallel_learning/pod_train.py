"""Pod-scale distributed training walkthrough (one process per host).

The SPMD translation of the reference's parallel-learning guide
(ref: docs/Parallel-Learning-Guide.rst:58+ — build a machine list, pick
ports, start N copies): here every host runs THIS script unchanged; the
launcher contract (LGBM_TPU_* env vars, or TPU-pod auto-detection with
no env at all) wires the world, and the global mesh spans every host's
chips. Collectives ride ICI/DCN via XLA — no machine list, no ports.

Launch examples:

  # TPU pod (GKE/QR): just run it on every host — zero config
  python pod_train.py

  # any generic launcher (SLURM, mpirun, k8s): set the env contract
  LGBM_TPU_COORDINATOR=host0:8476 LGBM_TPU_NUM_PROCESSES=4 \
  LGBM_TPU_PROCESS_ID=$RANK python pod_train.py

  # localhost rehearsal without hardware (2 procs x 2 virtual devices)
  python -c "from lightgbm_tpu.distributed import launch_local; \
             print(launch_local(['python', 'pod_train.py'], 2, \
                                cpu_devices_per_process=2))"

  # SUPERVISED rehearsal (ISSUE 10 fault-tolerant gang): per-rank
  # heartbeat supervision, rank death SIGTERMs the survivors, and the
  # whole gang auto-relaunches from the newest gang manifest — one
  # rank death costs one resume, not the session
  python pod_train.py --local-gang 2

Each process loads ITS OWN row shard (per-rank slice here; a per-rank
file via 'data_{rank}.csv' works the same) and ``pre_partition=true``
engages sharded ingestion: distributed bin finding (per-shard sample
summaries → feature-sliced find_bin → BinMapper allgather) makes the
bin boundaries globally identical, each host bins only its rows, and
the device mesh is fed from the process-local shards — host RAM per
process is O(rows/world), the reference's 176 GB/machine Criteo recipe
(src/io/dataset_loader.cpp:1175-1219) in SPMD form. See
docs/TPU_RUNBOOK.md "Sharded ingestion".
"""
import os
import sys

# runnable straight from a repo checkout (drop when pip-installed)
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", ".."))

_GANG_FLAG = "--local-gang"
_LAUNCHER = _GANG_FLAG in sys.argv

if not _LAUNCHER:
    from lightgbm_tpu.distributed import init_from_env  # noqa: E402

    rank = init_from_env()      # must precede any other jax use

    import numpy as np          # noqa: E402

    import lightgbm_tpu as lgb  # noqa: E402
    from lightgbm_tpu.distributed import (num_processes,  # noqa: E402
                                          row_slice)

N_ROWS = int(os.environ.get("POD_TRAIN_ROWS", 40_000))
N_FEATURES = 16
_GEN_BLOCK = 8192


def load_data(rank: int, world: int):
    """THIS process's row shard only — no host ever holds the global
    table. Synthetic data keeps the walkthrough runnable anywhere: the
    deterministic global table is defined in fixed 8192-row blocks,
    each seeded by its block index, and a rank materializes ONLY the
    blocks overlapping its slice — every world size trains on the same
    logical rows at O(rows/world) host memory (a real deployment reads
    a per-rank file or slice instead, e.g.
    ``lgb.Dataset("higgs_{rank}.csv", params={"pre_partition": True})``).
    """
    lo, hi = row_slice(N_ROWS, rank, world)
    parts = []
    for b in range(lo // _GEN_BLOCK, (max(hi, lo + 1) - 1) // _GEN_BLOCK + 1):
        b_lo = b * _GEN_BLOCK
        n_blk = min(b_lo + _GEN_BLOCK, N_ROWS) - b_lo
        blk = np.random.default_rng([7, b]).normal(
            size=(n_blk, N_FEATURES)).astype(np.float32)
        parts.append(blk[max(lo - b_lo, 0):hi - b_lo])
    X = np.concatenate(parts, axis=0)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2] * X[:, 3] > 0)
    return X, y.astype(np.float32)


def main() -> None:
    world = num_processes()
    X, y = load_data(rank, world)
    # fault tolerance (ISSUE 10): with a checkpoint dir set, rank 0
    # commits CRC checkpoints + gang manifests (world size, per-rank
    # shard digests) and EVERY rank resumes from the newest committed
    # manifest — the supervised launcher below relaunches a failed
    # gang through exactly this path
    ckpt_dir = os.environ.get("POD_TRAIN_CKPT_DIR", "")
    callbacks = []
    if ckpt_dir and rank == 0:
        callbacks.append(lgb.checkpoint_callback(
            ckpt_dir, every_n=int(os.environ.get("POD_TRAIN_CKPT_EVERY",
                                                 "5")), keep_last=5))
    bst = lgb.train(
        {"objective": "binary", "tree_learner": "data",
         "num_leaves": 63, "learning_rate": 0.1, "verbose": -1,
         # sharded ingestion: per-host row shards, distributed bin
         # finding, O(rows/world) host memory
         "pre_partition": True,
         # bit-identical across world sizes: exact int32 histogram
         # accumulation under the global scales
         "use_quantized_grad": True, "stochastic_rounding": False,
         "deterministic": True, "seed": 7},
        lgb.Dataset(X, label=y), num_boost_round=30,
        callbacks=callbacks, resume_from=ckpt_dir or None)
    if rank == 0:
        bst.save_model("pod_model.txt")
        pred = bst.predict(X)
        acc = float(np.mean((pred > 0.5) == y))
        print(f"[pod_train] world={world} shard_rows={len(X)} "
              f"train-shard acc={acc:.4f} model -> pod_model.txt",
              flush=True)


def _launch_gang() -> None:
    """``--local-gang N``: run N ranks of THIS script as a SUPERVISED
    fault-tolerant gang (robustness/gang.py). The launcher never runs a
    jax op or initializes a backend — supervisor discipline: backend
    init is what hangs on a wedged tunnel — and a mid-run rank death
    SIGTERMs the survivors and relaunches the gang, resuming from the
    newest valid gang manifest in POD_TRAIN_CKPT_DIR (a tmpdir by
    default)."""
    import tempfile

    from lightgbm_tpu.robustness.gang import run_supervised

    i = sys.argv.index(_GANG_FLAG)
    world = (int(sys.argv[i + 1])
             if len(sys.argv) > i + 1 and sys.argv[i + 1].isdigit()
             else 2)
    ckpt = os.environ.get("POD_TRAIN_CKPT_DIR") or \
        tempfile.mkdtemp(prefix="pod_train_ckpt_")
    results = run_supervised(
        [sys.executable, os.path.abspath(__file__)], world,
        cpu_devices_per_process=int(
            os.environ.get("POD_TRAIN_DEVICES", "2")),
        timeout=float(os.environ.get("POD_TRAIN_TIMEOUT", "600")),
        env_extra={"POD_TRAIN_CKPT_DIR": ckpt},
        label="pod_train gang")
    for r, (rc, out) in enumerate(results):
        print(f"--- rank {r} (rc={rc}) ---\n{out}", end="", flush=True)


if __name__ == "__main__":
    if _LAUNCHER:
        _launch_gang()
    else:
        main()
