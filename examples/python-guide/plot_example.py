"""Plotting API tour: importance, split values, tree digraph/plot, metric
curves during training (requires matplotlib; graphviz optional)."""
import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(1000, 10)).astype(np.float32)
y = X[:, 0] * 2 + X[:, 1] ** 2 + 0.1 * rng.normal(size=1000)

train_data = lgb.Dataset(X[:800], label=y[:800])
valid_data = train_data.create_valid(X[800:], label=y[800:])

evals_result = {}
bst = lgb.train({"objective": "regression", "metric": "l2", "verbose": -1},
                train_data, num_boost_round=50, valid_sets=[valid_data],
                callbacks=[lgb.record_evaluation(evals_result)])

try:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
except ImportError:
    raise SystemExit("matplotlib is required for plot_example.py")

print("Plotting feature importances...")
ax = lgb.plot_importance(bst, max_num_features=10)
plt.savefig("importance.png")

print("Plotting split value histogram...")
ax = lgb.plot_split_value_histogram(bst, feature=0)
plt.savefig("split_value.png")

print("Plotting metric during training...")
ax = lgb.plot_metric(evals_result, metric="l2")
plt.savefig("metric.png")

print("Plotting tree 0...")
try:
    ax = lgb.plot_tree(bst, tree_index=0, show_info=["split_gain"])
    plt.savefig("tree.png")
    print("Wrote importance.png split_value.png metric.png tree.png")
except Exception as e:  # graphviz binary not installed
    print(f"plot_tree skipped ({type(e).__name__}: graphviz 'dot' needed)")
    print("Wrote importance.png split_value.png metric.png")
