"""Advanced API tour: weights, categorical features, model JSON dump,
continued training, per-tree learning-rate decay, custom objective/metric,
SHAP contributions and refit (counterpart of the reference python-guide
advanced example, exercising the same surface on this framework)."""
import json

import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
n = 1500
X = rng.normal(size=(n, 12)).astype(np.float32)
# an integer categorical column
X[:, 5] = rng.integers(0, 8, size=n)
logits = X[:, 0] + (X[:, 5] == 3) * 1.5 - 0.4 * X[:, 1]
y = (logits + 0.5 * rng.normal(size=n) > 0).astype(np.float32)
w = rng.uniform(0.5, 1.5, size=n).astype(np.float32)

X_train, y_train, w_train = X[:1200], y[:1200], w[:1200]
X_test, y_test = X[1200:], y[1200:]

train_data = lgb.Dataset(X_train, label=y_train, weight=w_train,
                         categorical_feature=[5])
valid_data = train_data.create_valid(X_test, label=y_test)

params = {"objective": "binary", "metric": "auc", "num_leaves": 31,
          "verbose": 0}

print("Training with categorical feature + weights...")
bst = lgb.train(params, train_data, num_boost_round=30,
                valid_sets=[valid_data])

print("Dumping model to JSON...")
model_json = bst.dump_model()
print(f"  tree_info has {len(model_json['tree_info'])} trees")

print("Continued training with learning-rate decay...")
bst = lgb.train(params, train_data, num_boost_round=30,
                init_model=bst, valid_sets=[valid_data],
                callbacks=[lgb.reset_parameter(
                    learning_rate=lambda it: 0.05 * (0.99 ** it))])

print("Custom objective (logistic) + custom metric...")


def loglikelihood(preds, train_dataset):
    labels = train_dataset.get_label()
    p = 1.0 / (1.0 + np.exp(-preds))
    return p - labels, p * (1.0 - p)


def binary_error(preds, eval_dataset):
    labels = eval_dataset.get_label()
    p = 1.0 / (1.0 + np.exp(-preds))
    return "error", float(np.mean(labels != (p > 0.5))), False


bst2 = lgb.train({"objective": loglikelihood, "num_leaves": 31,
                  "verbose": 0}, train_data, num_boost_round=20,
                 feval=binary_error, valid_sets=[valid_data])

print("SHAP-style feature contributions on 5 rows...")
contrib = bst.predict(X_test[:5], pred_contrib=True)
print(f"  contrib shape: {np.asarray(contrib).shape}")

print("Refitting the existing structure on new data...")
bst_refit = bst.refit(X_test, y_test)
print(f"  refit model has {bst_refit.num_trees()} trees")
print("Done.")
