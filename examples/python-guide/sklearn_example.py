"""scikit-learn estimator API: fit/predict, early stopping, grid search."""
import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(1200, 20)).astype(np.float32)
y = X[:, 0] * 2 + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=1200)
X_train, y_train = X[:1000], y[:1000]
X_test, y_test = X[1000:], y[1000:]

print("Starting training...")
gbm = lgb.LGBMRegressor(num_leaves=31, learning_rate=0.05,
                        n_estimators=200)
gbm.fit(X_train, y_train, eval_set=[(X_test, y_test)], eval_metric="l2",
        callbacks=[lgb.early_stopping(stopping_rounds=10)])

print("Starting predicting...")
y_pred = gbm.predict(X_test, num_iteration=gbm.best_iteration_)
rmse = float(np.sqrt(np.mean((y_pred - y_test) ** 2)))
print(f"The RMSE of prediction is: {rmse:.5f}")

print(f"Feature importances: {list(gbm.feature_importances_[:5])} ...")

try:
    from sklearn.model_selection import GridSearchCV
except ImportError:
    GridSearchCV = None
if GridSearchCV is not None:
    print("Grid searching...")
    estimator = lgb.LGBMRegressor(num_leaves=31)
    gbm = GridSearchCV(estimator,
                       {"learning_rate": [0.01, 0.1],
                        "n_estimators": [20, 40]})
    gbm.fit(X_train, y_train)
    print(f"Best parameters found by grid search are: {gbm.best_params_}")
