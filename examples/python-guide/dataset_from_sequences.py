"""Build a Dataset from sharded on-disk data via the Sequence API
(counterpart of the reference's dataset_from_multi_hdf5 example —
npz shards stand in for HDF5 since h5py isn't bundled here).

Each shard is opened lazily; binning samples rows by random access and
quantization streams batches, so the full matrix never sits in memory.
"""
import os
import tempfile

import numpy as np

import lightgbm_tpu as lgb


class NpzSequence(lgb.Sequence):
    """Random-access rows from one .npz shard (loaded mmap-style)."""

    def __init__(self, path, batch_size=4096):
        self.path = path
        self.batch_size = batch_size
        self._arr = None

    @property
    def arr(self):
        if self._arr is None:
            self._arr = np.load(self.path)["X"]
        return self._arr

    def __getitem__(self, idx):
        return self.arr[idx]

    def __len__(self):
        return len(self.arr)


def create_shards(tmpdir, n_shards=4, rows_per_shard=2500, f=12, seed=0):
    rng = np.random.default_rng(seed)
    paths, labels = [], []
    for i in range(n_shards):
        X = rng.normal(size=(rows_per_shard, f)).astype(np.float32)
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
        p = os.path.join(tmpdir, f"shard_{i}.npz")
        np.savez(p, X=X)
        paths.append(p)
        labels.append(y)
    return paths, np.concatenate(labels)


def main():
    with tempfile.TemporaryDirectory() as tmpdir:
        paths, y = create_shards(tmpdir)
        seqs = [NpzSequence(p) for p in paths]
        ds = lgb.Dataset(seqs, label=y)
        bst = lgb.train({"objective": "binary", "metric": "auc",
                         "verbose": -1}, ds, num_boost_round=30)
        X_all = np.concatenate([np.load(p)["X"] for p in paths])
        pred = bst.predict(X_all)
        acc = float(np.mean((pred > 0.5) == y))
        print(f"Trained from {len(paths)} shards "
              f"({ds.num_data()} rows); accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
