"""Wide-sparse training walkthrough.

Two storage strategies cover sparse data (docs/Features.md
"Wide-sparse data"):
- mutually-exclusive columns (one-hot blocks) bundle via EFB into few
  dense physical columns;
- high-conflict wide-sparse data packs into multi-value [R, K] storage
  (`tpu_sparse_storage`), scatter-accumulating only stored nonzeros.
`auto` probes a row sample and picks the cheaper layout.
"""
import numpy as np
import scipy.sparse as sp

import lightgbm_tpu as lgb

rng = np.random.default_rng(7)

# high-conflict wide-sparse: 1000 features, ~8% density
n, f = 5000, 1000
mask = rng.uniform(size=(n, f)) < 0.08
X = sp.csr_matrix(np.where(mask, rng.normal(size=(n, f)) + 1.0, 0.0))
y = (X[:, 0].toarray().ravel() - X[:, 1].toarray().ravel() > 0)

train = lgb.Dataset(X, label=y.astype(np.float64))
bst = lgb.train({"objective": "binary", "num_leaves": 31,
                 "verbose": 1}, train, num_boost_round=20)
# the engine reports which storage engaged; force it explicitly with
# {"tpu_sparse_storage": "multival"} or "dense"
print("multival storage:", bst._engine._multival)

# sparse predict never densifies the full matrix (CSR row blocks), and
# SHAP contributions come back sparse for sparse input
pred = bst.predict(X)
contrib = bst.predict(X, pred_contrib=True)
print("acc:", float(np.mean((pred > 0.5) == y)),
      "contrib type:", type(contrib).__name__)

# LibSVM files stream into the same storage without a dense pass:
#   lgb.Dataset("data.svm", params={"two_round": True,
#                                   "tpu_sparse_storage": "multival"})
