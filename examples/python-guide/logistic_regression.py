"""Binary objective vs cross-entropy objective on probability labels.

Shows the two ways to fit probabilistic targets (ref python-guide
logistic_regression example): `binary` on 0/1 labels and `cross_entropy`
(xentropy) on soft labels in [0, 1], which accept fractional targets.
"""
import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
n = 2000
X = rng.normal(size=(n, 15)).astype(np.float32)
p_true = 1.0 / (1.0 + np.exp(-(X[:, 0] - 0.5 * X[:, 1])))
y_hard = (rng.uniform(size=n) < p_true).astype(np.float32)
y_soft = p_true.astype(np.float32)

for name, label, objective in (("binary on 0/1", y_hard, "binary"),
                               ("xentropy on soft", y_soft, "cross_entropy")):
    train = lgb.Dataset(X[:1600], label=label[:1600])
    valid = train.create_valid(X[1600:], label=label[1600:])
    bst = lgb.train({"objective": objective, "verbose": -1}, train,
                    num_boost_round=50, valid_sets=[valid])
    pred = bst.predict(X[1600:])
    ll = -np.mean(y_soft[1600:] * np.log(np.clip(pred, 1e-9, 1)) +
                  (1 - y_soft[1600:]) * np.log(np.clip(1 - pred, 1e-9, 1)))
    print(f"{name:18s} ({objective}): logloss vs true p = {ll:.5f}")
