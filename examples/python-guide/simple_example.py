"""Minimal train/validate/save/predict loop with the native API
(counterpart of the reference's python-guide simple example)."""
import numpy as np

import lightgbm_tpu as lgb

rng = np.random.default_rng(0)
X = rng.normal(size=(1200, 20)).astype(np.float32)
y = X[:, 0] * 2 + np.sin(3 * X[:, 1]) + 0.1 * rng.normal(size=1200)
X_train, y_train = X[:1000], y[:1000]
X_test, y_test = X[1000:], y[1000:]

train_data = lgb.Dataset(X_train, label=y_train)
valid_data = train_data.create_valid(X_test, label=y_test)

params = {
    "objective": "regression",
    "metric": "l2",
    "num_leaves": 31,
    "learning_rate": 0.05,
    "verbose": 0,
}

print("Starting training...")
bst = lgb.train(params, train_data, num_boost_round=100,
                valid_sets=[valid_data],
                callbacks=[lgb.early_stopping(stopping_rounds=10)])

print("Saving model...")
bst.save_model("model.txt")

print("Starting predicting...")
y_pred = bst.predict(X_test, num_iteration=bst.best_iteration)
rmse = float(np.sqrt(np.mean((y_pred - y_test) ** 2)))
print(f"The RMSE of prediction is: {rmse:.5f}")
