"""Write the synthetic train/test files used by every example directory.

The reference ships real excerpts of its benchmark datasets; we generate
shape-compatible synthetic data instead (same file formats: label-first TSV
for regression/classification, plus `.query` files for the ranking tasks —
ref: docs/Parameters.rst data format notes, examples/lambdarank/README.md).
"""
import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))


def _write_tsv(path, y, X, fmt="%.6g"):
    arr = np.column_stack([y, X])
    np.savetxt(path, arr, delimiter="\t", fmt=fmt)
    print(f"wrote {path}  [{arr.shape[0]} rows x {X.shape[1]} features]")


def regression(rng, n_train=500, n_test=100, f=20):
    X = rng.normal(size=(n_train + n_test, f))
    y = (X[:, 0] * 2.0 + np.sin(X[:, 1] * 3.0) + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rng.normal(size=len(X)))
    d = os.path.join(HERE, "regression")
    _write_tsv(os.path.join(d, "regression.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "regression.test"), y[n_train:], X[n_train:])


def binary(rng, n_train=700, n_test=150, f=28):
    X = rng.normal(size=(n_train + n_test, f))
    logits = X[:, 0] - 0.6 * X[:, 1] * X[:, 2] + 0.3 * X[:, 3] ** 2
    y = (logits + 0.5 * rng.normal(size=len(X)) > 0).astype(int)
    d = os.path.join(HERE, "binary_classification")
    _write_tsv(os.path.join(d, "binary.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "binary.test"), y[n_train:], X[n_train:])
    # per-row training weights (ref: <data>.weight sidecar convention)
    w = rng.uniform(0.5, 1.5, size=n_train)
    np.savetxt(os.path.join(d, "binary.train.weight"), w, fmt="%.4f")


def multiclass(rng, n_train=800, n_test=200, f=20, k=5):
    centers = rng.normal(scale=2.0, size=(k, f))
    y = rng.integers(0, k, size=n_train + n_test)
    X = centers[y] + rng.normal(size=(n_train + n_test, f))
    d = os.path.join(HERE, "multiclass_classification")
    _write_tsv(os.path.join(d, "multiclass.train"), y[:n_train], X[:n_train])
    _write_tsv(os.path.join(d, "multiclass.test"), y[n_train:], X[n_train:])


def ranking(rng, dirname, n_queries=60, f=16):
    rows, labels, qsizes = [], [], []
    for _ in range(n_queries):
        m = int(rng.integers(5, 25))
        Xq = rng.normal(size=(m, f))
        rel = Xq[:, 0] + 0.5 * Xq[:, 1] + 0.3 * rng.normal(size=m)
        # graded relevance 0..4 by within-query quantile
        grades = np.searchsorted(np.quantile(rel, [0.5, 0.75, 0.9, 0.97]),
                                 rel)
        rows.append(Xq)
        labels.append(grades)
        qsizes.append(m)
    X = np.concatenate(rows)
    y = np.concatenate(labels)
    d = os.path.join(HERE, dirname)
    n_train_q = int(0.8 * n_queries)
    split = int(np.sum(qsizes[:n_train_q]))
    _write_tsv(os.path.join(d, "rank.train"), y[:split], X[:split], fmt="%.5g")
    _write_tsv(os.path.join(d, "rank.test"), y[split:], X[split:], fmt="%.5g")
    np.savetxt(os.path.join(d, "rank.train.query"), qsizes[:n_train_q],
               fmt="%d")
    np.savetxt(os.path.join(d, "rank.test.query"), qsizes[n_train_q:],
               fmt="%d")


def parallel(rng, n_train=2000, f=24):
    X = rng.normal(size=(n_train, f))
    logits = X[:, 0] + 0.4 * X[:, 1] * X[:, 2]
    y = (logits > 0).astype(int)
    d = os.path.join(HERE, "parallel_learning")
    _write_tsv(os.path.join(d, "parallel.train"), y, X)


def main():
    rng = np.random.default_rng(7)
    regression(rng)
    binary(rng)
    multiclass(rng)
    ranking(rng, "lambdarank")
    ranking(rng, "xendcg", n_queries=50)
    parallel(rng)


if __name__ == "__main__":
    main()
