/* Minimal C consumer of the native ABI (lgbm_c_api.h).
 *
 * Build (the shared library self-builds on first python import):
 *   python -c "from lightgbm_tpu.native import get_lib; get_lib()"
 *   gcc -O2 -I ../../lightgbm_tpu/native train_and_predict.c \
 *       ../../lightgbm_tpu/native/_build/lgbm_native.so -lm -o demo
 *   LIGHTGBM_TPU_PLATFORM=cpu ./demo      # cpu pin for laptops
 */
#include <stdio.h>
#include <stdlib.h>

#include "lgbm_c_api.h"

int main(void) {
  const int n = 500, f = 4;
  double* X = malloc(sizeof(double) * n * f);
  float* y = malloc(sizeof(float) * n);
  unsigned s = 7;
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < f; ++j) {
      s = s * 1664525u + 1013904223u;
      X[i * f + j] = (double)(s >> 8) / (1u << 24) - 0.5;
    }
    y[i] = (float)(2.0 * X[i * f] - X[i * f + 1]);
  }

  DatasetHandle ds;
  BoosterHandle bst;
  if (LGBM_DatasetCreateFromMat(X, C_API_DTYPE_FLOAT64, n, f, 1, "",
                                NULL, &ds) ||
      LGBM_DatasetSetField(ds, "label", y, n, C_API_DTYPE_FLOAT32) ||
      LGBM_BoosterCreate(ds, "objective=regression num_leaves=15 "
                             "min_data_in_leaf=5 verbosity=-1", &bst)) {
    fprintf(stderr, "setup failed: %s\n", LGBM_GetLastError());
    return 1;
  }
  int finished = 0;
  for (int it = 0; it < 20 && !finished; ++it)
    LGBM_BoosterUpdateOneIter(bst, &finished);

  double pred[4];
  int64_t len = 0;
  LGBM_BoosterPredictForMat(bst, X, C_API_DTYPE_FLOAT64, 1, f, 1,
                            C_API_PREDICT_NORMAL, 0, 0, "", &len, pred);
  printf("prediction for row 0: %g (label %g)\n", pred[0], y[0]);

  LGBM_BoosterSaveModel(bst, 0, -1, 0, "model.txt");
  LGBM_BoosterFree(bst);
  LGBM_DatasetFree(ds);
  printf("model saved to model.txt (servable with zero Python via "
         "LGBM_BoosterCreateFromModelfile)\n");
  return 0;
}
