/* Wave-2 surface walkthrough: SynapseML-style streaming ingestion and
 * the reusable single-row Fast predict path (thread-safe).
 *
 * Build (the shared library self-builds on first python import):
 *   python -c "from lightgbm_tpu.native import get_lib; get_lib()"
 *   gcc -O2 -I ../../lightgbm_tpu/native streaming_and_fast_predict.c \
 *       ../../lightgbm_tpu/native/_build/lgbm_native.so -lm -o demo2
 *   LIGHTGBM_TPU_PLATFORM=cpu ./demo2
 *
 * Flow (ref: c_api.h:231-234 streaming recipe):
 *   1. LGBM_DatasetCreateFromSampledColumn  — declare the schema
 *   2. LGBM_DatasetInitStreaming            — allocate metadata
 *   3. LGBM_DatasetPushRowsWithMetadata     — push chunks
 *   4. LGBM_DatasetMarkFinished             — seal
 *   5. train, save, reload through the interpreter-free serving path
 *   6. LGBM_BoosterPredictForMatSingleRowFastInit / ...Fast — score
 */
#include <stdio.h>
#include <stdlib.h>

#include "lgbm_c_api.h"

#define CK(call)                                                       \
  if ((call) != 0) {                                                   \
    fprintf(stderr, "error: %s\n", LGBM_GetLastError());               \
    return 1;                                                          \
  }

int main(void) {
  const int n = 600, f = 4, chunk = 200;
  double* X = malloc(sizeof(double) * n * f);
  float* y = malloc(sizeof(float) * n);
  unsigned s = 7;
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < f; ++j) {
      s = s * 1664525u + 1013904223u;
      X[i * f + j] = (double)(s >> 8) / (1u << 24) - 0.5;
    }
    y[i] = (float)(2.0 * X[i * f] - X[i * f + 1]);
  }

  /* 1-4: streaming creation in chunks */
  DatasetHandle ds;
  CK(LGBM_DatasetCreateFromSampledColumn(
      NULL, NULL, f, NULL, 0, n, n,
      "min_data_in_leaf=5 verbosity=-1 device_type=cpu", &ds));
  CK(LGBM_DatasetInitStreaming(ds, 0, 0, 0, 1, 1, -1));
  CK(LGBM_DatasetSetWaitForManualFinish(ds, 1));
  for (int start = 0; start < n; start += chunk)
    CK(LGBM_DatasetPushRowsWithMetadata(
        ds, X + (long)start * f, C_API_DTYPE_FLOAT64, chunk, f, start,
        y + start, NULL, NULL, NULL, 0));
  CK(LGBM_DatasetMarkFinished(ds));

  /* 5: train + save + reload (serving handle, no interpreter) */
  BoosterHandle bst;
  CK(LGBM_BoosterCreate(
      ds, "objective=regression num_leaves=15 min_data_in_leaf=5 "
          "verbosity=-1 device_type=cpu", &bst));
  for (int it = 0, fin = 0; it < 20; ++it)
    CK(LGBM_BoosterUpdateOneIter(bst, &fin));
  CK(LGBM_BoosterSaveModel(bst, 0, -1, 0, "stream_model.txt"));
  BoosterHandle srv;
  int n_iter = 0;
  CK(LGBM_BoosterCreateFromModelfile("stream_model.txt", &n_iter, &srv));

  /* 6: frozen single-row fast config; per-call work is just the walk */
  FastConfigHandle fc;
  CK(LGBM_BoosterPredictForMatSingleRowFastInit(
      srv, C_API_PREDICT_NORMAL, 0, -1, C_API_DTYPE_FLOAT64, f, "",
      &fc));
  double mse = 0.0;
  for (int i = 0; i < n; ++i) {
    int64_t len;
    double pred;
    CK(LGBM_BoosterPredictForMatSingleRowFast(fc, X + (long)i * f,
                                              &len, &pred));
    mse += (pred - y[i]) * (pred - y[i]);
  }
  printf("streamed %d rows in %d chunks; single-row fast MSE = %.5f\n",
         n, n / chunk, mse / n);
  CK(LGBM_FastConfigFree(fc));
  CK(LGBM_BoosterFree(srv));
  CK(LGBM_BoosterFree(bst));
  CK(LGBM_DatasetFree(ds));
  free(X);
  free(y);
  return 0;
}
