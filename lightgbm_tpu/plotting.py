"""Plotting library.

TPU-native equivalent of python-package/lightgbm/plotting.py (849 LoC):
plot_importance, plot_split_value_histogram, plot_metric, plot_tree,
create_tree_digraph. matplotlib / graphviz are optional imports, checked
at call time like the reference.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError
from .sklearn import LGBMModel

__all__ = ["plot_importance", "plot_split_value_histogram", "plot_metric",
           "plot_tree", "create_tree_digraph"]


def _check_not_tuple_of_2_elements(obj: Any, obj_name: str) -> None:
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def _float2str(value: float, precision: Optional[int] = None) -> str:
    return (f"{value:.{precision}f}" if precision is not None
            and not isinstance(value, str) else str(value))


def _get_booster(booster: Union[Booster, LGBMModel]) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def plot_importance(booster: Union[Booster, LGBMModel], ax=None,
                    height: float = 0.2, xlim=None, ylim=None,
                    title: Optional[str] = "Feature importance",
                    xlabel: Optional[str] = "Feature importance",
                    ylabel: Optional[str] = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: Optional[int] = 3,
                    **kwargs):
    """Bar chart of feature importances (ref: plotting.py plot_importance)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot importance.")

    if importance_type == "auto":
        importance_type = (booster.importance_type
                           if isinstance(booster, LGBMModel) else "split")
    bst = _get_booster(booster)
    importance = bst.feature_importance(importance_type=importance_type)
    feature_name = bst.feature_name()

    if not len(importance):
        raise ValueError("Booster's feature_importance is empty.")

    tuples = sorted(zip(feature_name, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    labels, values = zip(*tuples)

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                _float2str(x, precision) if importance_type == "gain"
                else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, max(values) * 1.1)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (-1, len(values))
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        xlabel = xlabel.replace("@importance_type@", importance_type)
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster: Union[Booster, LGBMModel],
                               feature: Union[int, str], bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title: Optional[str] =
                               "Split value histogram for feature with "
                               "@index/name@ @feature@",
                               xlabel: Optional[str] = "Feature split value",
                               ylabel: Optional[str] = "Count",
                               figsize=None, dpi=None, grid: bool = True,
                               **kwargs):
    """Histogram of a feature's split thresholds across the model
    (ref: plotting.py plot_split_value_histogram)."""
    try:
        import matplotlib.pyplot as plt
        from matplotlib.ticker import MaxNLocator
    except ImportError:
        raise ImportError(
            "You must install matplotlib to plot split value histogram.")

    bst = _get_booster(booster)
    model = bst.dump_model()
    feature_names = model.get("feature_names", bst.feature_name())
    if isinstance(feature, str):
        if feature not in feature_names:
            raise ValueError(f"feature {feature} not found")
        fidx = feature_names.index(feature)
    else:
        fidx = int(feature)

    values: List[float] = []

    def _walk(node):
        if "split_feature" in node:
            if int(node["split_feature"]) == fidx and \
                    node.get("decision_type") == "<=":
                values.append(float(node["threshold"]))
            _walk(node["left_child"])
            _walk(node["right_child"])

    for tree in model["tree_info"]:
        _walk(tree["tree_structure"])
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")

    hist_counts, bin_edges = np.histogram(values, bins=bins or "auto")
    centred = (bin_edges[:-1] + bin_edges[1:]) / 2.0

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    width = width_coef * (bin_edges[1] - bin_edges[0])
    ax.bar(centred, hist_counts, width=width, align="center", **kwargs)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        range_result = bin_edges[-1] - bin_edges[0]
        xlim = (bin_edges[0] - range_result * 0.2,
                bin_edges[-1] + range_result * 0.2)
    ax.set_xlim(xlim)
    ax.yaxis.set_major_locator(MaxNLocator(integer=True))
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        ylim = (0, max(hist_counts) * 1.1)
    ax.set_ylim(ylim)
    if title is not None:
        title = title.replace("@feature@", str(feature))
        title = title.replace("@index/name@",
                              "name" if isinstance(feature, str) else "index")
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster: Union[Dict, LGBMModel], metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "@metric@", figsize=None, dpi=None,
                grid: bool = True):
    """Plot a recorded eval metric over iterations
    (ref: plotting.py plot_metric)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot metric.")

    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif isinstance(booster, Booster):
        raise TypeError("booster must be dict or LGBMModel. To use plot_"
                        "metric with Booster type, first record the metrics "
                        "using record_evaluation callback then pass that to "
                        "plot_metric as argument `booster`")
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    if dataset_names is None:
        dataset_names_iter = iter(eval_results.keys())
    elif not dataset_names:
        raise ValueError("dataset_names cannot be empty.")
    else:
        dataset_names_iter = iter(dataset_names)

    name = next(dataset_names_iter)  # take one as sample
    metrics_for_one = eval_results[name]
    num_metric = len(metrics_for_one)
    if metric is None:
        if num_metric > 1:
            raise ValueError(
                "more than one metric available, pick one with metric=...")
        metric, results = metrics_for_one.popitem()
    else:
        if metric not in metrics_for_one:
            raise KeyError("No given metric in eval results.")
        results = metrics_for_one[metric]
    num_iteration = len(results)
    max_result = max(results)
    min_result = min(results)
    x_ = range(num_iteration)
    ax.plot(x_, results, label=name)

    for name in dataset_names_iter:
        metrics_for_one = eval_results[name]
        results = metrics_for_one[metric]
        max_result = max(*results, max_result)
        min_result = min(*results, min_result)
        ax.plot(x_, results, label=name)

    ax.legend(loc="best")
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
    else:
        xlim = (0, num_iteration)
    ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
    else:
        range_result = max_result - min_result
        ylim = (min_result - range_result * 0.2,
                max_result + range_result * 0.2)
    ax.set_ylim(ylim)
    if title is not None:
        ax.set_title(title)
    if xlabel is not None:
        ax.set_xlabel(xlabel)
    if ylabel is not None:
        ylabel = ylabel.replace("@metric@", metric)
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def _to_graphviz(tree_info: Dict[str, Any], show_info: List[str],
                 feature_names: List[str], precision: Optional[int],
                 orientation: str, constraints=None, example_case=None,
                 max_category_values: int = 10, **kwargs):
    """Build a graphviz Digraph for one tree (ref: plotting.py _to_graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")

    def add(root, total_count, parent=None, decision=None):
        if "split_index" in root:  # non-leaf
            name = f"split{root['split_index']}"
            fidx = int(root["split_feature"])
            l_dec, r_dec = "<=", ">"
            if feature_names is not None and fidx < len(feature_names):
                feat = feature_names[fidx]
            else:
                feat = f"feature_{fidx}"
            if root.get("decision_type") == "==":
                l_dec, r_dec = "is", "isn't"
                threshold = str(root["threshold"])
                cats = threshold.split("||")
                if len(cats) > max_category_values:
                    cats = cats[:max_category_values] + ["..."]
                threshold = "||".join(cats)
            else:
                threshold = _float2str(root["threshold"], precision)
            label = f"{feat} {l_dec} {threshold}"
            for info in ["split_gain", "internal_value", "internal_weight",
                         "internal_count"]:
                if info in show_info and info in root:
                    output = info.split("_")[-1]
                    label += f"\n{output}: " + _float2str(root[info],
                                                          precision)
            graph.node(name, label=label, shape="rectangle")
            add(root["left_child"], total_count, name, l_dec)
            add(root["right_child"], total_count, name, r_dec)
        else:  # leaf
            name = f"leaf{root['leaf_index']}"
            label = f"leaf {root['leaf_index']}: "
            label += _float2str(root["leaf_value"], precision)
            if "leaf_weight" in show_info and "leaf_weight" in root:
                label += "\nweight: " + _float2str(root["leaf_weight"],
                                                   precision)
            if "leaf_count" in show_info and "leaf_count" in root:
                label += f"\ncount: {root['leaf_count']}"
                if "data_percentage" in show_info and total_count:
                    pct = root["leaf_count"] / total_count * 100
                    label += f"\n{pct:.2f}% of data"
            graph.node(name, label=label)
        if parent is not None:
            graph.edge(parent, name, decision)

    graph = Digraph(**kwargs)
    rankdir = "LR" if orientation == "horizontal" else "TB"
    graph.attr("graph", nodesep="0.05", ranksep="0.3", rankdir=rankdir)
    struct = tree_info["tree_structure"]
    total_count = struct.get("internal_count", 0)
    add(struct, total_count)
    return graph


def create_tree_digraph(booster: Union[Booster, LGBMModel],
                        tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: Optional[int] = 3,
                        orientation: str = "horizontal",
                        example_case=None, max_category_values: int = 10,
                        **kwargs):
    """Graphviz digraph of one tree (ref: plotting.py create_tree_digraph)."""
    bst = _get_booster(booster)
    model = bst.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", bst.feature_name())
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    if show_info is None:
        show_info = []
    return _to_graphviz(tree_infos[tree_index], show_info, feature_names,
                        precision, orientation,
                        max_category_values=max_category_values, **kwargs)


def plot_tree(booster: Union[Booster, LGBMModel], ax=None,
              tree_index: int = 0, figsize=None, dpi=None,
              show_info: Optional[List[str]] = None,
              precision: Optional[int] = 3,
              orientation: str = "horizontal", example_case=None, **kwargs):
    """Render one tree to a matplotlib axis (ref: plotting.py plot_tree)."""
    try:
        import matplotlib.image as image
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib to plot tree.")
    if ax is None:
        if figsize is not None:
            _check_not_tuple_of_2_elements(figsize, "figsize")
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)

    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation,
                                example_case=example_case, **kwargs)
    from io import BytesIO
    s = BytesIO()
    s.write(graph.pipe(format="png"))
    s.seek(0)
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
