"""Plotting library.

Behavioral equivalent of the reference plotting module
(ref: python-package/lightgbm/plotting.py — plot_importance,
plot_split_value_histogram, plot_metric, plot_tree, create_tree_digraph),
restructured around a shared axes pipeline: every chart goes through
``_new_axes`` -> draw -> ``_finish_axes`` with declarative default limits,
instead of repeating the limit/label boilerplate per function.
matplotlib / graphviz are optional imports, checked at call time.
"""
from __future__ import annotations

from copy import deepcopy
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from .basic import Booster, LightGBMError
from .sklearn import LGBMModel

__all__ = ["plot_importance", "plot_split_value_histogram", "plot_metric",
           "plot_tree", "create_tree_digraph"]


def _pyplot(what: str):
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError:
        raise ImportError(f"You must install matplotlib to plot {what}.")


def _fmt(value, precision: Optional[int]) -> str:
    """Number -> string honoring an optional decimal precision."""
    if precision is None or isinstance(value, str):
        return str(value)
    return f"{value:.{precision}f}"


def _pair(value, name: str) -> Tuple:
    """Validate a 2-tuple argument (xlim/ylim/figsize)."""
    if not isinstance(value, tuple) or len(value) != 2:
        raise TypeError(f"{name} must be a tuple of 2 elements.")
    return value


def _get_booster(booster: Union[Booster, LGBMModel]) -> Booster:
    if isinstance(booster, LGBMModel):
        return booster.booster_
    if isinstance(booster, Booster):
        return booster
    raise TypeError("booster must be Booster or LGBMModel.")


def _new_axes(ax, figsize, dpi):
    if ax is not None:
        return ax
    plt = _pyplot("charts")
    if figsize is not None:
        _pair(figsize, "figsize")
    _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    return ax


def _finish_axes(ax, *, xlim, ylim, default_xlim, default_ylim,
                 title, xlabel, ylabel, grid,
                 subs: Optional[Dict[str, str]] = None) -> None:
    """Apply limits / labels / grid with templated substitutions."""
    ax.set_xlim(_pair(xlim, "xlim") if xlim is not None else default_xlim)
    ax.set_ylim(_pair(ylim, "ylim") if ylim is not None else default_ylim)

    def expand(text):
        for key, val in (subs or {}).items():
            text = text.replace(key, val)
        return text

    if title is not None:
        ax.set_title(expand(title))
    if xlabel is not None:
        ax.set_xlabel(expand(xlabel))
    if ylabel is not None:
        ax.set_ylabel(expand(ylabel))
    ax.grid(grid)


def plot_importance(booster: Union[Booster, LGBMModel], ax=None,
                    height: float = 0.2, xlim=None, ylim=None,
                    title: Optional[str] = "Feature importance",
                    xlabel: Optional[str] = "Feature importance",
                    ylabel: Optional[str] = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: Optional[int] = 3,
                    **kwargs):
    """Horizontal bar chart of feature importances."""
    _pyplot("importance")
    if importance_type == "auto":
        importance_type = (booster.importance_type
                           if isinstance(booster, LGBMModel) else "split")
    bst = _get_booster(booster)
    imp = np.asarray(bst.feature_importance(
        importance_type=importance_type), dtype=np.float64)
    names = np.asarray(bst.feature_name(), dtype=object)
    if imp.size == 0:
        raise ValueError("Booster's feature_importance is empty.")

    order = np.argsort(imp, kind="stable")
    if ignore_zero:
        order = order[imp[order] > 0]
    if max_num_features is not None and max_num_features > 0:
        order = order[-max_num_features:]
    vals = imp[order]

    ax = _new_axes(ax, figsize, dpi)
    rows = np.arange(vals.size)
    ax.barh(rows, vals, align="center", height=height, **kwargs)
    is_gain = importance_type == "gain"
    for r, v in enumerate(vals):
        ax.text(v + 1, r, _fmt(v, precision) if is_gain else str(int(v)),
                va="center")
    ax.set_yticks(rows)
    ax.set_yticklabels(names[order])
    _finish_axes(ax, xlim=xlim, ylim=ylim,
                 default_xlim=(0, float(vals.max()) * 1.1),
                 default_ylim=(-1, vals.size),
                 title=title, xlabel=xlabel, ylabel=ylabel, grid=grid,
                 subs={"@importance_type@": importance_type})
    return ax


def _split_thresholds(model: Dict[str, Any], fidx: int) -> List[float]:
    """All numerical split thresholds on one feature across the model."""
    out: List[float] = []
    stack = [t["tree_structure"] for t in model["tree_info"]]
    while stack:
        node = stack.pop()
        if "split_feature" not in node:
            continue
        if (int(node["split_feature"]) == fidx and
                node.get("decision_type") == "<="):
            out.append(float(node["threshold"]))
        stack.append(node["left_child"])
        stack.append(node["right_child"])
    return out


def plot_split_value_histogram(booster: Union[Booster, LGBMModel],
                               feature: Union[int, str], bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None, ylim=None,
                               title: Optional[str] =
                               "Split value histogram for feature with "
                               "@index/name@ @feature@",
                               xlabel: Optional[str] = "Feature split value",
                               ylabel: Optional[str] = "Count",
                               figsize=None, dpi=None, grid: bool = True,
                               **kwargs):
    """Histogram of a feature's split thresholds across the model."""
    _pyplot("split value histogram")
    from matplotlib.ticker import MaxNLocator

    bst = _get_booster(booster)
    model = bst.dump_model()
    feature_names = model.get("feature_names", bst.feature_name())
    if isinstance(feature, str):
        if feature not in feature_names:
            raise ValueError(f"feature {feature} not found")
        fidx = feature_names.index(feature)
    else:
        fidx = int(feature)

    values = _split_thresholds(model, fidx)
    if not values:
        raise ValueError(
            "Cannot plot split value histogram, "
            f"because feature {feature} was not used in splitting")

    counts, edges = np.histogram(values, bins=bins or "auto")
    ax = _new_axes(ax, figsize, dpi)
    ax.bar((edges[:-1] + edges[1:]) / 2.0, counts,
           width=width_coef * (edges[1] - edges[0]), align="center",
           **kwargs)
    ax.yaxis.set_major_locator(MaxNLocator(integer=True))
    span = edges[-1] - edges[0]
    _finish_axes(ax, xlim=xlim, ylim=ylim,
                 default_xlim=(edges[0] - span * 0.2,
                               edges[-1] + span * 0.2),
                 default_ylim=(0, float(counts.max()) * 1.1),
                 title=title, xlabel=xlabel, ylabel=ylabel, grid=grid,
                 subs={"@feature@": str(feature),
                       "@index/name@": ("name" if isinstance(feature, str)
                                        else "index")})
    return ax


def plot_metric(booster: Union[Dict, LGBMModel], metric: Optional[str] = None,
                dataset_names: Optional[List[str]] = None, ax=None,
                xlim=None, ylim=None,
                title: Optional[str] = "Metric during training",
                xlabel: Optional[str] = "Iterations",
                ylabel: Optional[str] = "@metric@", figsize=None, dpi=None,
                grid: bool = True):
    """Curves of a recorded eval metric over boosting iterations."""
    _pyplot("metric")
    if isinstance(booster, LGBMModel):
        eval_results = deepcopy(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = deepcopy(booster)
    elif isinstance(booster, Booster):
        raise TypeError("booster must be dict or LGBMModel. To use plot_"
                        "metric with Booster type, first record the metrics "
                        "using record_evaluation callback then pass that to "
                        "plot_metric as argument `booster`")
    else:
        raise TypeError("booster must be dict or LGBMModel.")
    if not eval_results:
        raise ValueError("eval results cannot be empty.")

    names = (list(eval_results.keys()) if dataset_names is None
             else list(dataset_names))
    if not names:
        raise ValueError("dataset_names cannot be empty.")
    first = eval_results[names[0]]
    if metric is None:
        if len(first) > 1:
            raise ValueError(
                "more than one metric available, pick one with metric=...")
        metric = next(iter(first))
    elif metric not in first:
        raise KeyError("No given metric in eval results.")

    curves = [(name, eval_results[name][metric]) for name in names]
    ax = _new_axes(ax, figsize, dpi)
    for name, series in curves:
        ax.plot(range(len(series)), series, label=name)
    ax.legend(loc="best")

    flat = [v for _, series in curves for v in series]
    lo, hi = min(flat), max(flat)
    span = hi - lo
    _finish_axes(ax, xlim=xlim, ylim=ylim,
                 default_xlim=(0, len(curves[0][1])),
                 default_ylim=(lo - span * 0.2, hi + span * 0.2),
                 title=title, xlabel=xlabel, ylabel=ylabel, grid=grid,
                 subs={"@metric@": metric})
    return ax


def _node_label(node: Dict[str, Any], feature_names, precision,
                show_info: List[str], max_category_values: int,
                total_count) -> Tuple[str, str, Optional[Tuple[str, str]]]:
    """(node_name, label, (left_edge, right_edge)|None) for one dump node."""
    if "split_index" in node:
        fidx = int(node["split_feature"])
        feat = (feature_names[fidx]
                if feature_names is not None and fidx < len(feature_names)
                else f"feature_{fidx}")
        if node.get("decision_type") == "==":
            edges = ("is", "isn't")
            cats = str(node["threshold"]).split("||")
            if len(cats) > max_category_values:
                cats = cats[:max_category_values] + ["..."]
            thr = "||".join(cats)
        else:
            edges = ("<=", ">")
            thr = _fmt(node["threshold"], precision)
        label = f"{feat} {edges[0]} {thr}"
        for info in ("split_gain", "internal_value", "internal_weight",
                     "internal_count"):
            if info in show_info and info in node:
                label += f"\n{info.split('_')[-1]}: " + \
                    _fmt(node[info], precision)
        return f"split{node['split_index']}", label, edges
    label = (f"leaf {node['leaf_index']}: " +
             _fmt(node["leaf_value"], precision))
    if "leaf_weight" in show_info and "leaf_weight" in node:
        label += "\nweight: " + _fmt(node["leaf_weight"], precision)
    if "leaf_count" in show_info and "leaf_count" in node:
        label += f"\ncount: {node['leaf_count']}"
        if "data_percentage" in show_info and total_count:
            label += (f"\n{node['leaf_count'] / total_count * 100:.2f}"
                      "% of data")
    return f"leaf{node['leaf_index']}", label, None


def _to_graphviz(tree_info: Dict[str, Any], show_info: List[str],
                 feature_names: List[str], precision: Optional[int],
                 orientation: str, constraints=None, example_case=None,
                 max_category_values: int = 10, **kwargs):
    """Build a graphviz Digraph for one tree."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz to plot tree.")

    graph = Digraph(**kwargs)
    graph.attr("graph", nodesep="0.05", ranksep="0.3",
               rankdir="LR" if orientation == "horizontal" else "TB")
    struct = tree_info["tree_structure"]
    total_count = struct.get("internal_count", 0)

    stack = [(struct, None, None)]
    while stack:
        node, parent, decision = stack.pop()
        name, label, edges = _node_label(node, feature_names, precision,
                                         show_info, max_category_values,
                                         total_count)
        shape = "rectangle" if edges is not None else None
        graph.node(name, label=label,
                   **({"shape": shape} if shape else {}))
        if parent is not None:
            graph.edge(parent, name, decision)
        if edges is not None:
            stack.append((node["right_child"], name, edges[1]))
            stack.append((node["left_child"], name, edges[0]))
    return graph


def create_tree_digraph(booster: Union[Booster, LGBMModel],
                        tree_index: int = 0,
                        show_info: Optional[List[str]] = None,
                        precision: Optional[int] = 3,
                        orientation: str = "horizontal",
                        example_case=None, max_category_values: int = 10,
                        **kwargs):
    """Graphviz digraph of one tree from the JSON dump."""
    bst = _get_booster(booster)
    model = bst.dump_model()
    tree_infos = model["tree_info"]
    feature_names = model.get("feature_names", bst.feature_name())
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    return _to_graphviz(tree_infos[tree_index], show_info or [],
                        feature_names, precision, orientation,
                        max_category_values=max_category_values, **kwargs)


def plot_tree(booster: Union[Booster, LGBMModel], ax=None,
              tree_index: int = 0, figsize=None, dpi=None,
              show_info: Optional[List[str]] = None,
              precision: Optional[int] = 3,
              orientation: str = "horizontal", example_case=None, **kwargs):
    """Render one tree to a matplotlib axis via graphviz."""
    plt = _pyplot("tree")
    import matplotlib.image as image
    ax = _new_axes(ax, figsize, dpi)
    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, precision=precision,
                                orientation=orientation,
                                example_case=example_case, **kwargs)
    from io import BytesIO
    buf = BytesIO(graph.pipe(format="png"))
    ax.imshow(image.imread(buf))
    ax.axis("off")
    return ax
