"""scikit-learn estimator API.

TPU-native equivalent of python-package/lightgbm/sklearn.py (1954 LoC):
LGBMModel (ref: sklearn.py:535), LGBMRegressor (:1409), LGBMClassifier
(:1524), LGBMRanker (:1832). Estimators wrap the functional `train()`
engine; sklearn-style constructor args are translated to the Config
parameter names the same way the reference's `_process_params` does.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

try:
    from sklearn.base import (BaseEstimator as _SKBase,
                              ClassifierMixin as _SKClassifier,
                              RegressorMixin as _SKRegressor)
    from sklearn.preprocessing import LabelEncoder as _SKLabelEncoder
    _SKLEARN_INSTALLED = True
except ImportError:  # pragma: no cover - sklearn is present in CI
    _SKLEARN_INSTALLED = False

    class _SKBase:  # minimal stand-ins (ref: sklearn.py compat block)
        pass

    class _SKClassifier:
        pass

    class _SKRegressor:
        pass

    class _SKLabelEncoder:
        def fit(self, y):
            self.classes_ = np.unique(np.asarray(y))
            return self

        def transform(self, y):
            return np.searchsorted(self.classes_, np.asarray(y))

from .basic import Booster, Dataset, LightGBMError
from .callback import record_evaluation
from .config import _ConfigAliases
from .engine import train

__all__ = ["LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"]

# sklearn-style ctor arg -> native parameter name (ref: sklearn.py fit():
# "min_split_gain" -> "min_gain_to_split" etc. via the alias machinery)
_SK_TO_NATIVE = {
    "min_split_gain": "min_gain_to_split",
    "min_child_weight": "min_sum_hessian_in_leaf",
    "min_child_samples": "min_data_in_leaf",
    "subsample": "bagging_fraction",
    "subsample_freq": "bagging_freq",
    "colsample_bytree": "feature_fraction",
    "reg_alpha": "lambda_l1",
    "reg_lambda": "lambda_l2",
    "random_state": "seed",
    "boosting_type": "boosting",
    "subsample_for_bin": "bin_construct_sample_cnt",
}


class _ObjectiveFunctionWrapper:
    """Adapt sklearn-style fobj(y_true, y_pred[, weight|group]) to the
    engine's fobj(raw_score, dataset) (ref: sklearn.py:72)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds, dataset.get_weight())
        elif argc == 4:
            grad, hess = self.func(labels, preds, dataset.get_weight(),
                                   dataset.get_group())
        else:
            raise TypeError(
                f"Self-defined objective should have 2-4 arguments, "
                f"got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapt sklearn-style feval(y_true, y_pred[, weight|group]) to the
    engine's feval(raw_score, dataset) (ref: sklearn.py:155)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.get_label()
        argc = self.func.__code__.co_argcount
        if argc == 2:
            return self.func(labels, preds)
        if argc == 3:
            return self.func(labels, preds, dataset.get_weight())
        if argc == 4:
            return self.func(labels, preds, dataset.get_weight(),
                             dataset.get_group())
        raise TypeError(
            f"Self-defined eval function should have 2-4 arguments, "
            f"got {argc}")


class LGBMModel(_SKBase):
    """Base sklearn estimator (ref: sklearn.py:535 LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[Union[str, Callable]] = None,
                 class_weight: Optional[Union[Dict, str]] = None,
                 min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 random_state: Optional[int] = None,
                 n_jobs: Optional[int] = None,
                 importance_type: str = "split", **kwargs: Any):
        if not _SKLEARN_INSTALLED:
            raise LightGBMError(
                "scikit-learn is required for the sklearn estimator API")
        self.boosting_type = boosting_type
        self.objective = objective
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self.class_weight = class_weight
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_score: Dict = {}
        self._best_iteration: int = -1
        self._other_params: Dict[str, Any] = {}
        self._objective = objective
        self._fobj = None
        self._n_features: int = -1
        self._n_features_in: int = -1
        self._n_classes: int = -1
        self.set_params(**kwargs)

    # -- sklearn plumbing ------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = super().get_params(deep=deep) if _SKLEARN_INSTALLED else {}
        params.update(self._other_params)
        return params

    def set_params(self, **params: Any) -> "LGBMModel":
        for key, value in params.items():
            setattr(self, key, value)
            if hasattr(self, f"_{key}"):
                setattr(self, f"_{key}", value)
            self._other_params[key] = value
        return self

    def _more_tags(self):
        return {"allow_nan": True, "X_types": ["2darray", "sparse", "1dlabels"],
                "non_deterministic": False}

    def __sklearn_tags__(self):  # sklearn >= 1.6 tag protocol
        tags = super().__sklearn_tags__()
        tags.input_tags.allow_nan = True
        tags.input_tags.sparse = True
        return tags

    # -- param translation (ref: sklearn.py _process_params) -------------
    def _process_params(self, stage: str) -> Dict[str, Any]:
        params = self.get_params()
        params.pop("objective", None)
        for sk_name in ("n_estimators", "class_weight", "importance_type",
                        "silent"):
            params.pop(sk_name, None)
        n_jobs = params.pop("n_jobs", None)
        if n_jobs is not None:
            params["num_threads"] = n_jobs
        for sk_name, native in _SK_TO_NATIVE.items():
            if sk_name in params:
                params[native] = params.pop(sk_name)
        if callable(self._objective):
            self._fobj = _ObjectiveFunctionWrapper(self._objective)
            params["objective"] = self._fobj  # train() detects the callable
        else:
            self._fobj = None
            if self._objective is not None:
                params["objective"] = self._objective
        if self._n_classes > 2 and not callable(self._objective):
            for alias in _ConfigAliases.get("num_class"):
                params.pop(alias, None)
            params["num_class"] = self._n_classes
        return {k: v for k, v in params.items() if v is not None}

    # -- fit --------------------------------------------------------------
    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_class_weight=None, eval_init_score=None, eval_group=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None,
            init_model=None) -> "LGBMModel":
        """ref: sklearn.py LGBMModel.fit (:895)."""
        params = self._process_params(stage="fit")
        if callable(eval_metric):
            feval = _EvalFunctionWrapper(eval_metric)
            eval_metric_name = None
        elif isinstance(eval_metric, list) and any(
                callable(m) for m in eval_metric):
            feval = [_EvalFunctionWrapper(m) for m in eval_metric
                     if callable(m)]
            eval_metric_name = [m for m in eval_metric if not callable(m)]
        else:
            feval = None
            eval_metric_name = eval_metric
        if eval_metric_name:
            params["metric"] = eval_metric_name

        if self.class_weight is not None and sample_weight is None:
            sample_weight = self._class_weights_to_sample_weight(y)

        X_arr = _as_matrix(X)
        self._n_features = X_arr.shape[1]
        self._n_features_in = X_arr.shape[1]
        if hasattr(X, "columns"):
            self.feature_names_in_ = np.asarray(
                [str(c) for c in X.columns], dtype=object)
            if feature_name == "auto":
                feature_name = [str(c) for c in X.columns]

        train_set = Dataset(X_arr, label=y, weight=sample_weight,
                            group=group, init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature,
                            params=params)
        valid_sets: List[Dataset] = []
        valid_names: List[str] = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vname = (eval_names[i] if eval_names is not None
                         else f"valid_{i}")

                def _pick(arrs, idx):
                    if arrs is None:
                        return None
                    return arrs[idx] if isinstance(arrs, (list, tuple)) \
                        else arrs
                if _is_same_data(vx, X) and _is_same_data(vy, y):
                    valid_sets.append(train_set)
                else:
                    vw = _pick(eval_sample_weight, i)
                    if _pick(eval_class_weight, i) is not None and vw is None:
                        vw = self._class_weights_to_sample_weight(
                            vy, _pick(eval_class_weight, i))
                    valid_sets.append(train_set.create_valid(
                        _as_matrix(vx), label=vy, weight=vw,
                        group=_pick(eval_group, i),
                        init_score=_pick(eval_init_score, i)))
                valid_names.append(vname)

        evals_result: Dict = {}
        cbs = list(callbacks) if callbacks else []
        cbs.append(record_evaluation(evals_result))

        self._Booster = train(
            params=params, train_set=train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets or None,
            valid_names=valid_names or None,
            feval=feval, init_model=init_model, callbacks=cbs)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        self._best_score = self._Booster.best_score
        self._objective_str = self._Booster.config.objective
        self._Booster.free_dataset()
        return self

    def _class_weights_to_sample_weight(self, y, class_weight=None):
        cw = class_weight if class_weight is not None else self.class_weight
        y_arr = np.asarray(y)
        if cw == "balanced":
            classes, counts = np.unique(y_arr, return_counts=True)
            weights = {c: len(y_arr) / (len(classes) * n)
                       for c, n in zip(classes, counts)}
        elif isinstance(cw, dict):
            weights = cw
        else:
            return None
        return np.asarray([weights.get(v, 1.0) for v in y_arr], np.float64)

    # -- predict ----------------------------------------------------------
    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                device: Optional[bool] = None, **kwargs):
        """ref: sklearn.py LGBMModel.predict (:1073).

        ``device=True`` routes through the packed-forest serving engine
        (batched device traversal, ISSUE 5) — identical split decisions
        to the host walk, f32 leaf accumulation; shapes the engine cannot
        serve fall back to the host path with a warning. ``None`` defers
        to the ``tpu_predict_device`` parameter. With
        ``pred_contrib=True`` the same flag selects the packed SHAP path
        tensors (ISSUE 20) — f32-accumulated device TreeSHAP; linear /
        categorical models fall back to the host walk loudly once."""
        if self._Booster is None:
            raise LightGBMError(
                "Estimator not fitted, call fit before predict")
        X_arr = _as_matrix(X)
        if X_arr.shape[1] != self._n_features:
            raise ValueError(
                f"Number of features of the model must match the input. "
                f"Model n_features_ is {self._n_features} and input "
                f"n_features is {X_arr.shape[1]}")
        if device is not None:
            kwargs = dict(kwargs, device=device)
        return self._Booster.predict(
            X_arr, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features,
            **kwargs)

    # -- fitted attributes (ref: sklearn.py properties) -------------------
    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    @property
    def n_features_in_(self) -> int:
        self._check_fitted()
        return self._n_features_in

    @property
    def best_score_(self) -> Dict:
        self._check_fitted()
        return self._best_score

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._best_iteration

    @property
    def objective_(self):
        self._check_fitted()
        return self._objective if callable(self._objective) \
            else self._objective_str

    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        self._check_fitted()
        return self._evals_result

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster.feature_importance(
            importance_type=self.importance_type)

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()

    @property
    def feature_names_in_(self) -> np.ndarray:
        """sklearn-compatible feature names (ref: sklearn.py:1368);
        raises AttributeError when unfitted or for anonymous (Column_N)
        features so sklearn's hasattr-based checks behave like the
        reference."""
        if self._Booster is None:
            raise AttributeError(
                "No feature_names_in_ found. Need to call fit beforehand.")
        names = self._Booster.feature_name()
        if all(n.startswith("Column_") for n in names):
            raise AttributeError(
                "feature_names_in_ is only available when training data "
                "had feature names")
        return np.asarray(names, dtype=object)

    @feature_names_in_.setter
    def feature_names_in_(self, value) -> None:
        # sklearn's validate_data assigns this on fit; the canonical
        # names live in the Booster (ref: sklearn.py:1380 opt-out)
        pass

    @feature_names_in_.deleter
    def feature_names_in_(self) -> None:
        # sklearn deletes it for name-less refits; same opt-out
        pass

    @property
    def n_estimators_(self) -> int:
        self._check_fitted()
        return self._Booster.num_trees() // max(
            self._Booster.num_model_per_iteration(), 1)

    @property
    def n_iter_(self) -> int:
        return self.n_estimators_

    def _check_fitted(self) -> None:
        if self._Booster is None:
            raise LightGBMError("No booster found. Need to call fit first.")

    def __sklearn_is_fitted__(self) -> bool:
        return self._Booster is not None


class LGBMRegressor(_SKRegressor, LGBMModel):
    """ref: sklearn.py:1409 LGBMRegressor."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        if self._objective is None and not callable(self.objective):
            self._objective = self.objective or "regression"
        return super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)


class LGBMClassifier(_SKClassifier, LGBMModel):
    """ref: sklearn.py:1524 LGBMClassifier."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_class_weight=None,
            eval_init_score=None, eval_metric=None, feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        self._le = _SKLabelEncoder().fit(y)
        self._classes = self._le.classes_
        self._n_classes = len(self._classes)
        # class weights must be resolved against ORIGINAL labels, before
        # label encoding (dict keys are in user label space)
        if self.class_weight is not None and sample_weight is None:
            sample_weight = self._class_weights_to_sample_weight(y)
        if eval_set is not None and eval_class_weight is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            ecw = (eval_class_weight if isinstance(eval_class_weight,
                                                   (list, tuple))
                   else [eval_class_weight] * len(eval_set))
            esw = list(eval_sample_weight) if eval_sample_weight is not None \
                else [None] * len(eval_set)
            for i, (vx, vy) in enumerate(eval_set):
                if ecw[i] is not None and esw[i] is None:
                    esw[i] = self._class_weights_to_sample_weight(vy, ecw[i])
            eval_sample_weight = esw
            eval_class_weight = None
        y_enc = self._le.transform(y)
        if not callable(self.objective):
            if self.objective is None:
                self._objective = ("binary" if self._n_classes <= 2
                                   else "multiclass")
            else:
                self._objective = self.objective
        else:
            self._objective = self.objective
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            eval_set = [(vx, self._le.transform(vy)) for vx, vy in eval_set]
        return super().fit(
            X, y_enc, sample_weight=sample_weight, init_score=init_score,
            eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_class_weight=eval_class_weight,
            eval_init_score=eval_init_score, eval_metric=eval_metric,
            feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)

    def predict(self, X, raw_score: bool = False, start_iteration: int = 0,
                num_iteration: Optional[int] = None, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                device: Optional[bool] = None, **kwargs):
        result = self.predict_proba(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features,
            device=device, **kwargs)
        if callable(self._objective) or raw_score or pred_leaf or \
                pred_contrib:
            return result
        if result.ndim == 2:
            class_index = np.argmax(result, axis=1)
        else:
            class_index = (result > 0.5).astype(np.int64)
        return self._classes[class_index]

    def decision_function(self, X, *, start_iteration: int = 0,
                          num_iteration: Optional[int] = None,
                          validate_features: bool = False, **kwargs):
        """Raw margin score per sample (ref: sklearn.py:1769
        decision_function — sklearn's standard margin accessor)."""
        return self.predict_proba(
            X, raw_score=True, start_iteration=start_iteration,
            num_iteration=num_iteration,
            validate_features=validate_features, **kwargs)

    def predict_proba(self, X, raw_score: bool = False,
                      start_iteration: int = 0,
                      num_iteration: Optional[int] = None,
                      pred_leaf: bool = False, pred_contrib: bool = False,
                      validate_features: bool = False,
                      device: Optional[bool] = None, **kwargs):
        """ref: sklearn.py LGBMClassifier.predict_proba (:1738)."""
        result = super().predict(
            X, raw_score=raw_score, start_iteration=start_iteration,
            num_iteration=num_iteration, pred_leaf=pred_leaf,
            pred_contrib=pred_contrib, validate_features=validate_features,
            device=device, **kwargs)
        if callable(self._objective) or raw_score or pred_leaf or \
                pred_contrib:
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.vstack((1.0 - result, result)).transpose()
        return result

    @property
    def classes_(self) -> np.ndarray:
        self._check_fitted()
        return self._classes

    @property
    def n_classes_(self) -> int:
        self._check_fitted()
        return self._n_classes


class LGBMRanker(LGBMModel):
    """ref: sklearn.py:1832 LGBMRanker."""

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), feature_name="auto",
            categorical_feature="auto", callbacks=None, init_model=None):
        if group is None:
            raise ValueError("Should set group for ranking task")
        if eval_set is not None and eval_group is None:
            raise ValueError("Eval_group cannot be None when eval_set is not "
                             "None")
        if not callable(self.objective):
            self._objective = self.objective or "lambdarank"
        self._eval_at = eval_at  # -> ndcg@k metrics via _process_params
        booster = super().fit(
            X, y, sample_weight=sample_weight, init_score=init_score,
            group=group, eval_set=eval_set, eval_names=eval_names,
            eval_sample_weight=eval_sample_weight,
            eval_init_score=eval_init_score, eval_group=eval_group,
            eval_metric=eval_metric, feature_name=feature_name,
            categorical_feature=categorical_feature, callbacks=callbacks,
            init_model=init_model)
        return booster

    def _process_params(self, stage: str) -> Dict[str, Any]:
        params = super()._process_params(stage)
        params.pop("eval_at", None)
        if getattr(self, "_eval_at", None) is not None:
            ea = self._eval_at
            params["eval_at"] = ([ea] if isinstance(ea, int)
                                 else list(ea))
        return params


def _as_matrix(X):
    """numpy / pandas / scipy-sparse -> dense 2-D float array."""
    try:
        import scipy.sparse as sp
        if sp.issparse(X):
            return np.asarray(X.todense(), dtype=np.float64)
    except ImportError:
        pass
    if hasattr(X, "values") and hasattr(X, "columns"):
        X = X.values
    arr = np.asarray(X)
    if arr.ndim == 1:
        arr = arr.reshape(-1, 1)
    return np.ascontiguousarray(arr, dtype=np.float64)


def _is_same_data(a, b) -> bool:
    if a is b:
        return True
    try:
        return (np.asarray(a).shape == np.asarray(b).shape and
                np.shares_memory(np.asarray(a), np.asarray(b)))
    except Exception:
        return False
