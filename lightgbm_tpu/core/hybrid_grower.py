"""Hybrid level+tail grower (round-6 phase B, docs/TPU_RUNBOOK.md §3).

The pure level grower (core/level_grower.py) kills the sequential
split loop but its dense [2^d, F, B, 3] level hists cap it at
``max_depth <= MAX_LEVEL_DEPTH`` — excluding the DEFAULT benchmark
config (255 leaves, ``max_depth=-1``), the one shape the round-5
device verdict says is dispatch-bound. This module lifts the cap:

1. run the level phase to a handoff depth D0 (~15 dispatches per LEVEL
   instead of ~40 per SPLIT), scanning levels 0..D0 so every candidate
   node's gain — and hence e(v) = min path gain — is known EXACTLY for
   all nodes at depth <= D0;
2. rank all candidates by e (descending, stable ties = heap order) and
   COMMIT the rank prefix that provably matches the sequential
   best-first expansion: the cut stops at the first rank that expands
   a depth-D0 node (exactness guard). Any deeper node w has
   e(w) <= e(parent(w)) with parent at depth D0, and the parent's own
   expansion position is >= the cut, so no unscanned node can preempt
   a committed rank — the committed prefix IS the true first-k0
   expansion sequence, set and numbering;
3. seed the sequential grower's GrowState from the level output —
   per-leaf stats/best rows straight from the level scans
   (ops/split.pack_record_rows layout), histogram-pool rows gathered
   from the kept level hists, order/seg reconstructed by a stable sort
   on leaf ids — and resume core/grower.py's fori_loop at traced step
   k0. The tail finishes the deep part leaf-wise to ``num_leaves`` at
   unbounded depth with the EXISTING, fully-tested sequential body.

Exactness: the committed splits and the tail use the same SplitRecord
arithmetic; the only divergence channel vs a pure sequential run is
histogram accumulation order (bit-exact for dyadic gradients and the
quantized int32 path, f32 reassociation noise otherwise — same caveat
as the pure level mode). A balanced 255-leaf tree is depth 8, so at
D0 = 9 the level phase typically resolves the bulk of the 254 splits
and the tail handles only the deep best-first excursions.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.split import (SplitRecord, meta_has_categorical,
                         pack_record_rows)
from .grower import (NS, S_LMAX, S_LMIN, S_PARENT, GrowerConfig,
                     GrowState, make_tree_grower)
from .level_grower import (MAX_LEVEL_DEPTH, make_level_phase,
                           rank_and_slots)


def auto_handoff_depth(num_leaves: int) -> int:
    """Default D0: one past the balanced depth of a num_leaves tree
    (ceil(log2(L)) + 1 — 255 leaves -> 9), clamped to
    [1, MAX_LEVEL_DEPTH]. One extra level costs ~4 batched kernels and
    moves best-first excursions out of the sequential tail."""
    d = int(np.ceil(np.log2(max(int(num_leaves), 2)))) + 1
    return max(1, min(d, MAX_LEVEL_DEPTH))


def resolve_handoff_depth(num_leaves: int, requested: int) -> int:
    """The ONE handoff-depth resolution (<=0 -> auto; clamp to
    [1, MAX_LEVEL_DEPTH]) — shared by make_hybrid_grower and the
    eligibility memory gate in models/gbdt.py so the depth the gate
    budgets is always the depth the grower runs."""
    d = int(requested) if int(requested) > 0 else \
        auto_handoff_depth(num_leaves)
    return max(1, min(d, MAX_LEVEL_DEPTH))


def make_hybrid_grower(cfg: GrowerConfig, meta, bundle=None,
                       handoff_depth: int = 0):
    """Build ``grow(bins_rm, gh, feature_mask, cegb, rng_key)`` ->
    ``(TreeArrays, leaf_id)`` over row-major uint8/16 bins [R, F]
    ([R, G] physical groups when ``bundle`` is set) for unbounded /
    deep ``max_depth`` — the level phase to D0 plus the sequential
    compact tail. ``handoff_depth`` <= 0 means auto."""
    L = int(cfg.num_leaves)
    D0 = resolve_handoff_depth(L, handoff_depth)
    if 0 < cfg.max_depth <= D0:
        raise ValueError(
            f"hybrid growth needs max_depth > handoff depth {D0} "
            f"(got {cfg.max_depth}); the pure level grower serves "
            "shallow configs")
    hp = cfg.hparams
    B = int(cfg.num_bin)
    has_cat = meta_has_categorical(meta)
    MAXK = min(hp.max_cat_threshold, B) if has_cat else 0
    NB = 13 if has_cat else 12
    NN = 10 if has_cat else 9
    quantized = cfg.quantized
    hist_dtype = jnp.int32 if quantized else jnp.float32
    inf = jnp.float32(jnp.inf)

    phase = make_level_phase(cfg, meta, depth=D0, scan_last=True,
                             bundle=bundle, collect_hists=True)
    # the tail is the EXISTING compact sequential program, resumed from
    # the level phase's committed state via its ``init`` seam. The
    # level-only histogram backend (e.g. pallas_level) must not leak
    # into the tail's row-major kernel selection: the tail reads
    # hist_rm_backend only, and the pool it resumes from is seeded
    # below from whatever kernel the level phase ran — the raw
    # accumulator dtype contract (f32 / exact int32) is identical
    # across scatter, blocks and pallas_level, so the handoff stays
    # bit-exact regardless of which one produced the hists.
    tail_cfg = dataclasses.replace(cfg, row_sched="compact",
                                   level_hist_backend="")
    tail_grow = make_tree_grower(tail_cfg, meta, bundle=bundle)

    T = 2 ** (D0 + 1) - 1             # heap nodes, levels 0..D0
    ids_np = np.arange(T)
    depth_np = np.floor(np.log2(ids_np + 1)).astype(np.int32)
    par_np = np.maximum((ids_np - 1) // 2, 0).astype(np.int32)
    is_deep_np = depth_np == D0
    # right children have even heap ids (> 0)
    isr_np = ((ids_np % 2 == 0) & (ids_np > 0)).astype(np.float32)

    def grow(bins_rm, gh, feature_mask=None, cegb=None, rng_key=None):
        R = bins_rm.shape[0]
        res = phase(bins_rm, gh, feature_mask, rng_key)

        # ---- rank + exactness cut + committed-tree leaf slots ------
        # (level_grower.rank_and_slots — the shared slot-numbering/
        # eff-resolution invariant). The cut: the selected prefix stops
        # at the first rank held by a depth-D0 node; ranks before it
        # beat every depth-D0 e, hence (e is monotone down any path)
        # every unscanned deeper node too. Invalid deep nodes
        # (e = -inf) sit in the -inf tail at positions >= k, so a tree
        # that never reaches depth D0 commits all k splits and the tail
        # starts done.
        rank, k0, committed, slot, eff = rank_and_slots(
            res["e"], L, D0, cut_mask=jnp.asarray(is_deep_np))
        # every row's node resolves: committed nodes hold no rows
        # (their partitions ran), and the first non-committed ancestor
        # is the row's live leaf
        leaf_slot = jnp.maximum(eff[res["heap"]], 0)    # [R]

        # ---- order/seg: stable sort on leaf ids --------------------
        # (runbook §3: the sequential order after k0 stable partitions
        # of arange(R) keeps original row order inside every leaf —
        # exactly what a stable argsort on the slot keys rebuilds)
        order_rows = jnp.argsort(leaf_slot,
                                 stable=True).astype(jnp.int32)
        cnt = jnp.zeros(L, jnp.int32).at[leaf_slot].add(1)
        starts = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(cnt)])[:L]

        ids_all = jnp.asarray(ids_np, jnp.int32)
        par_all = jnp.asarray(par_np, jnp.int32)
        live = (~committed) & ((committed[par_all] & (ids_all > 0)) |
                               ((ids_all == 0) & (k0 == 0)))
        lslot = jnp.where(live, slot, L)                # dump slot L
        live_slot = jnp.zeros(L + 1, bool).at[lslot].set(True)[:L]
        node_of_slot = jnp.zeros(L + 1, jnp.int32).at[lslot].set(
            ids_all)[:L]
        seg = jnp.stack([jnp.where(live_slot, starts, 0),
                         jnp.where(live_slot, cnt, 0)], axis=1)

        # ---- per-leaf stats rows (grower.py S_* columns) -----------
        depth_h = jnp.asarray(depth_np, jnp.float32)
        isr_h = jnp.asarray(isr_np)
        prank = rank[par_all].astype(jnp.float32)
        root = ids_all == 0
        stat_rows = jnp.stack(
            [res["sg"], res["sh"], res["cn"], res["out"],
             jnp.full(T, -inf), jnp.full(T, inf), depth_h,
             jnp.where(root, -1.0, prank), isr_h,
             jnp.where(root, 0.0, 2.0 * prank + 1.0 + isr_h)],
            axis=1)                                     # [T, NS]
        stats0 = jnp.zeros((L + 1, NS), jnp.float32)
        stats0 = stats0.at[:, S_LMIN].set(-inf)
        stats0 = stats0.at[:, S_LMAX].set(inf)
        stats0 = stats0.at[:, S_PARENT].set(-1.0)
        stats = stats0.at[lslot].set(stat_rows)[:L]

        # ---- per-leaf best rows: straight from the level scans -----
        # (every live leaf sits at depth <= D0 and was scanned)
        inv_row = pack_record_rows(
            SplitRecord.invalid((), max_cat=MAXK), has_cat)
        best = jnp.broadcast_to(inv_row, (L + 1, NB)).at[lslot].set(
            res["rows"])[:L]
        if has_cat:
            best_cat = jnp.full((L + 1, MAXK), -1, jnp.int32).at[
                lslot].set(res["catb"])[:L]
        else:
            best_cat = None

        # ---- committed internal-node rows (grower.py N_* columns) --
        f32 = lambda a: a.astype(jnp.float32)
        lc_all = jnp.minimum(2 * ids_all + 1, T - 1)
        rc_all = jnp.minimum(2 * ids_all + 2, T - 1)
        lptr = jnp.where(committed[lc_all], f32(rank[lc_all]),
                         -f32(slot[lc_all] + 1))
        rptr = jnp.where(committed[rc_all], f32(rank[rc_all]),
                         -f32(slot[rc_all] + 1))
        node_cols = [f32(res["feat"]), f32(res["thr"]), f32(res["dl"]),
                     res["gain"], res["out"], res["sh"], res["cn"],
                     lptr, rptr]
        if has_cat:
            node_cols.append(f32(res["ncat"]))
        node_rows = jnp.stack(node_cols, axis=1)        # [T, NN]
        # dump slot = L-1, the node matrix's never-read scratch row
        rk_nodes = jnp.where(committed, rank, L - 1)
        node = jnp.zeros((L, NN), jnp.float32).at[rk_nodes].set(
            node_rows)
        if has_cat:
            tree_cat = jnp.full((L, MAXK), -1, jnp.int32).at[
                rk_nodes].set(res["catb"])[:L - 1]
        else:
            tree_cat = None

        # ---- histogram pool: gather live leaves' level hists -------
        # (raw accumulator dtype — the tail converts at scan time with
        # the same per-tree scales; unborn slots alias the root row,
        # which the tail never reads before writing)
        pool = res["hists"][node_of_slot]               # [L, Fp, B, 3]
        pool = pool.astype(hist_dtype)

        state = GrowState(
            leaf_id=leaf_slot,
            hist=pool,
            stats=stats,
            best=best,
            node=node,
            num_leaves=(k0 + 1).astype(jnp.int32),
            done=jnp.asarray(False),
            best_cat=best_cat,
            tree_cat=tree_cat,
            path_mask=None,
            forced_ok=jnp.asarray(True),
            order=order_rows,
            seg=seg,
        )
        return tail_grow(bins_rm, gh, feature_mask, cegb, rng_key,
                         init=(state, k0))

    return grow
