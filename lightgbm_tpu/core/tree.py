"""Tree model arrays (structure-of-arrays, fixed capacity).

TPU-native equivalent of the reference Tree object
(ref: include/LightGBM/tree.h:27, src/io/tree.cpp). The reference stores
per-node vectors that grow during training; here every tree is a pytree of
fixed-size arrays (capacity = num_leaves), XLA-friendly and stackable across
trees for batched prediction.

Node numbering matches Tree::Split exactly so that the text format
round-trips against the reference: splitting leaf ``l`` at step ``s`` creates
internal node ``s``; the left child keeps leaf index ``l``, the right child
becomes leaf ``s+1``; leaves are encoded in child pointers as ``~leaf_idx``
(ref: tree.cpp Tree::Split, tree.h left_child_/right_child_ docs).
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


def _f32_round(arr32: np.ndarray) -> np.ndarray:
    """Widen an f32 result back to the f64 storage dtype (exact)."""
    return arr32.astype(np.float64)


def max_leaf_depth(left_child, right_child, num_leaves) -> int:
    """Max root->leaf path length in DECISIONS — the number of lockstep
    traversal steps needed for every row to absorb into a leaf (a leaf at
    depth d absorbs at step d). 0 for a single-leaf tree. Malformed child
    pointers (cyclic / out of range, e.g. a corrupted model file) fall
    back to the exhaustive ``num_leaves - 1`` bound instead of looping."""
    n = int(num_leaves) - 1
    if n <= 0:
        return 0
    lc = np.asarray(left_child[:n], np.int64)
    rc = np.asarray(right_child[:n], np.int64)
    best = 1
    stack = [(0, 1)]
    budget = 4 * n + 8
    while stack:
        budget -= 1
        if budget <= 0:
            return n
        node, d = stack.pop()
        if d > best:
            best = d
        if d >= n:        # deeper than any well-formed tree: cycle
            return n
        for c in (int(lc[node]), int(rc[node])):
            if 0 <= c < n:
                stack.append((c, d + 1))
    return best


class TreeArrays(NamedTuple):
    """One tree. Internal-node arrays have length L-1, leaf arrays L."""
    # internal nodes
    split_feature: jnp.ndarray    # i32 [L-1] inner (used-feature) index
    threshold_bin: jnp.ndarray    # i32 [L-1]
    default_left: jnp.ndarray     # bool [L-1]
    left_child: jnp.ndarray       # i32 [L-1]; >=0 internal, <0 is ~leaf
    right_child: jnp.ndarray      # i32 [L-1]
    split_gain: jnp.ndarray       # f32 [L-1]
    internal_value: jnp.ndarray   # f32 [L-1] node output (ref: internal_value_)
    internal_weight: jnp.ndarray  # f32 [L-1] sum_hessian at node
    internal_count: jnp.ndarray   # f32 [L-1]
    # leaves
    leaf_value: jnp.ndarray       # f32 [L]
    leaf_weight: jnp.ndarray      # f32 [L] sum_hessian
    leaf_count: jnp.ndarray       # f32 [L]
    leaf_parent: jnp.ndarray      # i32 [L]
    num_leaves: jnp.ndarray       # i32 scalar
    shrinkage: jnp.ndarray        # f32 scalar
    # categorical splits (None when the dataset has no categorical
    # features; ref: tree.h cat_boundaries_inner_/cat_threshold_inner_ —
    # stored here as a fixed-width padded set of category BINS per node)
    cat_count: jnp.ndarray = None  # i32 [L-1]; 0 = numerical node
    cat_bins: jnp.ndarray = None   # i32 [L-1, max_cat_threshold], -1 pad
    # max leaf depth recorded at pack time (host_tree_to_arrays); bounds
    # the traversal fori_loop at the tree's REAL depth instead of L-1
    # (ops/predict.py). None for grower-built device trees (the grower
    # never traverses its own output; depth is computed on the host copy)
    max_depth: jnp.ndarray = None  # i32 scalar

    @staticmethod
    def empty(max_leaves: int, max_cat: int = 0) -> "TreeArrays":
        li = max_leaves - 1
        return TreeArrays(
            split_feature=jnp.zeros(li, jnp.int32),
            threshold_bin=jnp.zeros(li, jnp.int32),
            default_left=jnp.zeros(li, bool),
            left_child=jnp.zeros(li, jnp.int32),
            right_child=jnp.zeros(li, jnp.int32),
            split_gain=jnp.zeros(li, jnp.float32),
            internal_value=jnp.zeros(li, jnp.float32),
            internal_weight=jnp.zeros(li, jnp.float32),
            internal_count=jnp.zeros(li, jnp.float32),
            leaf_value=jnp.zeros(max_leaves, jnp.float32),
            leaf_weight=jnp.zeros(max_leaves, jnp.float32),
            leaf_count=jnp.zeros(max_leaves, jnp.float32),
            leaf_parent=jnp.full(max_leaves, -1, jnp.int32),
            num_leaves=jnp.asarray(1, jnp.int32),
            shrinkage=jnp.asarray(1.0, jnp.float32),
            cat_count=jnp.zeros(li, jnp.int32) if max_cat else None,
            cat_bins=(jnp.full((li, max_cat), -1, jnp.int32)
                      if max_cat else None),
        )

    @property
    def max_leaves(self) -> int:
        return self.leaf_value.shape[0]


class HostTree:
    """Host-side (numpy) view of a trained tree, for model IO & prediction
    bookkeeping. Thresholds are resolved to real values lazily via the
    dataset's BinMappers (ref: Tree::threshold_ double values in model text).
    """

    def __init__(self, arrays: TreeArrays, used_feature_map: np.ndarray):
        a = {f: np.asarray(getattr(arrays, f))
             for f in arrays._fields if getattr(arrays, f) is not None}
        self.num_leaves = int(a["num_leaves"])
        n_int = max(self.num_leaves - 1, 0)
        self.split_feature_inner = a["split_feature"][:n_int].astype(np.int32)
        self.split_feature = (
            used_feature_map[self.split_feature_inner]
            if n_int else np.zeros(0, np.int32))
        self.threshold_bin = a["threshold_bin"][:n_int]
        self.default_left = a["default_left"][:n_int]
        self.left_child = a["left_child"][:n_int]
        self.right_child = a["right_child"][:n_int]
        self.split_gain = a["split_gain"][:n_int].astype(np.float64)
        self.internal_value = a["internal_value"][:n_int].astype(np.float64)
        self.internal_weight = a["internal_weight"][:n_int].astype(np.float64)
        self.internal_count = a["internal_count"][:n_int].astype(np.int64)
        L = self.num_leaves
        self.leaf_value = a["leaf_value"][:L].astype(np.float64)
        self.leaf_weight = a["leaf_weight"][:L].astype(np.float64)
        self.leaf_count = a["leaf_count"][:L].astype(np.int64)
        self.leaf_parent = a["leaf_parent"][:L]
        self.shrinkage = float(a["shrinkage"])
        self.max_depth = max_leaf_depth(self.left_child, self.right_child,
                                        self.num_leaves)
        # per-node category-BIN sets from the grower (inner representation,
        # ref: cat_threshold_inner_); -1 padded, empty for numerical nodes
        if "cat_bins" in a and n_int:
            self.cat_bins_inner = a["cat_bins"][:n_int].astype(np.int32)
            self.cat_count_inner = a["cat_count"][:n_int].astype(np.int32)
        else:
            self.cat_bins_inner = np.zeros((n_int, 0), np.int32)
            self.cat_count_inner = np.zeros(n_int, np.int32)
        # filled by model IO
        self.threshold_real: np.ndarray = np.zeros(n_int, np.float64)
        self.decision_type: np.ndarray = np.zeros(n_int, np.int32)
        self.is_linear = False
        self.num_cat = 0
        # bitset storage of RAW category values per cat node
        # (ref: tree.h cat_boundaries_/cat_threshold_)
        self.cat_boundaries: np.ndarray = np.zeros(1, np.int64)
        self.cat_threshold: np.ndarray = np.zeros(0, np.uint32)
        self._init_linear_fields()

    def _init_linear_fields(self) -> None:
        """Per-leaf linear models (ref: tree.h leaf_const_/leaf_coeff_/
        leaf_features_), populated when is_linear."""
        L = self.num_leaves
        self.leaf_const = np.zeros(L, np.float64)
        self.leaf_coeff: list = [np.zeros(0, np.float64)] * L
        self.leaf_features: list = [[] for _ in range(L)]  # ORIGINAL idx

    @classmethod
    def constant(cls, value: float) -> "HostTree":
        """Single-leaf constant tree (ref: tree.cpp Tree::AsConstantTree)."""
        self = cls.__new__(cls)
        self.num_leaves = 1
        for f in ("split_feature_inner", "split_feature", "threshold_bin",
                  "default_left", "left_child", "right_child"):
            setattr(self, f, np.zeros(0, np.int32))
        for f in ("split_gain", "internal_value", "internal_weight"):
            setattr(self, f, np.zeros(0, np.float64))
        self.internal_count = np.zeros(0, np.int64)
        self.leaf_value = np.asarray([value], np.float64)
        self.leaf_weight = np.zeros(1, np.float64)
        self.leaf_count = np.zeros(1, np.int64)
        self.leaf_parent = np.full(1, -1, np.int32)
        self.shrinkage = 1.0
        self.max_depth = 0
        self.threshold_real = np.zeros(0, np.float64)
        self.decision_type = np.zeros(0, np.int32)
        self.is_linear = False
        self.num_cat = 0
        self.cat_bins_inner = np.zeros((0, 0), np.int32)
        self.cat_count_inner = np.zeros(0, np.int32)
        self.cat_boundaries = np.zeros(1, np.int64)
        self.cat_threshold = np.zeros(0, np.uint32)
        self._init_linear_fields()
        return self

    def shrink(self, rate: float) -> None:
        """ref: tree.h Tree::Shrinkage (scales linear consts/coeffs too).

        The product rounds through f32: the f32 score accumulator adds
        ``f32(leaf_value) * f32(rate)`` (models/gbdt.py sync and async
        score updates), so the STORED value must be that exact product —
        an f64 product that rounds differently by one ulp makes a
        replayed model (init_model / checkpoint resume) diverge from the
        live score and eventually flip near-tie splits."""
        self.leaf_value = _f32_round(
            self.leaf_value.astype(np.float32) * np.float32(rate))
        self.internal_value = _f32_round(
            self.internal_value.astype(np.float32) * np.float32(rate))
        self.shrinkage *= rate
        if self.is_linear:
            # linear terms predict in f64 from raw features; keep full
            # precision (the linear path has no async/replay counterpart)
            self.leaf_const = self.leaf_const * rate
            self.leaf_coeff = [c * rate for c in self.leaf_coeff]

    def copy(self) -> "HostTree":
        """Deep copy (continued training keeps the source model intact)."""
        import copy as _copy
        new = self.__class__.__new__(self.__class__)
        for k, v in self.__dict__.items():
            new.__dict__[k] = v.copy() if isinstance(v, np.ndarray) else v
        return new

    def add_bias(self, val: float) -> None:
        """ref: tree.cpp Tree::AddBias — folds the boost-from-average init
        score into the first tree so the saved model is self-contained.

        Rounds through f32 for the same replay-exactness reason as
        :meth:`shrink`: the live score received ``f32(bias)`` and
        ``f32(leaf_value)`` as separate f32 adds, so the folded stored
        value must be the f32 sum of those two f32 terms."""
        self.leaf_value = _f32_round(
            self.leaf_value.astype(np.float32) + np.float32(val))
        self.internal_value = _f32_round(
            self.internal_value.astype(np.float32) + np.float32(val))
        if self.is_linear:
            self.leaf_const = self.leaf_const + val

    def linear_output(self, X: np.ndarray, leaf: np.ndarray) -> np.ndarray:
        """Per-row output of a LINEAR tree given raw features and leaf
        routing (ref: tree.cpp PredictionFunLinear — NaN in any leaf
        feature falls back to the leaf constant)."""
        out = self.leaf_const[leaf]
        for l in range(self.num_leaves):
            feats = self.leaf_features[l]
            if not feats:
                continue
            rows = leaf == l
            if not rows.any():
                continue
            Xl = X[rows][:, feats].astype(np.float64)
            lin = Xl @ self.leaf_coeff[l]
            nan_rows = np.isnan(Xl).any(axis=1)
            out[rows] += np.where(nan_rows, 0.0, lin)
        return out

    def add_output(self, delta: np.ndarray) -> None:
        self.leaf_value = self.leaf_value + delta

    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Raw-feature traversal -> leaf index per row (host path; device
        batched traversal lives in ops/predict.py)."""
        n = X.shape[0]
        out = np.zeros(n, dtype=np.int64)
        if self.num_leaves == 1:
            return out
        node = np.zeros(n, dtype=np.int64)
        active = np.ones(n, dtype=bool)
        # decision_type bits (ref: tree.h kCategoricalMask=1, kDefaultLeftMask=2,
        # missing type in bits 2-3)
        for _ in range(self.num_leaves):  # depth bound
            if not active.any():
                break
            f = self.split_feature[node]
            thr = self.threshold_real[node]
            dl = (self.decision_type[node] & 2) != 0
            is_cat = (self.decision_type[node] & 1) != 0
            mtype = (self.decision_type[node] >> 2) & 3
            x = X[np.arange(n), f]
            isnan = np.isnan(x)
            x0 = np.where(isnan, 0.0, x)
            le = x0 <= thr
            if is_cat.any():
                # bitset membership on RAW category values, vectorized
                # (ref: tree.h:375 CategoricalDecision + FindInBitset)
                le = np.where(is_cat,
                              self._cat_in_bitset(node, x0, isnan), le)
            # missing handling: 0 none (NaN->0), 1 zero, 2 nan
            miss = np.where(mtype == 2, isnan,
                            (mtype == 1) & (np.abs(x0) <= 1e-35))
            miss = miss & ~is_cat  # cat NaN/unseen goes right (not in set)
            go_left = np.where(miss, dl, le)
            child = np.where(go_left, self.left_child[node],
                             self.right_child[node])
            is_leaf = child < 0
            upd = active & is_leaf
            out[upd] = ~child[upd]
            active = active & ~is_leaf
            node = np.where(active, np.maximum(child, 0), node)
        return out

    def cat_values(self, cat_idx: int) -> list:
        """Decode one categorical node's bitset back to its raw category
        values (ref: Common::FindInBitset layout — 32-bit words)."""
        lo = int(self.cat_boundaries[cat_idx])
        hi = int(self.cat_boundaries[min(cat_idx + 1,
                                         len(self.cat_boundaries) - 1)])
        return [w * 32 + b for w in range(hi - lo) for b in range(32)
                if (int(self.cat_threshold[lo + w]) >> b) & 1]

    def _cat_in_bitset(self, node: np.ndarray, x0: np.ndarray,
                       isnan: np.ndarray) -> np.ndarray:
        """Vectorized FindInBitset over per-node category bitsets
        (ref: include/LightGBM/utils/common.h FindInBitset,
        tree.h:375-391 CategoricalDecision). ``threshold_real`` of a cat
        node holds its index into ``cat_boundaries``."""
        cat_idx = self.threshold_real[node].astype(np.int64)
        cat_idx = np.clip(cat_idx, 0, max(self.num_cat - 1, 0))
        lo = self.cat_boundaries[cat_idx]
        hi = self.cat_boundaries[np.minimum(cat_idx + 1,
                                            len(self.cat_boundaries) - 1)]
        v = np.where(isnan | (x0 < 0), -1, np.floor(x0)).astype(np.int64)
        word = lo + (v >> 5)
        ok = (v >= 0) & (word < hi)
        word_c = np.clip(word, 0, max(len(self.cat_threshold) - 1, 0))
        bits = (self.cat_threshold[word_c] if len(self.cat_threshold)
                else np.zeros_like(word_c, np.uint32))
        return ok & (((bits >> (v & 31).astype(np.uint32)) & 1) != 0)

    def predict(self, X: np.ndarray) -> np.ndarray:
        leaf = self.predict_leaf(X)
        if self.is_linear:
            return self.linear_output(X, leaf)
        return self.leaf_value[leaf]


def host_tree_to_arrays(t: HostTree, max_leaves: int) -> TreeArrays:
    """Rebuild device TreeArrays from a host tree (DART drop/restore,
    valid-set traversal of reloaded models, and packed-forest serving).
    Records the tree's max leaf depth so traversals can run depth-bounded
    instead of the exhaustive ``max_leaves - 1`` lockstep walk."""
    li = max_leaves - 1
    L = max_leaves

    def pad_i(a, n):
        out = np.zeros(n, np.int32)
        out[:len(a)] = a
        return jnp.asarray(out)

    def pad_f(a, n):
        out = np.zeros(n, np.float32)
        out[:len(a)] = a
        return jnp.asarray(out)

    def pad_b(a, n):
        out = np.zeros(n, bool)
        out[:len(a)] = a
        return jnp.asarray(out)

    cat_count = cat_bins = None
    cci = getattr(t, "cat_count_inner", None)
    if cci is not None and len(cci) and cci.any():
        width = max(t.cat_bins_inner.shape[1], 1)
        cb = np.full((li, width), -1, np.int32)
        cb[:t.cat_bins_inner.shape[0]] = t.cat_bins_inner
        cat_bins = jnp.asarray(cb)
        cat_count = pad_i(cci, li)
    depth = getattr(t, "max_depth", None)
    if depth is None:
        depth = max_leaf_depth(t.left_child, t.right_child, t.num_leaves)
    return TreeArrays(
        split_feature=pad_i(t.split_feature_inner, li),
        threshold_bin=pad_i(t.threshold_bin, li),
        default_left=pad_b(t.default_left, li),
        left_child=pad_i(t.left_child, li),
        right_child=pad_i(t.right_child, li),
        split_gain=pad_f(t.split_gain, li),
        internal_value=pad_f(t.internal_value, li),
        internal_weight=pad_f(t.internal_weight, li),
        internal_count=pad_f(t.internal_count, li),
        leaf_value=pad_f(t.leaf_value, L),
        leaf_weight=pad_f(t.leaf_weight, L),
        leaf_count=pad_f(t.leaf_count, L),
        leaf_parent=pad_i(t.leaf_parent, L),
        num_leaves=jnp.asarray(t.num_leaves, jnp.int32),
        shrinkage=jnp.asarray(t.shrinkage, jnp.float32),
        cat_count=cat_count,
        cat_bins=cat_bins,
        max_depth=jnp.asarray(min(int(depth), li), jnp.int32),
    )
