"""Leaf-wise (best-first) tree grower as a single jitted program.

TPU-native equivalent of SerialTreeLearner::Train
(ref: src/treelearner/serial_tree_learner.cpp:183-249 main split loop,
:344 BeforeFindBestSplit smaller/larger leaf logic, :770 SplitInner).

Design (SURVEY.md §7 "hard parts"):
- The reference's dynamic leaf membership (permuted index arrays in
  DataPartition) becomes a per-row ``leaf_id`` vector updated by masked
  `where` — XLA-friendly, no dynamic shapes.
- The split loop is a `fori_loop` with exactly num_leaves-1 steps. A latched
  ``done`` flag turns trailing steps into no-ops, so when step i proceeds,
  the tree provably has i+1 leaves: node/new-leaf indices are static.
- LightGBM's "build smaller child, subtract for the larger" trick
  (serial_tree_learner.cpp:368-386 + FeatureHistogram::Subtract) is kept:
  one masked full-row histogram pass per split for the smaller child; the
  sibling comes from parent - smaller.
- Distributed training reuses this exact program: `reduce_hist` /
  `reduce_sums` hooks psum partial histograms over the mesh's data axis
  (≡ DataParallelTreeLearner's ReduceScatter+sync, SURVEY §2.3).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.histogram import make_hist_fn, hist_rowmajor
from ..ops.split import (FeatureMeta, SplitHyperParams, SplitRecord,
                         K_EPSILON, K_MIN_SCORE, best_split_for_leaf,
                         calculate_splitted_leaf_output, forced_split_record,
                         meta_has_categorical, pack_record_rows)
from .tree import TreeArrays


@dataclasses.dataclass(frozen=True)
class GrowerConfig:
    """Static knobs baked into the jitted grower."""
    num_leaves: int = 31
    max_depth: int = -1
    num_bin: int = 256          # B: max bins over used features
    hparams: SplitHyperParams = SplitHyperParams()
    hist_backend: str = "xla"   # xla | scatter | pallas
    block_rows: int = 4096
    # row scheduling: "full" = masked full-row histogram passes (bins given
    # feature-major [F, R]); "compact" = per-leaf contiguous row ordering
    # with gathered O(rows_in_leaf) passes (bins given ROW-major [R, F]) —
    # the TPU expression of DataPartition + smaller-child scheduling
    # (ref: serial_tree_learner.cpp:368-386, data_partition.hpp:22)
    row_sched: str = "full"
    # compact-mode histogram input dtype: float32 | bfloat16
    hist_dtype: str = "float32"
    # compact-mode histogram kernel: einsum (TPU) | scatter (CPU)
    hist_rm_backend: str = "einsum"
    # level-mode histogram kernel: "" derives from hist_rm_backend
    # (legacy); otherwise scatter | einsum | pallas | pallas_level —
    # the last is the ONE-launch sorted-segment Pallas kernel
    # (ops/hist_level_pallas.py). Resolved by
    # models/gbdt.resolve_level_hist_kernel from tpu_hist_kernel +
    # the tuned cache at the training row count.
    level_hist_backend: str = ""
    # compact-mode segment partition primitive: scatter | sort
    partition_mode: str = "scatter"
    # smallest pow2 segment bucket (smaller leaves pad up to this)
    min_bucket: int = 2048
    # histogram memory policy: "full" keeps the [L, F, B, 3] per-leaf pool
    # (sibling subtraction, fastest); "none" keeps NO pool and computes
    # both children's histograms per split from their gathered rows —
    # O(F*B) memory so wide data (Allstate-class F) fits HBM; "bounded"
    # keeps a [pool_slots, F, B, 3] LRU pool — cached parents use the
    # subtraction trick, evicted parents recompute both children
    # (recompute-on-miss). The XLA answers to the reference's
    # histogram_pool_size-capped LRU HistogramPool
    # (ref: feature_histogram.hpp:1368, serial_tree_learner.cpp:144-165).
    # "none"/"bounded" require row_sched="compact"; forced splits and
    # refined monotone modes need the full pool.
    hist_pool: str = "full"
    # slot count for hist_pool="bounded" (>= 2)
    pool_slots: int = 0
    # quantized-gradient training (ref: gradient_discretizer.{hpp,cpp},
    # config use_quantized_grad): int8 grad/hess with stochastic rounding,
    # EXACT int32 histogram accumulation on the MXU — deterministic sums
    # regardless of reduction order (the "bit-identical splits" path) and
    # 2x the bf16 matmul rate. Per-leaf 8/16-bit histogram narrowing is a
    # CPU cache optimization with no TPU analogue (int32 is the MXU
    # accumulator width) and is deliberately not carried over.
    quantized: bool = False
    quant_bins: int = 4          # ref: num_grad_quant_bins
    stochastic_rounding: bool = True
    # extremely randomized trees (ref: config extra_trees / extra_seed;
    # feature_histogram.hpp USE_RAND): one random numerical threshold per
    # (node, feature) instead of the full scan
    extra_trees: bool = False
    # monotone constraint method (ref: config monotone_constraints_method;
    # monotone_constraints.hpp BasicLeafConstraints:466 /
    # IntermediateLeafConstraints:517). "basic" bounds children by the
    # split mid-point; "intermediate" bounds them by the sibling outputs
    # AND tightens other contiguous leaves. The reference's recursive
    # GoUp/GoDownToFindLeavesToUpdate tree walk is re-derived here as
    # vectorized feature-space geometry: each leaf carries its bin
    # hyper-rectangle [L, F, 2]; "contiguous" = overlapping in every
    # non-split feature; affected leaves are found with one [L] mask and
    # re-scanned under a lax.cond only when a bound actually tightened.
    mc_method: str = "basic"
    # feature_mask is [L, F] with one row per node (feature_fraction_bynode,
    # ref: col_sampler.hpp) instead of a single [F] row for the whole tree
    bynode_mask: bool = False
    # static interaction groups over USED feature indices
    # (ref: col_sampler.hpp interaction_constraints)
    interaction_groups: Optional[tuple] = None
    # >0: compact-mode bins arrive bit-packed — uint32 [R, ceil(F/4)]
    # holding this many logical uint8 columns (little-endian byte k =
    # column 4w+k). TPU gathers cost per ELEMENT, so packing 4 bins per
    # word quarters the per-leaf row-gather cost; the kernel unpacks with
    # shifts in registers after the gather.
    packed_cols: int = 0


# The split loop's fixed per-split cost on TPU is the while-body op count
# (docs/TPU_RUNBOOK.md cost model: each fused kernel dispatch costs ~2 us
# through the tunnel, and the body runs num_leaves-1 times). Per-leaf
# scalars therefore live in PACKED matrices — one fused row write per
# child instead of ~10 separate gather/dynamic-update-slice pairs — and
# the tree is materialized as TreeArrays only after the loop.
#
# stats columns (f32 [L, NS]; ints are exact in f32 below 2^24):
S_SG, S_SH, S_CNT, S_VAL, S_LMIN, S_LMAX, S_DEPTH, S_PARENT, S_ISR, \
    S_NROW = range(10)
NS = 10
# packed SplitRecord columns (f32 [L, NB]; NB = 13 with categoricals)
B_GAIN, B_FEAT, B_THR, B_DL, B_LG, B_LH, B_LC, B_LO, B_RG, B_RH, B_RC, \
    B_RO, B_NCAT = range(13)
# tree internal-node columns (f32 [L-1, NN]; NN = 10 with categoricals)
N_FEAT, N_THR, N_DL, N_GAIN, N_IVAL, N_IWT, N_ICNT, N_LC, N_RC, \
    N_CCNT = range(10)


class GrowState(NamedTuple):
    leaf_id: jnp.ndarray        # i32 [R]
    hist: jnp.ndarray           # f32 [L, F, B, 3]
    # packed per-leaf stats: [L, NS] f32 (columns S_* above) — sums,
    # output, monotone bounds, depth, parent node, is_right, node row
    stats: jnp.ndarray
    # packed per-leaf best split: [L, NB] f32 (columns B_* above)
    best: jnp.ndarray
    # packed internal-node tree rows: [L-1, NN] f32 (columns N_* above)
    node: jnp.ndarray
    num_leaves: jnp.ndarray     # i32
    done: jnp.ndarray           # bool
    # categorical split sets ([L, MAXK] best / [L-1, MAXK] tree), only
    # when the dataset has categorical features
    best_cat: jnp.ndarray = None
    tree_cat: jnp.ndarray = None
    # bool [L, F]: features used on the path from root (interaction
    # constraints); None when constraints are off
    path_mask: jnp.ndarray = None
    # forced-split sequence still on track (ForceSplits abort semantics)
    forced_ok: jnp.ndarray = None  # bool scalar
    # compact row scheduling (row_sched="compact"): rows grouped by leaf
    # (≡ DataPartition::indices_, data_partition.hpp:22)
    order: jnp.ndarray = None       # i32 [R] row ids, leaf-contiguous
    # i32 [L, 2]: (segment start, RAW rows incl. bagged-out riders) per
    # leaf — kept i32 (row offsets exceed f32's 2^24 exact range)
    seg: jnp.ndarray = None
    # intermediate monotone mode: per-leaf bin hyper-rectangle
    leaf_flo: jnp.ndarray = None    # i32 [L, F] inclusive low bin
    leaf_fhi: jnp.ndarray = None    # i32 [L, F] inclusive high bin
    # hist-dtype [L, 3]: per-leaf LOCAL (shard) gh sums — tracked only
    # when the histogram pool is LOCAL (voting learner), where the
    # global sums in the split records cannot stand in for shard totals
    # (the vote ranks by LOCAL gain; multival/EFB default-bin
    # reconstruction of a LOCAL hist needs LOCAL totals)
    lsum: jnp.ndarray = None
    # bounded LRU pool bookkeeping (hist_pool="bounded"; ≡ the
    # reference's histogram_pool_size LRU, feature_histogram.hpp:1368)
    slot_map: jnp.ndarray = None    # i32 [L] leaf -> pool slot (-1 miss)
    slot_stamp: jnp.ndarray = None  # i32 [P] last-touch step (-1 free)
    slot_owner: jnp.ndarray = None  # i32 [P] owning leaf (-1 free)


def _set(arr, idx, val, cond):
    """arr[idx] = val if cond (guarded functional update)."""
    return arr.at[idx].set(jnp.where(cond, val, arr[idx]))


def _set_rows2(arr, idx_a, idx_b, row_a, row_b, cond, fallback=None):
    """Guarded write of the (parent, new-leaf) row pair as ONE gather +
    ONE scatter instead of two of each — every scatter in the split
    loop's while body is a dispatched kernel on device, and the body op
    count is the fixed per-split cost (docs/TPU_RUNBOOK.md cost model).
    Indices must be distinct (parent != new leaf always holds).
    ``fallback`` overrides the not-cond rows (default: current rows)."""
    idx2 = jnp.stack([idx_a, idx_b])
    upd2 = jnp.stack([row_a, row_b])
    if fallback is None:
        fallback = arr[idx2]
    return arr.at[idx2].set(jnp.where(cond, upd2, fallback))



def _bucket_sizes(num_rows: int, min_bucket: int) -> list:
    """Descending static segment sizes: [R, pow2 < R, ..., min_bucket].

    Dynamic leaf sizes are padded up to the next bucket so every gather /
    partition in the split loop has a static shape; the pow2 ladder bounds
    padding waste at 2x (the XLA answer to LightGBM's exact-size
    DataPartition segments)."""
    sizes = [num_rows]
    p = 1
    while p * 2 < num_rows:
        p *= 2
    while p >= max(min_bucket, 1) and p < num_rows:
        sizes.append(p)
        p //= 2
    return sizes


def quantize_gradients(cfg: GrowerConfig, gh, rng_key,
                       reduce_max: Optional[Callable] = None,
                       localize_key: Optional[Callable] = None):
    """int8 gradient discretization with stochastic rounding
    (ref: GradientDiscretizer::DiscretizeGradients,
    gradient_discretizer.cpp:71-162): scale |g| to
    [-quant_bins/2, quant_bins/2] and h to [0, quant_bins]; the mask
    channel stays exact 0/1. Histogram sums then accumulate EXACTLY in
    int32 and convert back via the returned ``conv``.

    Shared by the sequential grower and the level/hybrid schedulers so
    one tree's quantization is bit-identical wherever its histograms
    are built (the hybrid's level phase and its sequential tail must
    see the SAME int8 rows or the handoff breaks parity).

    Returns ``(gh_int8 [R, 3], conv)`` where ``conv`` maps raw int32
    histogram sums back to f32 through the per-tree scales."""
    if reduce_max is None:
        reduce_max = lambda x: x
    if localize_key is None:
        localize_key = lambda k: k
    g, h, m = gh[:, 0], gh[:, 1], gh[:, 2]
    kq = max(cfg.quant_bins // 2, 1)
    # reduce_max makes the scales global under row sharding so the
    # downstream int32 psum is exact (identity when serial)
    g_scale = jnp.maximum(reduce_max(jnp.max(jnp.abs(g))),
                          1e-30) / kq
    h_scale = jnp.maximum(reduce_max(jnp.max(h)),
                          1e-30) / cfg.quant_bins
    if cfg.stochastic_rounding:
        # localize_key decorrelates the rounding noise across row
        # shards (each row is rounded once, on its owning device)
        kg, kh = jax.random.split(localize_key(
            rng_key if rng_key is not None else jax.random.PRNGKey(0)))
        ug = jax.random.uniform(kg, g.shape, jnp.float32)
        uh = jax.random.uniform(kh, h.shape, jnp.float32)
    else:
        ug = uh = jnp.float32(0.5)
    gq = jnp.trunc(g / g_scale + jnp.where(g >= 0, ug, -ug))
    hq = jnp.trunc(h / h_scale + uh)
    gh_q = jnp.stack([gq, hq, m], axis=1).astype(jnp.int8)
    scale3 = jnp.stack([g_scale, h_scale, jnp.float32(1.0)])
    return gh_q, (lambda hh: hh.astype(jnp.float32) * scale3)


def _feature_meta_scalars(pmeta: FeatureMeta, f):  # jaxlint: disable=JL001
    """(num_bin, missing_type, default_bin) of split feature ``f``.

    jaxlint JL001 suppressed for the whole helper: the np.asarray/int()
    concretization is a TRACE-TIME probe of concrete closure constants,
    guarded by try/except so traced metas fall through to the gather.

    Uniform metas (every feature shares the three values — the dense
    numerical case) fold to static constants so the partition branches
    receive three scalar constants instead of gathers from [F] arrays
    (which cost a broadcast kernel per split in the grower's body)."""
    nb, mt, db = pmeta.num_bin, pmeta.missing_type, pmeta.default_bin
    try:
        nbc, mtc, dbc = np.asarray(nb), np.asarray(mt), np.asarray(db)
        if (nbc.max() == nbc.min() and mtc.max() == mtc.min()
                and dbc.max() == dbc.min()):
            return (jnp.int32(int(nbc[0])), jnp.int32(int(mtc[0])),
                    jnp.int32(int(dbc[0])))
    except Exception:
        pass  # traced metas — gather at runtime
    fs = jnp.maximum(f, 0)
    return (nb[fs], mt[fs], db[fs])


def _go_left_bins(col, thr, dl, f, pmeta: FeatureMeta, num_cat=None,
                  cat_bins=None, fscal=None):
    """Partition direction for a bin column (ref: dense_bin.hpp:317
    SplitInner missing-type dispatch; categorical bitset membership per
    dense_bin.hpp SplitCategoricalInner — bins not in the chosen set,
    including bin 0 (NaN/unseen), go right).

    ``fscal`` optionally carries the split feature's pre-gathered
    (num_bin, missing_type, default_bin) scalars so switch branches
    don't capture the [F] meta arrays as cond operands (each costs a
    broadcast kernel per split in the grower's while body)."""
    if fscal is not None:
        nbin_f, miss_f, dflt_f = fscal
    else:
        nbin_f = pmeta.num_bin[f]
        miss_f = pmeta.missing_type[f]
        dflt_f = pmeta.default_bin[f]
    go_left = col <= thr
    is_nan_bin = (miss_f == 2) & (col == nbin_f - 1)
    is_dflt_bin = (miss_f == 1) & (col == dflt_f)
    go_left = jnp.where(is_nan_bin | is_dflt_bin, dl, go_left)
    if num_cat is not None:
        in_set = jnp.any(col[:, None] == cat_bins[None, :], axis=1)
        go_left = jnp.where(num_cat > 0, in_set, go_left)
    return go_left


def make_tree_grower(cfg: GrowerConfig, meta: FeatureMeta,
                     reduce_hist: Optional[Callable] = None,
                     reduce_sums: Optional[Callable] = None,
                     forced: Optional[tuple] = None,
                     prepare_split_hist: Optional[Callable] = None,
                     select_best: Optional[Callable] = None,
                     scan_window: Optional[Callable] = None,
                     fetch_bin_column: Optional[Callable] = None,
                     partition_meta: Optional[FeatureMeta] = None,
                     bundle=None,
                     reduce_max: Optional[Callable] = None,
                     localize_key: Optional[Callable] = None,
                     prepare_is_pure: bool = False,
                     local_pool: bool = False,
                     mc_rescan_hooks_ok: bool = False,
                     reduce_box: Optional[Callable] = None,
                     localize_feature: Optional[Callable] = None):
    """Build the tree-growing function for a fixed dataset geometry.

    Returns ``grow(bins_t, gh, feature_mask, cegb) -> (TreeArrays, leaf_id)``
    where ``bins_t`` is uint8/uint16 [F, R] and ``gh`` is f32 [R, 3] =
    (grad*m, hess*m, m) with m the bagging/validity mask. ``cegb`` is an
    optional (const [F], per_count [F]) penalty pair — CEGB's DeltaGain as
    penalty[f] = const[f] + per_count[f] * num_data_in_leaf.

    The row axis R is a LAYOUT contract, not a semantic one: callers may
    pad or permute rows freely (mesh padding; sharded ingestion's
    per-process regions, models/gbdt._setup_distributed) as long as
    padded slots carry gh = (0, 0, 0) — zero-mass rows are invisible to
    histograms, root sums and counts (exactly so under quantized int32
    accumulation; to f32 reduction order otherwise), and ``leaf_id`` is
    returned in whatever row order ``bins_t``/``gh`` used.

    ``forced`` bakes a forced-split prefix into the program
    (ref: SerialTreeLearner::ForceSplits serial_tree_learner.cpp:560):
    (active [L-1] bool, slot [L-1], feature [L-1], threshold_bin [L-1])
    numpy arrays; step i with active[i] splits leaf slot[i] at the given
    (feature, threshold) instead of the best-gain leaf. A forced split whose
    net gain is not positive aborts the remaining forced prefix and normal
    best-first growth takes over (abort_last_forced_split semantics).

    Distributed-learner hooks (SURVEY.md §2.3 strategies):
    - reduce_hist(h, ctx): applied to the freshly built (smaller-child)
      histogram before it enters the pool. Data-parallel psums here so
      the pool holds GLOBAL hists and sibling subtraction needs no comm
      (≡ ReduceScatter, data_parallel_tree_learner.cpp:285). Voting keeps
      it identity so the pool stays LOCAL (≡ voting learner's local
      smaller/larger arrays + local Subtract).
    - prepare_split_hist(h, ctx) -> (h', extra_feature_mask|None): applied
      per child right before the split scan. Voting does its vote +
      selective psum here (≡ GlobalVoting + CopyLocalHistogram +
      ReduceScatter of selected features).
    - select_best(rec) -> rec: cross-device winner selection
      (≡ SyncUpGlobalBestSplit, parallel_tree_learner.h:210) — used by the
      feature-parallel learner, where each device scans its feature slice.
    - scan_window(hist, ctx, feature_mask, gain_penalty, rand_u) ->
      (hist_w, meta_w, fids, fm_w, gp_w, rand_w): feature-sharded split
      scanning (tpu_hist_reduce=reduce_scatter, ≡ the owned-feature scan
      after Network::ReduceScatter). The hook maps the per-leaf histogram
      plus the per-feature vectors into THIS device's feature window with
      globally-correct ids; the scan then runs on the window and
      ``select_best`` combines the per-device winners. Replaces
      prepare_split_hist in the scan path (the two do not compose).
      Numerical dense only: no categorical/EFB/multival/forced/monotone —
      callers fall back to the allreduce contract for those.
    - fetch_bin_column(bins_t, f) -> [R] i32: the split feature's bin
      column for partitioning; feature-parallel broadcasts the owner's
      column. ``partition_meta`` is the GLOBAL FeatureMeta used for the
      partition direction rules when ``meta`` is a sharded slice.
    ctx is (sum_g, sum_h, count, output) of the leaf the histogram
    belongs to.
    """
    hp = cfg.hparams
    L = cfg.num_leaves
    B = cfg.num_bin
    hist_fn = make_hist_fn(cfg.hist_backend, B, cfg.block_rows)
    compact = cfg.row_sched == "compact"
    # multi-value sparse storage: bins are a SparseBins [R, K] pytree;
    # histograms scatter only stored nonzeros (O(rows*K)) and compact
    # gathers its leaf segments from the same layout
    mv_mode = cfg.hist_backend == "multival"
    if compact:
        if mv_mode:
            from ..ops.hist_multival import hist_multival as _hist_mv

            def hist_rm(sb, ghv):
                return _hist_mv(sb, ghv, B)
        else:
            hist_rm = functools.partial(hist_rowmajor, num_bin=B,
                                        block_rows=cfg.block_rows,
                                        dtype=cfg.hist_dtype,
                                        backend=cfg.hist_rm_backend)
    # Distributed mode: collectives (psum over the mesh's data axis) must
    # not sit inside divergent control flow. In full mode the per-split
    # histogram pass is masked instead of branched; in compact mode the
    # partition/gather/hist inside the cond are LOCAL-only (the reduce is
    # applied to the cond's result), and the predicate is replicated —
    # every device computes the identical best split from the reduced
    # histograms, so the branch is uniform across the mesh.
    distributed = reduce_hist is not None
    # "pure" prepare hooks (multival's default-bin fix) are plain local
    # transforms, safe to re-apply in the refined-monotone rescan;
    # voting's vote/psum and feature-parallel's select are not
    has_scan_hooks = ((prepare_split_hist is not None and
                       not prepare_is_pure) or
                      select_best is not None or
                      scan_window is not None)
    # feature-sharded layout (feature-parallel): bins hold a LOCAL column
    # slice; the partition column comes from the owner via the
    # fetch_bin_column hook (one [R] psum per split, outside control flow)
    feat_sharded = fetch_bin_column is not None
    quantized = cfg.quantized
    # Quantized + distributed (≡ the reference's int-histogram
    # ReduceScatter variants, data_parallel_tree_learner.cpp:285-299):
    # the discretization scales are made GLOBAL via reduce_max (pmax over
    # the data axis), so every device quantizes with identical scales and
    # the int32 histogram psum accumulates exactly — the deterministic
    # bit-identical-splits path survives sharding.
    hist_dtype = jnp.int32 if quantized else jnp.float32
    has_cat = meta_has_categorical(meta)
    if scan_window is not None:
        # the reduce-scatter scan contract (models/gbdt resolves
        # ineligible configs back to allreduce BEFORE building; these
        # raises keep direct grower users honest)
        if select_best is None:
            raise ValueError("scan_window needs a select_best combine "
                             "(the per-device winners must be merged)")
        if has_cat or bundle is not None or mv_mode or \
                fetch_bin_column is not None or forced is not None or \
                meta.monotone is not None or prepare_split_hist is not None:
            raise ValueError(
                "scan_window (tpu_hist_reduce=reduce_scatter) supports "
                "dense numerical features without EFB bundles, multival "
                "storage, feature sharding, forced splits, monotone "
                "constraints or a prepare hook — resolve those configs "
                "to the allreduce contract instead")
    MAXK = min(hp.max_cat_threshold, B) if has_cat else 0
    NB = 13 if has_cat else 12
    NN = 10 if has_cat else 9

    def pack_rec(rec: SplitRecord) -> jnp.ndarray:
        """SplitRecord (any leading shape) -> packed f32 [..., NB]
        (ops/split.py pack_record_rows — the layout shared with the
        level/hybrid schedulers' GrowState handoff)."""
        return pack_record_rows(rec, has_cat)

    def unpack_rec(v: jnp.ndarray, cat_bins=None) -> SplitRecord:
        """Packed f32 [..., NB] -> SplitRecord (integer fields restored)."""
        i32 = lambda x: x.astype(jnp.int32)
        return SplitRecord(
            gain=v[..., B_GAIN], feature=i32(v[..., B_FEAT]),
            threshold=i32(v[..., B_THR]), default_left=v[..., B_DL] > 0.5,
            left_sum_gradient=v[..., B_LG], left_sum_hessian=v[..., B_LH],
            left_count=v[..., B_LC], left_output=v[..., B_LO],
            right_sum_gradient=v[..., B_RG], right_sum_hessian=v[..., B_RH],
            right_count=v[..., B_RC], right_output=v[..., B_RO],
            num_cat=i32(v[..., B_NCAT]) if has_cat else None,
            cat_bins=cat_bins)
    pool_none = cfg.hist_pool == "none"
    pool_bounded = cfg.hist_pool == "bounded"
    P_slots = max(int(cfg.pool_slots), 2) if pool_bounded else 0
    if (pool_none or pool_bounded) and not compact:
        raise ValueError(f"hist_pool={cfg.hist_pool!r} requires "
                         "row_sched='compact'")
    if pool_bounded and (reduce_hist is not None or
                         prepare_split_hist is not None or
                         select_best is not None or
                         fetch_bin_column is not None):
        # the miss/hit lax.cond would put collectives inside divergent
        # control flow; the LRU cap is a single-machine memory concern
        # (like the reference's) — distributed learners shard memory
        # pressure instead
        raise ValueError("hist_pool='bounded' supports the serial "
                         "learner only")
    if local_pool and mv_mode and not compact:
        # full-mode multival histograms omit default-bin mass, so leaf
        # totals cannot be read off feature 0's bins (the full-mode
        # local-sums shortcut); the compact path carries raw gh totals
        raise ValueError("tree_learner=voting with multi-value sparse "
                         "storage requires row_sched='compact'")
    if (pool_none or pool_bounded) and forced is not None:
        raise ValueError("forced splits need the full histogram pool; "
                         "use hist_pool='full'")

    # EFB (ref: dataset.cpp FindGroups/FastFeatureBundling + FixHistogram):
    # histograms are built over PHYSICAL bundled columns and expanded to
    # logical features at scan time; the default bin is reconstructed from
    # the leaf totals.
    bundled = bundle is not None
    if bundled:
        # EFB composes with data-parallel (group hists psum across row
        # shards; the scan-time expansion is replicated), with voting
        # via the local-sums channel (local_pool: expansion uses LOCAL
        # leaf totals, so the vote ranks correct local logical hists),
        # and with feature-parallel (feat_sharded: the bundle arrives
        # as the shard's LOCAL group layout and the partition column is
        # owner-decoded inside fetch_bin_column, so no global decode
        # happens here).
        # only an impure PREPARE hook (voting's vote/psum over LOCAL
        # hists) needs the local-sums channel; select_best merges after
        # the scan and is layout-agnostic (feature-parallel's rows are
        # replicated, so its pool holds GLOBAL sums)
        if (prepare_split_hist is not None and not prepare_is_pure and
                not local_pool):
            raise ValueError("EFB bundling with an impure scan hook "
                             "needs the local-sums channel "
                             "(local_pool=True)")
        from ..io.bundling import make_expand_hist
        b_group = jnp.asarray(bundle["group"], jnp.int32)         # [F]
        b_offset = jnp.asarray(bundle["offset"], jnp.int32)       # [F]
        b_default = jnp.asarray(bundle["default_bin"], jnp.int32)  # [F]
        b_nbin = jnp.asarray(bundle["num_bin"], jnp.int32)        # [F]
        # [G, B, 3] group hist -> [F, B, 3] logical (FixHistogram);
        # shared with the level/hybrid schedulers (io/bundling.py)
        expand_hist = make_expand_hist(bundle)

        def decode_bin(col_phys, f):
            """Physical group column -> logical bin of feature f."""
            from ..io.bundling import decode_logical_bin
            return decode_logical_bin(col_phys, b_offset[f], b_nbin[f],
                                      b_default[f])
    if reduce_hist is None:
        reduce_hist = lambda h, ctx=None: h
    if reduce_sums is None:
        reduce_sums = lambda s: s
    if reduce_max is None:
        reduce_max = lambda x: x
    if localize_key is None:
        localize_key = lambda k: k
    if prepare_split_hist is None:
        prepare_split_hist = lambda h, ctx=None, fm=None: (h, None)
    # serial + numerical-only: children's best rows are packed inside
    # the split selection (vector pieces), not via pack_rec's scalar
    # stack — see best_of(want_row=...)
    packed_best_rows = select_best is None and not has_cat
    if select_best is None:
        select_best = lambda rec: rec
    if fetch_bin_column is None:
        fetch_bin_column = lambda bt, f: jnp.take(
            bt, jnp.maximum(f, 0), axis=0).astype(jnp.int32)
    pmeta = partition_meta if partition_meta is not None else meta

    use_mc = meta.monotone is not None
    # intermediate machinery (leaf boxes + contiguous-leaf tightening +
    # gated rescan) underpins BOTH refined modes; advanced additionally
    # recomputes child bounds from geometry at split time
    use_mc_inter = use_mc and cfg.mc_method in ("intermediate", "advanced")
    use_mc_adv = use_mc and cfg.mc_method == "advanced"
    if use_mc_inter:
        if pool_none or pool_bounded:
            raise ValueError("monotone_constraints_method=intermediate "
                             "re-scans affected leaves from the histogram "
                             "pool; use hist_pool='full'")
        if cfg.extra_trees:
            raise ValueError("monotone_constraints_method=intermediate "
                             "does not compose with extra_trees")
        if has_scan_hooks and not mc_rescan_hooks_ok:
            # the rescan re-applies the scan hooks under a lax.cond; a
            # learner opts in when (a) its hooks are sound to re-apply
            # and (b) the cond predicate is REPLICATED across the mesh,
            # so its collectives execute uniformly. Voting and
            # feature-parallel both opt in (feature-parallel also
            # supplies reduce_box/localize_feature for the sharded box
            # geometry); the only path left here is the bundled feature
            # learner, whose EFB group layout permutes features across
            # shards in a way the box psum cannot follow.
            raise ValueError("refined monotone constraints do not "
                             "compose with tree_learner=feature + EFB "
                             "bundling; use "
                             "monotone_constraints_method='basic'")
    use_ic = cfg.interaction_groups is not None
    # NOTE (measured, don't redo): redirecting dead-step pair writes to
    # scratch rows (to drop the _set_rows2 fallback gather + select) was
    # tried and REVERTED — XLA already fuses the guarded write into one
    # gather-select-scatter kernel, so the redirect's extra index selects
    # grew the while body from 79 to 81 instrs.
    if forced is not None:
        forced_active = jnp.asarray(forced[0], bool)
        forced_slot = jnp.asarray(forced[1], jnp.int32)
        forced_feat = jnp.asarray(forced[2], jnp.int32)
        forced_thr = jnp.asarray(forced[3], jnp.int32)

    def leaf_hist(bins_t, gh, leaf_id, target_leaf, ctx=None):
        mask = (leaf_id == target_leaf).astype(gh.dtype)
        return reduce_hist(hist_fn(bins_t, gh * mask[:, None]), ctx)

    # extra_trees composes with the row-sharded learners: the random
    # thresholds derive from the REPLICATED per-tree key, so every device
    # draws identical uniforms and selects the identical split.
    use_rand = cfg.extra_trees

    def rand_uniforms(key):
        """One uniform draw per feature — the split scan derives the
        random numerical threshold / categorical candidate from it
        (ref: meta_->rand draws, feature_histogram.hpp:205)."""
        return jax.random.uniform(key, (int(meta.num_bin.shape[0]),))

    def best_of(hist, sg, sh, cnt, parent_out, feature_mask,
                leaf_range=None, leaf_depth=None, cegb=None,
                rand_u=None, lsum3=None, want_row=False):
        ctx = (sg, sh, cnt, parent_out)
        if lsum3 is not None:
            # local-sums channel (voting): ctx grows to 7 entries —
            # (global sg/sh/cnt/out, LOCAL sg/sh/cnt)
            ctx = ctx + (lsum3[0], lsum3[1], lsum3[2])
        gp = None if cegb is None else cegb[0] + cegb[1] * cnt
        if scan_window is not None:
            # feature-sharded scan (reduce_scatter): the hook windows the
            # histogram/masks/penalties with globally-correct ids; the
            # combine below merges the per-device winners into the one
            # replicated record every device applies (≡ owned-feature
            # FindBestSplits + SyncUpGlobalBestSplit)
            hist_w, meta_w, fids, fm_w, gp_w, rand_w = scan_window(
                hist, ctx, feature_mask, gp, rand_u)
            out = best_split_for_leaf(
                hist_w, sg, sh, cnt, parent_out, meta_w, hp, fm_w,
                leaf_range=leaf_range, leaf_depth=leaf_depth,
                gain_penalty=gp_w, rand_u=rand_w, feature_ids=fids)
            return select_best(out)
        hist, extra_mask = prepare_split_hist(hist, ctx, feature_mask)
        if extra_mask is not None:
            feature_mask = (extra_mask if feature_mask is None
                            else feature_mask & extra_mask)
        out = best_split_for_leaf(hist, sg, sh, cnt, parent_out, meta, hp,
                                  feature_mask, leaf_range=leaf_range,
                                  leaf_depth=leaf_depth, gain_penalty=gp,
                                  rand_u=rand_u, want_row=want_row)
        if want_row:
            return out[1]
        return select_best(out)

    def grow(bins_t: jnp.ndarray, gh: jnp.ndarray,
             feature_mask: Optional[jnp.ndarray] = None,
             cegb: Optional[tuple] = None,
             rng_key: Optional[jnp.ndarray] = None,
             init: Optional[tuple] = None
             ) -> Tuple[TreeArrays, jnp.ndarray]:
        # ``init`` (hybrid level+tail growth, core/hybrid_grower.py):
        # a ``(GrowState, start_step)`` pair replacing the root
        # initialization — the loop resumes at traced step
        # ``start_step`` with a state the level phase committed. The
        # python-level branch specializes the trace; the normal path
        # compiles exactly as before.
        # full mode takes feature-major [F, R] bins; compact mode takes
        # ROW-major [R, F] (the gather-friendly layout). With EFB the
        # stored columns are PHYSICAL bundles (Fp) while masks/paths/the
        # split scan stay per LOGICAL feature (F). SparseBins reports
        # (F, R) in either mode (its layout is row-major by nature).
        packed = compact and not mv_mode and cfg.packed_cols > 0
        if mv_mode or not compact:
            Fp, R = bins_t.shape
        elif packed:
            R, Wp = bins_t.shape
            Fp = cfg.packed_cols
        else:
            R, Fp = bins_t.shape
        F = int(meta.num_bin.shape[0]) if bundled else Fp

        if quantized:
            gh, conv = quantize_gradients(cfg, gh, rng_key,
                                          reduce_max=reduce_max,
                                          localize_key=localize_key)
        else:
            conv = lambda hh: hh

        if compact:
            sizes = _bucket_sizes(R, cfg.min_bucket)
            sizes_arr = jnp.asarray(sizes, jnp.int32)
            # feat_sharded/multival partitions read the fetched column
            # vector instead of the bins matrix
            flat_ok = (R * (Wp if packed else Fp) < 2 ** 31
                       and not feat_sharded)
            bins_flat = bins_t.reshape(-1) if flat_ok else None

            def unpack_rows(w):
                """uint32 [S, Wp] packed words -> int32 [S, Fp] bins."""
                parts = [(w >> w.dtype.type(8 * k)) & w.dtype.type(0xFF)
                         for k in range(4)]
                return jnp.stack(parts, axis=2).reshape(
                    w.shape[0], Wp * 4)[:, :Fp].astype(jnp.int32)

            def bucket_branch(n):
                """Index of the smallest bucket >= n (descending sizes)."""
                return (jnp.sum(sizes_arr >= n) - 1).astype(jnp.int32)

            def make_part(P):
                def part(order, start, rows, f, thr, dl, ncat, cbins,
                         colv, fscal):
                    """Stable two-way partition of the leaf's segment
                    (≡ DataPartition::Split, data_partition.hpp:102).
                    ``colv`` is the replicated [R] global bin column of the
                    split feature when features are sharded (gathered once
                    per split via fetch_bin_column), else a dummy."""
                    f = jnp.maximum(f, 0)
                    start_c = jnp.clip(start, 0, max(R - P, 0))
                    delta = start - start_c
                    seg = lax.dynamic_slice(order, (start_c,), (P,))
                    if feat_sharded:
                        col = jnp.take(colv, seg).astype(jnp.int32)
                    else:
                        col_idx = b_group[f] if bundled else f
                        if packed:
                            word_i = col_idx // 4
                            shift = 8 * (col_idx % 4)
                            if flat_ok:
                                w = bins_flat[seg * Wp + word_i]
                            else:
                                w = jnp.take(
                                    jnp.take(bins_t, seg, axis=0),
                                    word_i, axis=1)
                            col = ((w >> shift.astype(w.dtype)) &
                                   w.dtype.type(0xFF)).astype(jnp.int32)
                        elif flat_ok:
                            col = bins_flat[seg * Fp + col_idx].astype(
                                jnp.int32)
                        else:
                            col = jnp.take(jnp.take(bins_t, seg, axis=0),
                                           col_idx, axis=1).astype(jnp.int32)
                        if bundled:
                            col = decode_bin(col, f)
                    go_left = _go_left_bins(
                        col, thr, dl, f, pmeta,
                        ncat if has_cat else None,
                        cbins if has_cat else None, fscal=fscal)
                    pos = jnp.arange(P, dtype=jnp.int32)
                    valid = (pos >= delta) & (pos < delta + rows)
                    lm = valid & go_left
                    rmk = valid & ~go_left
                    nL = jnp.sum(lm.astype(jnp.int32))
                    # "auto": per-bucket-size choice — lax.sort wins on
                    # big TPU segments (1.77 vs 5.17 ms at 1M rows) but
                    # its bitonic stages carry a fixed cost that loses to
                    # the cumsum scatter on small buckets
                    use_sort = (cfg.partition_mode == "sort" or
                                (cfg.partition_mode == "auto" and
                                 P >= 32768))
                    if use_sort:
                        key = jnp.where(
                            lm, 1, jnp.where(rmk, 2,
                                             jnp.where(pos < delta, 0, 3))
                        ).astype(jnp.int32)
                        _, new_seg = lax.sort((key, seg), num_keys=1,
                                              is_stable=True)
                    else:
                        dst_l = delta + jnp.cumsum(lm.astype(jnp.int32)) - 1
                        dst_r = (delta + nL +
                                 jnp.cumsum(rmk.astype(jnp.int32)) - 1)
                        dest = jnp.where(lm, dst_l,
                                         jnp.where(rmk, dst_r, pos))
                        new_seg = jnp.zeros_like(seg).at[dest].set(
                            seg, unique_indices=True)
                    order = lax.dynamic_update_slice(order, new_seg,
                                                     (start_c,))
                    return order, nL
                return part

            def make_histb(S):
                def hb(order, start, rows, ghv):
                    """O(rows_in_leaf) histogram over the gathered segment
                    (≡ indexed Bin::ConstructHistogram, dense_bin.hpp;
                    multival: O(rows_in_leaf * K) over stored nonzeros,
                    ≡ multi_val_sparse_bin.hpp ConstructHistogram).
                    With the local-sums channel the segment's raw gh
                    totals ride along (multival hists lack the
                    default-bin mass, so totals can't come from them)."""
                    start_c = jnp.clip(start, 0, max(R - S, 0))
                    delta = start - start_c
                    idx = lax.dynamic_slice(order, (start_c,), (S,))
                    if mv_mode:
                        from ..ops.hist_multival import take_rows
                        blk = take_rows(bins_t, idx)
                    elif packed:
                        # gather packed words (4x fewer elements), unpack
                        # with shifts after the gather
                        blk = unpack_rows(jnp.take(bins_t, idx, axis=0))
                    else:
                        blk = jnp.take(bins_t, idx, axis=0)
                    ghg = jnp.take(ghv, idx, axis=0)
                    pos = jnp.arange(S, dtype=jnp.int32)
                    w = ((pos >= delta) &
                         (pos < delta + rows)).astype(ghg.dtype)
                    ghw = ghg * w[:, None]
                    h = hist_rm(blk, ghw)
                    if local_pool:
                        return h, jnp.sum(ghw.astype(hist_dtype), axis=0)
                    return h
                return hb

            part_branches = [make_part(P) for P in sizes]
            hist_branches = [make_histb(S) for S in sizes]

        if use_ic:
            # bool [G, F]: membership of each interaction group
            gm = np.zeros((len(cfg.interaction_groups), F), bool)
            for gi, group in enumerate(cfg.interaction_groups):
                for fi in group:
                    if 0 <= fi < F:
                        gm[gi, fi] = True
            group_masks = jnp.asarray(gm)

            def allowed_features(path):
                """Union of groups that contain every path feature
                (ref: col_sampler.hpp interaction-constraint filtering)."""
                contains = jnp.all(group_masks | ~path[None, :], axis=1)
                return jnp.any(group_masks & contains[:, None], axis=0)

        def node_mask(node_row, path):
            """Mask for one node: row `node_row` of the per-node sample
            (root=0, step i children = 2i+1 / 2i+2) ∧ interaction filter."""
            fm = feature_mask
            if cfg.bynode_mask and fm is not None:
                fm = fm[jnp.minimum(node_row, fm.shape[0] - 1)]
            if use_ic:
                al = allowed_features(path)
                fm = al if fm is None else (fm & al)
            return fm

        inf = jnp.float32(jnp.inf)
        if use_rand:
            et_key = jax.random.fold_in(
                rng_key if rng_key is not None else jax.random.PRNGKey(0),
                7919)
        if init is not None:
            # hybrid handoff: the level phase committed `start_step`
            # splits; resume the sequential loop from its state
            state, start_step = init
        else:
            start_step = 0
            # ---- root (ref: LeafSplits::Init + first FindBestSplits) ----
            if quantized:
                local_root = gh.sum(axis=0, dtype=jnp.int32)
                sums = conv(reduce_sums(local_root))
            else:
                local_root = gh.sum(axis=0)               # [3] LOCAL
                sums = reduce_sums(local_root)            # [3] global
            root_g, root_h, root_c = sums[0], sums[1], sums[2]
            root_out = calculate_splitted_leaf_output(
                root_g, root_h + 2 * K_EPSILON, hp, root_c, jnp.float32(0.0))
            leaf_id0 = jnp.zeros(R, jnp.int32)
            if compact:
                root_bins = unpack_rows(bins_t) if packed else bins_t
                hist_root = reduce_hist(hist_rm(root_bins, gh),
                                        (root_g, root_h, root_c, root_out))
            else:
                hist_root = reduce_hist(hist_fn(bins_t, gh),
                                        (root_g, root_h, root_c, root_out))
            root_path = jnp.zeros(F, bool)
            hist_root_l = conv(hist_root)
            root_lsum = conv(local_root.astype(hist_dtype)) if local_pool \
                else None
            if bundled:
                # a LOCAL pool expands with LOCAL totals (the default-bin
                # mass of this shard's rows), global pools with global
                if local_pool:
                    hist_root_l = expand_hist(hist_root_l, root_lsum[0],
                                              root_lsum[1], root_lsum[2])
                else:
                    hist_root_l = expand_hist(hist_root_l, root_g, root_h,
                                              root_c)
            if use_rand:
                root_rand = rand_uniforms(jax.random.fold_in(et_key, 2 ** 20))
            else:
                root_rand = None
            best_root = best_of(hist_root_l, root_g, root_h, root_c,
                                root_out, node_mask(0, root_path),
                                leaf_range=(-inf, inf),
                                leaf_depth=jnp.int32(0), cegb=cegb,
                                rand_u=root_rand, lsum3=root_lsum)

            # pool slots take the REDUCED root histogram's shape: under
            # reduce_scatter aggregation the pool holds each device's
            # feature WINDOW ([Fp/D, B, 3] — the mesh shards the pool's
            # memory too), under allreduce/serial it stays [Fp, B, 3]
            slot_shape = tuple(hist_root.shape)
            if pool_none:
                hist_pool = None
            elif pool_bounded:
                hist_pool = jnp.zeros((P_slots,) + slot_shape,
                                      hist_dtype).at[0].set(hist_root)
            else:
                hist_pool = jnp.zeros((L,) + slot_shape,
                                      hist_dtype).at[0].set(hist_root)
            stats0 = jnp.zeros((L, NS), jnp.float32)
            stats0 = stats0.at[:, S_LMIN].set(-jnp.inf)
            stats0 = stats0.at[:, S_LMAX].set(jnp.inf)
            stats0 = stats0.at[:, S_PARENT].set(-1.0)
            stats0 = stats0.at[0].set(jnp.stack([
                root_g, root_h, root_c, root_out, -inf, inf,
                jnp.float32(0.0), jnp.float32(-1.0), jnp.float32(0.0),
                jnp.float32(0.0)]))
            inv_row = pack_rec(SplitRecord.invalid((), max_cat=MAXK))
            best0 = jnp.broadcast_to(inv_row, (L, NB)).at[0].set(
                pack_rec(best_root))

            state = GrowState(
                leaf_id=leaf_id0,
                hist=hist_pool,
                stats=stats0,
                best=best0,
                # L-1 internal-node rows + one scratch row (index L-1) that
                # absorbs the parent-pointer write of parentless splits so
                # the body's paired row write always has distinct indices
                node=jnp.zeros((L, NN), jnp.float32),
                num_leaves=jnp.asarray(1, jnp.int32),
                done=jnp.asarray(False),
                best_cat=(jnp.full((L, MAXK), -1, jnp.int32).at[0].set(
                    best_root.cat_bins) if has_cat else None),
                tree_cat=(jnp.full((L - 1, MAXK), -1, jnp.int32)
                          if has_cat else None),
                path_mask=jnp.zeros((L, F), bool) if use_ic else None,
                forced_ok=jnp.asarray(True),
                order=jnp.arange(R, dtype=jnp.int32) if compact else None,
                seg=(jnp.zeros((L, 2), jnp.int32).at[0, 1].set(R)
                     if compact else None),
                lsum=(jnp.zeros((L, 3), hist_dtype).at[0].set(
                    local_root.astype(hist_dtype)) if local_pool else None),
                slot_map=(jnp.full(L, -1, jnp.int32).at[0].set(0)
                          if pool_bounded else None),
                slot_stamp=(jnp.full(P_slots, -1, jnp.int32).at[0].set(0)
                            if pool_bounded else None),
                slot_owner=(jnp.full(P_slots, -1, jnp.int32).at[0].set(0)
                            if pool_bounded else None),
                leaf_flo=(jnp.zeros((L, F), jnp.int32) if use_mc_inter
                          else None),
                leaf_fhi=(jnp.broadcast_to(
                    meta.num_bin.astype(jnp.int32)[None, :] - 1,
                    (L, F)).copy() if use_mc_inter else None),
            )

        def body(i, state: GrowState) -> GrowState:
            # ---- pick best leaf (ref: serial_tree_learner.cpp:229 ArgMax) --
            exists = jnp.arange(L) < state.num_leaves
            if cfg.max_depth > 0:
                exists &= state.stats[:, S_DEPTH] < cfg.max_depth
            cand = jnp.where(exists, state.best[:, B_GAIN], K_MIN_SCORE)
            l = jnp.argmax(cand).astype(jnp.int32)
            gain = cand[l]
            forced_ok = state.forced_ok

            if forced is not None:
                # forced-prefix step: split forced_slot[i] at the given
                # (feature, threshold) if its net gain is positive;
                # otherwise abort the rest of the forced prefix and fall
                # back to the best-gain leaf this very step
                # (ref: serial_tree_learner.cpp ForceSplits + abort path)
                want_forced = forced_active[i] & state.forced_ok
                slot_i = forced_slot[i]
                fs = state.stats[slot_i]
                fhist = conv(state.hist[slot_i])
                if bundled:
                    fhist = expand_hist(fhist, fs[S_SG], fs[S_SH],
                                        fs[S_CNT])
                frec = forced_split_record(
                    fhist, forced_feat[i], forced_thr[i],
                    fs[S_SG], fs[S_SH], fs[S_CNT], fs[S_VAL], meta, hp)
                if has_cat:  # forced splits are numerical-only
                    frec = frec._replace(
                        num_cat=jnp.int32(0),
                        cat_bins=jnp.full((MAXK,), -1, jnp.int32))
                f_valid = frec.gain > 0.0
                if cfg.max_depth > 0:  # forced prefix honors max_depth too
                    f_valid &= fs[S_DEPTH] < cfg.max_depth
                apply_forced = want_forced & f_valid
                forced_ok = state.forced_ok & (~want_forced | f_valid)
                l = jnp.where(apply_forced, slot_i, l)
                gain = jnp.where(apply_forced, frec.gain, gain)
            # ONE row gather each for the chosen leaf's stats/best — the
            # packed-matrix layout makes every per-leaf scalar read a
            # column of these rows instead of its own gather kernel
            srow = state.stats[l]
            brow = state.best[l]
            bcat = state.best_cat[l] if has_cat else None
            if forced is not None:
                brow = jnp.where(apply_forced, pack_rec(frec), brow)
                if has_cat:
                    bcat = jnp.where(apply_forced, frec.cat_bins, bcat)
            rec = unpack_rec(brow, bcat)

            proceed = jnp.logical_and(~state.done, gain > 0.0)
            done = ~proceed
            new_leaf = i + 1  # deterministic thanks to latched done
            i_f = i.astype(jnp.float32)

            # ---- record split into tree arrays (ref: tree.cpp Tree::Split) --
            # one fused row write; leaf arrays are derived from stats
            # after the loop (leaf_value ≡ the child output stats hold)
            noderow = jnp.stack(
                [brow[B_FEAT], brow[B_THR], brow[B_DL], brow[B_GAIN],
                 srow[S_VAL], srow[S_SH], srow[S_CNT],
                 -(l.astype(jnp.float32) + 1.0),
                 -(new_leaf.astype(jnp.float32) + 1.0)]
                + ([brow[B_NCAT]] if has_cat else []))
            # the new node row and the parent's child-pointer fix-up
            # land as ONE gather + ONE scatter over the row pair. The
            # parent row p < i is never the row being written; with no
            # parent the second write is routed to the scratch row L-1
            # (the node matrix carries one extra never-read row for
            # exactly this), so the pair's indices are always distinct.
            p = srow[S_PARENT].astype(jnp.int32)
            p_safe = jnp.maximum(p, 0)
            has_parent = proceed & (p >= 0)
            isr = srow[S_ISR] > 0.5
            rows_np = state.node[jnp.stack([i, p_safe])]        # [2, NN]
            prow = rows_np[1]
            pr = prow[N_LC:N_LC + 2]
            pr_new = jnp.where(isr, jnp.stack([pr[0], i_f]),
                               jnp.stack([i_f, pr[1]]))
            prow_new = lax.dynamic_update_slice(prow, pr_new,
                                                (jnp.int32(N_LC),))
            p_tgt = jnp.where(has_parent, p_safe, jnp.int32(L - 1))
            node = state.node.at[jnp.stack([i, p_tgt])].set(
                jnp.stack([jnp.where(proceed, noderow, rows_np[0]),
                           prow_new]))
            if has_cat:
                tree_cat = state.tree_cat.at[i].set(
                    jnp.where(proceed, rec.cat_bins, state.tree_cat[i]))
            else:
                tree_cat = None
            nl_new = jnp.where(proceed, new_leaf + 1, state.num_leaves)

            # ---- partition rows (ref: dense_bin.hpp:317 SplitInner) --------
            if compact:
                # segment partition + smaller-child gather happen together
                # below (both need the updated order); leaf_id is rebuilt
                # from the final segments after the loop
                leaf_id = state.leaf_id
            else:
                if bundled and not feat_sharded:
                    fsafe = jnp.maximum(rec.feature, 0)
                    bin_col = decode_bin(
                        fetch_bin_column(bins_t, b_group[fsafe]), fsafe)
                else:
                    # feature-sharded EFB: fetch_bin_column already
                    # returns the owner-decoded LOGICAL column
                    bin_col = fetch_bin_column(bins_t, rec.feature)
                go_left = _go_left_bins(
                    bin_col, rec.threshold, rec.default_left, rec.feature,
                    pmeta, rec.num_cat if has_cat else None,
                    rec.cat_bins if has_cat else None)
                in_leaf = state.leaf_id == l
                leaf_id = jnp.where(proceed & in_leaf & ~go_left,
                                    new_leaf, state.leaf_id)

            # ---- children stats: assembled into two packed rows and
            # written once the monotone bounds below are known
            child_depth = srow[S_DEPTH] + 1.0

            # ---- children histograms: smaller pass + subtraction -----------
            # (ref: serial_tree_learner.cpp:368-386 + FeatureHistogram::Subtract)
            if compact:
                # partition the leaf's segment, then gathered hist passes;
                # the switch picks the static pow2 bucket. With the pool,
                # one O(rows_in_smaller) pass + sibling subtraction; pool
                # "none" gathers BOTH children (O(rows_in_parent) work,
                # O(F*B) memory).
                segrow = state.seg[l]
                start_l = segrow[0]
                rows_l = segrow[1]

                if feat_sharded:
                    # owner-column broadcast OUTSIDE the (uniform) branch
                    # so the collective runs unconditionally every step
                    # (≡ feature_parallel_tree_learner.cpp:62-75)
                    colv = fetch_bin_column(bins_t, rec.feature)
                else:
                    colv = jnp.zeros((1,), jnp.int32)

                # the split feature's meta scalars, gathered at BODY
                # level (outside every cond) so the partition branches
                # don't capture the [F] meta arrays as cond operands —
                # each cost a broadcast kernel per split in the while
                # body. Uniform metas (the dense numerical case) fold
                # to static constants: zero runtime ops.
                fscal = _feature_meta_scalars(pmeta, rec.feature)

                def do_partition():
                    pb = bucket_branch(rows_l)
                    ncat_a = rec.num_cat if has_cat else jnp.int32(0)
                    cbins_a = rec.cat_bins if has_cat else \
                        jnp.full((1,), -1, jnp.int32)
                    return lax.switch(
                        pb, part_branches, state.order, start_l, rows_l,
                        rec.feature, rec.threshold, rec.default_left,
                        ncat_a, cbins_a, colv, fscal)

                def part_and_both():
                    """Partition the leaf and histogram BOTH children
                    (shared by the poolless and bounded-miss paths)."""
                    order2, nL = do_partition()
                    nR = rows_l - nL
                    hl = lax.switch(bucket_branch(nL), hist_branches,
                                    order2, start_l, nL, gh)
                    hr = lax.switch(bucket_branch(nR), hist_branches,
                                    order2, start_l + nL, nR, gh)
                    return order2, nL, hl, hr

                small_ctx = None
                if pool_bounded:
                    # LRU hit: smaller child + sibling subtraction from
                    # the cached parent; miss: recompute BOTH children
                    # (≡ HistogramPool recompute-on-miss,
                    # feature_histogram.hpp:1368)
                    sp = state.slot_map[l]
                    have = sp >= 0
                    hist_parent_b = state.hist[jnp.maximum(sp, 0)]

                    def hit_path():
                        order2, nL = do_partition()
                        nR = rows_l - nL
                        lsm = nL <= nR
                        s_start = start_l + jnp.where(lsm, 0, nL)
                        s_rows = jnp.where(lsm, nL, nR)
                        h = lax.switch(bucket_branch(s_rows),
                                       hist_branches, order2, s_start,
                                       s_rows, gh)
                        large = hist_parent_b - h
                        hl = jnp.where(lsm, h, large)
                        hr = jnp.where(lsm, large, h)
                        return order2, nL, hl, hr

                    miss_path = part_and_both

                    order, nL_raw, hist_left_c, hist_right_c = lax.cond(
                        proceed,
                        lambda: lax.cond(have, hit_path, miss_path),
                        lambda: (state.order, jnp.int32(0),
                                 jnp.zeros((Fp, B, 3), hist_dtype),
                                 jnp.zeros((Fp, B, 3), hist_dtype)))
                    left_smaller = jnp.asarray(True)  # unused downstream
                    hist_small = None
                elif pool_none:
                    def do_part_hist2():
                        order2, nL, hl, hr = part_and_both()
                        if local_pool:
                            return (order2, nL, hl[0], hr[0], hl[1],
                                    hr[1])
                        return order2, nL, hl, hr

                    if local_pool:
                        (order, nL_raw, hist_left_c, hist_right_c,
                         lsum_l_c, lsum_r_c) = lax.cond(
                            proceed, do_part_hist2,
                            lambda: (state.order, jnp.int32(0),
                                     jnp.zeros((Fp, B, 3), hist_dtype),
                                     jnp.zeros((Fp, B, 3), hist_dtype),
                                     jnp.zeros((3,), hist_dtype),
                                     jnp.zeros((3,), hist_dtype)))
                    else:
                        order, nL_raw, hist_left_c, hist_right_c = \
                            lax.cond(
                                proceed, do_part_hist2,
                                lambda: (state.order, jnp.int32(0),
                                         jnp.zeros((Fp, B, 3),
                                                   hist_dtype),
                                         jnp.zeros((Fp, B, 3),
                                                   hist_dtype)))
                    if distributed:
                        # collectives live OUTSIDE the (uniform) branch
                        lctx = (rec.left_sum_gradient, rec.left_sum_hessian,
                                rec.left_count, rec.left_output)
                        rctx = (rec.right_sum_gradient,
                                rec.right_sum_hessian,
                                rec.right_count, rec.right_output)
                        hist_left_c = reduce_hist(hist_left_c, lctx)
                        hist_right_c = reduce_hist(hist_right_c, rctx)
                    left_smaller = jnp.asarray(True)  # unused downstream
                    hist_small = None
                else:
                    if distributed:
                        # the smaller side must be agreed mesh-wide: pick
                        # by the REPLICATED split record's global counts
                        # (local raw segment sizes differ per shard)
                        lsm_global = rec.left_count <= rec.right_count

                    def do_part_hist():
                        order2, nL = do_partition()
                        nR = rows_l - nL
                        # smaller child by RAW rows (locally) or by the
                        # replicated global counts (distributed)
                        lsm = lsm_global if distributed else (nL <= nR)
                        s_start = start_l + jnp.where(lsm, 0, nL)
                        s_rows = jnp.where(lsm, nL, nR)
                        sb = bucket_branch(s_rows)
                        hs = lax.switch(sb, hist_branches, order2,
                                        s_start, s_rows, gh)
                        if local_pool:
                            return (order2, nL, lsm) + hs
                        return order2, nL, lsm, hs

                    if local_pool:
                        (order, nL_raw, left_smaller, hist_small,
                         small_lsum) = lax.cond(
                            proceed, do_part_hist,
                            lambda: (state.order, jnp.int32(0),
                                     jnp.asarray(True),
                                     jnp.zeros((Fp, B, 3), hist_dtype),
                                     jnp.zeros((3,), hist_dtype)))
                    else:
                        order, nL_raw, left_smaller, hist_small = \
                            lax.cond(
                                proceed, do_part_hist,
                                lambda: (state.order, jnp.int32(0),
                                         jnp.asarray(True),
                                         jnp.zeros((Fp, B, 3),
                                                   hist_dtype)))
                    if distributed:
                        pick = lambda a, b: jnp.where(left_smaller, a, b)
                        small_ctx = (pick(rec.left_sum_gradient,
                                          rec.right_sum_gradient),
                                     pick(rec.left_sum_hessian,
                                          rec.right_sum_hessian),
                                     pick(rec.left_count, rec.right_count),
                                     pick(rec.left_output,
                                          rec.right_output))
                        hist_small = reduce_hist(hist_small, small_ctx)
                seg = _set_rows2(
                    state.seg, l, new_leaf,
                    jnp.stack([start_l, nL_raw]),
                    jnp.stack([start_l + nL_raw, rows_l - nL_raw]),
                    proceed)
            else:
                order = state.order
                seg = state.seg
                left_smaller = rec.left_count <= rec.right_count
                small_leaf = jnp.where(left_smaller, l, new_leaf)
                pick = lambda a, b: jnp.where(left_smaller, a, b)
                small_ctx = (pick(rec.left_sum_gradient,
                                  rec.right_sum_gradient),
                             pick(rec.left_sum_hessian,
                                  rec.right_sum_hessian),
                             pick(rec.left_count, rec.right_count),
                             pick(rec.left_output, rec.right_output))
                if distributed:
                    # mask instead of branch: dead steps contribute psum(0)
                    gh_live = gh * proceed.astype(gh.dtype)
                    hist_small = leaf_hist(bins_t, gh_live, leaf_id,
                                           small_leaf, small_ctx)
                else:
                    hist_small = lax.cond(
                        proceed,
                        lambda: leaf_hist(bins_t, gh, leaf_id, small_leaf,
                                          small_ctx),
                        lambda: jnp.zeros((Fp, B, 3), hist_dtype))
                if local_pool:
                    # full mode is dense-only: any feature's bin sums are
                    # the segment's raw gh totals
                    small_lsum = hist_small[0].sum(axis=0)
            if pool_none:
                hist_left, hist_right = hist_left_c, hist_right_c
                hist = None
                slot_map = state.slot_map
                slot_stamp = state.slot_stamp
                slot_owner = state.slot_owner
            elif pool_bounded:
                hist_left, hist_right = hist_left_c, hist_right_c
                # LRU slot assignment: the left child reuses the
                # parent's slot on a hit, else evicts the least-recent
                # slot; the right child evicts the next least-recent.
                # Evicted owners' map entries are invalidated so their
                # future splits take the miss path.
                stamps = state.slot_stamp
                sl = jnp.where(have, jnp.maximum(sp, 0),
                               jnp.argmin(stamps).astype(jnp.int32))
                stamps1 = stamps.at[sl].set(
                    jnp.where(proceed, i, stamps[sl]))
                sr = jnp.argmin(stamps1).astype(jnp.int32)
                own_l = state.slot_owner[sl]
                own_r = state.slot_owner[sr]
                slot_map = state.slot_map
                inv_l = proceed & (own_l >= 0) & (own_l != l)
                ols = jnp.maximum(own_l, 0)
                slot_map = slot_map.at[ols].set(
                    jnp.where(inv_l, -1, slot_map[ols]))
                inv_r = proceed & (own_r >= 0) & (own_r != l)
                ors = jnp.maximum(own_r, 0)
                slot_map = slot_map.at[ors].set(
                    jnp.where(inv_r, -1, slot_map[ors]))
                slot_map = _set(slot_map, l, sl, proceed)
                slot_map = _set(slot_map, new_leaf, sr, proceed)
                slot_stamp = _set(stamps1, sr, i, proceed)
                slot_owner = _set(_set(state.slot_owner, sl, l, proceed),
                                  sr, new_leaf, proceed)
                hist = state.hist.at[sl].set(
                    jnp.where(proceed, hist_left, state.hist[sl]))
                hist = hist.at[sr].set(
                    jnp.where(proceed, hist_right, hist[sr]))
            else:
                slot_map = state.slot_map
                slot_stamp = state.slot_stamp
                slot_owner = state.slot_owner
                hist_parent = state.hist[l]
                hist_large = hist_parent - hist_small
                hist_left = jnp.where(left_smaller, hist_small, hist_large)
                hist_right = jnp.where(left_smaller, hist_large, hist_small)
                # NOTE: an unconditional pair write (no proceed select)
                # was tried here and REVERTED — without the fallback
                # read XLA lost the in-place pattern and double-copied
                # the whole [L, F, B, 3] pool every split (2x 21 MB at
                # the bench geometry); don't redo it.
                hist = _set_rows2(state.hist, l, new_leaf,
                                  hist_left, hist_right, proceed)

            # ---- local-sums channel (voting): children's LOCAL totals --
            if local_pool:
                if pool_none:
                    lsum_lrow, lsum_rrow = lsum_l_c, lsum_r_c
                else:
                    lsum_parent = state.lsum[l]
                    lsum_large = lsum_parent - small_lsum
                    lsum_lrow = jnp.where(left_smaller, small_lsum,
                                          lsum_large)
                    lsum_rrow = jnp.where(left_smaller, lsum_large,
                                          small_lsum)
                lsum = _set_rows2(state.lsum, l, new_leaf,
                                  lsum_lrow, lsum_rrow, proceed)
                lsums2 = conv(jnp.stack([lsum_lrow, lsum_rrow]))
            else:
                lsum = state.lsum
                lsums2 = None

            # ---- monotone constraint propagation ---------------------------
            # (ref: monotone_constraints.hpp:488-504 BasicLeafConstraints::
            # Update — mid-point bound tightening on the split children;
            # :546 IntermediateLeafConstraints::UpdateConstraintsWithOutputs
            # — sibling-output bounds, looser on the children, with other
            # contiguous leaves tightened below)
            p_min, p_max = srow[S_LMIN], srow[S_LMAX]
            if use_mc:
                mono_f = jnp.where(rec.feature >= 0,
                                   pmeta.monotone[jnp.maximum(rec.feature, 0)],
                                   0)
                is_num = (rec.num_cat == 0) if has_cat else jnp.bool_(True)
                mono_f = jnp.where(is_num, mono_f, 0)
                if use_mc_adv:
                    # advanced: each child's bounds are RECOMPUTED from
                    # the full current-leaf geometry instead of inherited
                    # from the parent's scalars (ref role:
                    # AdvancedLeafConstraints' per-threshold refinement,
                    # monotone_constraints.hpp:859 — a leaf linked to the
                    # parent through the half that became the OTHER child
                    # no longer constrains this one). The pairwise test
                    # below enumerates the complete constraint set, so
                    # direct enforcement stays sound while bounds only
                    # get looser (= more accurate) than intermediate's.
                    # feature-sharded boxes ([L, F_local]): the split
                    # feature's box update happens on the OWNER shard
                    # only; separator counts/selectors reduce below
                    if localize_feature is not None:
                        f_box_a, f_own_a = localize_feature(rec.feature)
                    else:
                        f_box_a, f_own_a = rec.feature, jnp.bool_(True)
                    fsafe_a = jnp.clip(f_box_a, 0, F - 1)
                    upd_ok_a = is_num & f_own_a
                    flo_pa = state.leaf_flo[l]
                    fhi_pa = state.leaf_fhi[l]
                    a_left_fhi = jnp.where(
                        upd_ok_a, fhi_pa.at[fsafe_a].set(rec.threshold),
                        fhi_pa)
                    a_right_flo = jnp.where(
                        upd_ok_a,
                        flo_pa.at[fsafe_a].set(rec.threshold + 1),
                        flo_pa)
                    ac_flo = jnp.stack([flo_pa, a_right_flo])   # [2, F]
                    ac_fhi = jnp.stack([a_left_fhi, fhi_pa])
                    lar_a = jnp.arange(L)
                    exists_j = (lar_a < state.num_leaves) & (lar_a != l)
                    ov_a = ((state.leaf_flo[:, None, :] <=
                             ac_fhi[None, :, :]) &
                            (state.leaf_fhi[:, None, :] >=
                             ac_flo[None, :, :]))
                    n_sep_a = jnp.sum(~ov_a, axis=2)            # [L, 2]
                    sep_a = jnp.argmax(~ov_a, axis=2)
                    # sep is a LOCAL feature index -> LOCAL meta lookup
                    msep_a = meta.monotone[sep_a]
                    linked_a = ((n_sep_a == 1) & (msep_a != 0) &
                                exists_j[:, None])
                    jl = jnp.take_along_axis(state.leaf_flo, sep_a, axis=1)
                    jh = jnp.take_along_axis(state.leaf_fhi, sep_a, axis=1)
                    cl = jnp.take_along_axis(
                        jnp.broadcast_to(ac_flo[None], (L, 2, F)),
                        sep_a[..., None], axis=2)[..., 0]
                    ch = jnp.take_along_axis(
                        jnp.broadcast_to(ac_fhi[None], (L, 2, F)),
                        sep_a[..., None], axis=2)[..., 0]
                    j_below = jh < cl      # j below the child
                    j_above = jl > ch
                    inc_a = msep_a > 0
                    # j ABOVE bounds the child's max when increasing
                    ub_on_c = linked_a & jnp.where(inc_a, j_above, j_below)
                    lb_on_c = linked_a & jnp.where(inc_a, j_below, j_above)
                    if reduce_box is not None:
                        # sharded boxes: a link exists when the GLOBAL
                        # separator count is one; the owning shard's
                        # local selector carries direction/sign
                        one_a = reduce_box(n_sep_a) == 1
                        ub_on_c = one_a & (reduce_box(
                            ub_on_c.astype(jnp.int32)) > 0)
                        lb_on_c = one_a & (reduce_box(
                            lb_on_c.astype(jnp.int32)) > 0)
                    jout = state.stats[:, S_VAL][:, None]
                    geo_max = jnp.min(
                        jnp.where(ub_on_c, jout, jnp.inf), axis=0)  # [2]
                    geo_min = jnp.max(
                        jnp.where(lb_on_c, jout, -jnp.inf), axis=0)
                    base_lmin, base_lmax = geo_min[0], geo_max[0]
                    base_rmin, base_rmax = geo_min[1], geo_max[1]
                else:
                    base_lmin = base_rmin = p_min
                    base_lmax = base_rmax = p_max
                if use_mc_inter:
                    bl = rec.right_output   # left child's bound source
                    br = rec.left_output    # right child's bound source
                else:
                    bl = br = (rec.left_output + rec.right_output) * 0.5
                l_min = jnp.where(mono_f < 0,
                                  jnp.maximum(base_lmin, bl), base_lmin)
                l_max = jnp.where(mono_f > 0,
                                  jnp.minimum(base_lmax, bl), base_lmax)
                r_min = jnp.where(mono_f > 0,
                                  jnp.maximum(base_rmin, br), base_rmin)
                r_max = jnp.where(mono_f < 0,
                                  jnp.minimum(base_rmax, br), base_rmax)
            else:
                l_min = r_min = p_min
                l_max = r_max = p_max

            # ---- write the two children's packed stats rows ---------------
            lrow = jnp.stack([rec.left_sum_gradient, rec.left_sum_hessian,
                              rec.left_count, rec.left_output, l_min,
                              l_max, child_depth, i_f, jnp.float32(0.0),
                              2.0 * i_f + 1.0])
            rrow = jnp.stack([rec.right_sum_gradient,
                              rec.right_sum_hessian, rec.right_count,
                              rec.right_output, r_min, r_max, child_depth,
                              i_f, jnp.float32(1.0), 2.0 * i_f + 2.0])
            stats = _set_rows2(state.stats, l, new_leaf, lrow, rrow,
                               proceed)

            # ---- interaction path bookkeeping ------------------------------
            if use_ic:
                f_onehot = (jnp.arange(F) ==
                            jnp.maximum(rec.feature, 0)) & (rec.feature >= 0)
                child_path = state.path_mask[l] | f_onehot
                path_mask = _set_rows2(state.path_mask, l, new_leaf,
                                       child_path, child_path, proceed)
            else:
                child_path = None
                path_mask = None

            # ---- children best splits --------------------------------------
            # each child gets its own per-node feature sample (rows 2i+1 and
            # 2i+2 — siblings decorrelated, like ColSampler bynode)
            fm_l = node_mask(2 * i + 1, child_path)
            fm_r = node_mask(2 * i + 2, child_path)
            # children totals as one [2, 4] view of the packed best row
            # (columns B_LG..B_RO are [lsg, lsh, lc, lout, rsg, rsh, rc,
            # rout]) — slices fuse where per-field stacks each dispatched
            # a concatenate kernel in the while body
            lr4 = brow[B_LG:B_RO + 1].reshape(2, 4)
            sg2, sh2, cn2 = lr4[:, 0], lr4[:, 1], lr4[:, 2]
            hists2 = conv(jnp.stack([hist_left, hist_right]))
            if bundled:
                if local_pool:
                    # LOCAL pool: default-bin mass reconstructed from
                    # the shard's own totals (local-sums channel)
                    hists2 = jax.vmap(expand_hist)(
                        hists2, lsums2[:, 0], lsums2[:, 1],
                        lsums2[:, 2])
                else:
                    hists2 = jax.vmap(expand_hist)(hists2, sg2, sh2,
                                                   cn2)
            ou2 = lr4[:, 3]
            mn2 = jnp.stack([l_min, r_min])
            mx2 = jnp.stack([l_max, r_max])
            dp2 = jnp.stack([child_depth, child_depth]).astype(jnp.int32)
            if use_rand:
                ki = jax.random.fold_in(et_key, i)
                rb2 = jnp.stack([
                    rand_uniforms(jax.random.fold_in(ki, 1)),
                    rand_uniforms(jax.random.fold_in(ki, 2))])
            else:
                rb2 = None
            # serial numerical path: best_of assembles the packed rows
            # from its vector intermediates (want_row), skipping the
            # 12-operand scalar concatenate pack_rec would dispatch
            pack_inline = packed_best_rows
            if fm_l is None:
                best2 = jax.vmap(
                    lambda hh, a, b, c, d, mn, mx, dp, rb, ls: best_of(
                        hh, a, b, c, d, None, leaf_range=(mn, mx),
                        leaf_depth=dp, cegb=cegb, rand_u=rb, lsum3=ls,
                        want_row=pack_inline)
                )(hists2, sg2, sh2, cn2, ou2, mn2, mx2, dp2, rb2,
                  lsums2)
            else:
                fm2 = jnp.stack([fm_l, fm_r])
                best2 = jax.vmap(
                    lambda hh, a, b, c, d, mn, mx, dp, fm, rb, ls:
                    best_of(
                        hh, a, b, c, d, fm, leaf_range=(mn, mx),
                        leaf_depth=dp, cegb=cegb, rand_u=rb, lsum3=ls,
                        want_row=pack_inline)
                )(hists2, sg2, sh2, cn2, ou2, mn2, mx2, dp2, fm2, rb2,
                  lsums2)
            rows2 = best2 if pack_inline else pack_rec(best2)    # [2, NB]
            # fallback keeps brow/bcat (forced-split overwrites), not
            # the raw state rows
            best = _set_rows2(
                state.best, l, new_leaf, rows2[0], rows2[1], proceed,
                fallback=jnp.stack([brow, state.best[new_leaf]]))
            if has_cat:
                best_cat = _set_rows2(
                    state.best_cat, l, new_leaf,
                    best2.cat_bins[0], best2.cat_bins[1], proceed,
                    fallback=jnp.stack([bcat, state.best_cat[new_leaf]]))
            else:
                best_cat = None

            # ---- intermediate mode: tighten contiguous leaves --------------
            # (ref: monotone_constraints.hpp:625 GoUpToFindLeavesToUpdate /
            # :700 GoDownToFindLeavesToUpdate + serial_tree_learner's
            # re-FindBestSplits over leaves_to_update_). The recursive walk
            # enumerates exactly the leaves whose region overlaps the new
            # children in every non-split feature; here that set comes from
            # one vectorized hyper-rectangle test, and the affected leaves
            # are re-scanned from the (global) histogram pool only when a
            # bound actually tightened.
            if use_mc_inter:
                if localize_feature is not None:
                    f_box, f_own = localize_feature(rec.feature)
                else:
                    f_box, f_own = rec.feature, jnp.bool_(True)
                fsafe = jnp.clip(f_box, 0, F - 1)
                upd_ok = is_num & f_own
                flo_p = state.leaf_flo[l]
                fhi_p = state.leaf_fhi[l]
                left_fhi = jnp.where(upd_ok,
                                     fhi_p.at[fsafe].set(rec.threshold),
                                     fhi_p)
                right_flo = jnp.where(upd_ok,
                                      flo_p.at[fsafe].set(rec.threshold + 1),
                                      flo_p)
                leaf_flo = _set(state.leaf_flo, new_leaf, right_flo, proceed)
                leaf_fhi = _set(_set(state.leaf_fhi, l, left_fhi, proceed),
                                new_leaf, fhi_p, proceed)
                leaf_min = stats[:, S_LMIN]
                leaf_max = stats[:, S_LMAX]

                lar = jnp.arange(L)
                updatable = ((lar < nl_new) & (lar != l) &
                             (lar != new_leaf) &
                             (best[:, B_GAIN] > K_MIN_SCORE))
                # A constraint links leaf j to child c iff exactly ONE
                # feature separates their boxes and that feature is
                # monotone (points can then move between the regions by
                # changing only that feature). This is the same leaf set
                # the reference's GoUp walk reaches: the separating
                # feature is the monotone ancestor split it checks
                # (monotone_constraints.hpp:655 monotone_type != 0), and
                # ShouldKeepGoingLeftRight's threshold pruning is the
                # box-overlap test.
                c_flo = jnp.stack([flo_p, right_flo])       # [2, F]
                c_fhi = jnp.stack([left_fhi, fhi_p])
                c_out = jnp.stack([rec.left_output, rec.right_output])
                ov = ((leaf_flo[:, None, :] <= c_fhi[None, :, :]) &
                      (leaf_fhi[:, None, :] >= c_flo[None, :, :]))
                n_sep = jnp.sum(~ov, axis=2)                # [L, 2]
                sep = jnp.argmax(~ov, axis=2)               # [L, 2]
                msep = meta.monotone[sep]          # LOCAL index lookup
                linked = (n_sep == 1) & (msep != 0)
                j_lo = jnp.take_along_axis(leaf_flo, sep, axis=1)  # [L, 2]
                j_hi = jnp.take_along_axis(leaf_fhi, sep, axis=1)
                c_lo = jnp.take_along_axis(
                    jnp.broadcast_to(c_flo[None], (L, 2, F)),
                    sep[..., None], axis=2)[..., 0]
                c_hi = jnp.take_along_axis(
                    jnp.broadcast_to(c_fhi[None], (L, 2, F)),
                    sep[..., None], axis=2)[..., 0]
                below = j_hi < c_lo                          # [L, 2]
                above = j_lo > c_hi
                inc = msep > 0
                # increasing: j below a child => out_j <= child out (max
                # bound); j above => min bound. Decreasing: mirrored.
                ub_sel = linked & jnp.where(inc, below, above)
                lb_sel = linked & jnp.where(inc, above, below)
                if reduce_box is not None:
                    one_sep = reduce_box(n_sep) == 1
                    ub_sel = one_sep & (reduce_box(
                        ub_sel.astype(jnp.int32)) > 0)
                    lb_sel = one_sep & (reduce_box(
                        lb_sel.astype(jnp.int32)) > 0)
                cand_max = jnp.min(
                    jnp.where(ub_sel, c_out[None, :], jnp.inf), axis=1)
                cand_min = jnp.max(
                    jnp.where(lb_sel, c_out[None, :], -jnp.inf), axis=1)
                okj = proceed & updatable
                nmax = jnp.where(okj, jnp.minimum(leaf_max, cand_max),
                                 leaf_max)
                nmin = jnp.where(okj, jnp.maximum(leaf_min, cand_min),
                                 leaf_min)
                changed = (nmax < leaf_max) | (nmin > leaf_min)
                stats = stats.at[:, S_LMIN].set(nmin)
                stats = stats.at[:, S_LMAX].set(nmax)

                def _rescan(args):
                    best_in, bcat_in = args
                    hp_all = conv(hist)
                    lsums_all = conv(lsum) if local_pool else None
                    if bundled:
                        if local_pool:
                            # LOCAL pool: expand with the shard's totals
                            hp_all = jax.vmap(expand_hist)(
                                hp_all, lsums_all[:, 0],
                                lsums_all[:, 1], lsums_all[:, 2])
                        else:
                            hp_all = jax.vmap(expand_hist)(
                                hp_all, stats[:, S_SG], stats[:, S_SH],
                                stats[:, S_CNT])

                    def one(hh, sg_, sh_, cn_, out_, mn_, mx_, dp_, nrow,
                            pj, ls):
                        fm = feature_mask
                        if cfg.bynode_mask and fm is not None:
                            fm = fm[jnp.minimum(nrow, fm.shape[0] - 1)]
                        if use_ic:
                            al = allowed_features(pj)
                            fm = al if fm is None else fm & al
                        return best_of(hh, sg_, sh_, cn_, out_, fm,
                                       leaf_range=(mn_, mx_),
                                       leaf_depth=dp_, cegb=cegb,
                                       lsum3=ls)

                    pj_arg = (path_mask if use_ic
                              else jnp.zeros((L, 1), bool))
                    new_recs = jax.vmap(one)(
                        hp_all, stats[:, S_SG], stats[:, S_SH],
                        stats[:, S_CNT], stats[:, S_VAL], nmin, nmax,
                        stats[:, S_DEPTH].astype(jnp.int32),
                        stats[:, S_NROW].astype(jnp.int32), pj_arg,
                        lsums_all)
                    bo = jnp.where(changed[:, None], pack_rec(new_recs),
                                   best_in)
                    bc = (jnp.where(changed[:, None], new_recs.cat_bins,
                                    bcat_in) if has_cat else bcat_in)
                    return bo, bc

                best, best_cat = lax.cond(jnp.any(changed), _rescan,
                                          lambda a: a, (best, best_cat))
            else:
                leaf_flo = state.leaf_flo
                leaf_fhi = state.leaf_fhi

            return GrowState(
                leaf_id=leaf_id, hist=hist, stats=stats, best=best,
                node=node, num_leaves=nl_new, done=done | state.done,
                best_cat=best_cat, tree_cat=tree_cat,
                path_mask=path_mask, forced_ok=forced_ok, order=order,
                seg=seg, leaf_flo=leaf_flo, leaf_fhi=leaf_fhi,
                lsum=lsum, slot_map=slot_map, slot_stamp=slot_stamp,
                slot_owner=slot_owner)

        state = lax.fori_loop(start_step, L - 1, body, state)

        # ---- materialize TreeArrays from the packed loop state ----------
        nodem = state.node[:L - 1]   # drop the scratch row
        statm = state.stats
        i32c = lambda c: nodem[:, c].astype(jnp.int32)
        # leaf arrays: every existing leaf's (value, weight, count) are the
        # stats its creating split wrote; a never-split tree keeps the
        # empty() zeros (the reference also emits a zero leaf then)
        grew = state.num_leaves > 1
        tree = TreeArrays(
            split_feature=i32c(N_FEAT),
            threshold_bin=i32c(N_THR),
            default_left=nodem[:, N_DL] > 0.5,
            left_child=i32c(N_LC),
            right_child=i32c(N_RC),
            split_gain=nodem[:, N_GAIN],
            internal_value=nodem[:, N_IVAL],
            internal_weight=nodem[:, N_IWT],
            internal_count=nodem[:, N_ICNT],
            leaf_value=jnp.where(grew, statm[:, S_VAL], 0.0),
            leaf_weight=jnp.where(grew, statm[:, S_SH], 0.0),
            leaf_count=jnp.where(grew, statm[:, S_CNT], 0.0),
            leaf_parent=statm[:, S_PARENT].astype(jnp.int32),
            num_leaves=state.num_leaves,
            shrinkage=jnp.asarray(1.0, jnp.float32),
            cat_count=i32c(N_CCNT) if has_cat else None,
            cat_bins=state.tree_cat,
        )
        if compact:
            # rebuild per-row leaf ids from the final segments: mark each
            # segment start with its leaf, forward-fill along positions,
            # undo the ordering permutation
            lar = jnp.arange(L, dtype=jnp.int32)
            starts = jnp.where((lar < state.num_leaves) &
                               (state.seg[:, 1] > 0), state.seg[:, 0], R)
            marks = jnp.full(R, -1, jnp.int32).at[starts].set(
                lar, mode="drop")
            pos2leaf = lax.associative_scan(
                lambda a, b: jnp.where(b >= 0, b, a), marks)
            leaf_id = jnp.zeros(R, jnp.int32).at[state.order].set(
                pos2leaf, unique_indices=True)
            return tree, leaf_id
        return tree, state.leaf_id

    return grow
