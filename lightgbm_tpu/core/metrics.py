"""Evaluation metrics.

TPU-native equivalent of the reference metric layer
(ref: include/LightGBM/metric.h Metric, src/metric/metric.cpp:26 factory,
regression_metric.hpp, binary_metric.hpp, multiclass_metric.hpp,
rank_metric.hpp, map_metric.hpp, xentropy_metric.hpp, dcg_calculator.cpp).

Metrics run host-side in numpy/f64: they're O(N) once per eval round, far off
the hot path, and f64 accumulation matches the reference's `double` sums.
Each metric returns ``[(name, value, is_higher_better), ...]``.

Score layout convention matches objectives: raw scores [N] or [K, N]
class-major; the metric applies the objective's ConvertOutput-equivalent
transform itself (ref: metrics construct with the objective pointer and call
ConvertOutput, e.g. binary_metric.hpp).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..utils import log
from .objective import default_label_gain

K_EPSILON = 1e-15

MetricResult = List[Tuple[str, float, bool]]


class Metric:
    """Base metric (ref: metric.h)."""

    NAME = "metric"
    HIGHER_BETTER = False

    def __init__(self, config: Config):
        self.config = config
        self.num_data = 0
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self.sum_weights = 0.0

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = (np.asarray(metadata.label, np.float64)
                      if metadata.label is not None else None)
        self.weight = (np.asarray(metadata.weight, np.float64)
                       if metadata.weight is not None else None)
        self.sum_weights = (float(self.weight.sum()) if self.weight is not None
                            else float(num_data))

    def eval(self, score: np.ndarray, objective=None) -> MetricResult:
        raise NotImplementedError

    # ---- device evaluation (async-boosting fast path) ----------------
    # Through a high-latency tunnel, pulling the full [K, N] score to
    # host every eval costs a round-trip plus bandwidth; the common
    # metrics evaluate on device and the engine fetches ONE stacked
    # scalar vector per eval (models/gbdt.py _eval). Metrics without a
    # device path return None and fall back to the host implementation.

    def eval_device(self, score, objective=None):
        """jnp evaluation: list of (name, device_scalar, higher_better)
        or None when no device path applies for this metric/objective."""
        return None

    def _dev_arrays(self):
        """Cached device copies of label/weight."""
        if not hasattr(self, "_dev_cache"):
            import jax.numpy as jnp
            self._dev_cache = (
                jnp.asarray(self.label, jnp.float32)
                if self.label is not None else None,
                jnp.asarray(self.weight, jnp.float32)
                if self.weight is not None else None)
        return self._dev_cache

    def _dev_mean(self, losses, weight_dev):
        import jax.numpy as jnp
        if weight_dev is not None:
            return jnp.sum(losses * weight_dev) / jnp.float32(
                self.sum_weights)
        return jnp.mean(losses)

    @property
    def names(self) -> List[str]:
        return [self.NAME]


def _dev_convert(score, objective):
    """Device counterpart of the objectives' convert_output for the
    transforms the device metrics understand; None = unsupported
    objective (host fallback). Mirrors core/objective.py ConvertOutput
    bodies exactly (sigmoid params, reg_sqrt, exp family)."""
    import jax.numpy as jnp
    if objective is None:
        return score
    name = getattr(objective, "NAME", "")
    if name in ("regression", "regression_l1", "huber", "fair",
                "quantile", "mape"):
        if getattr(objective, "sqrt", False):
            return jnp.sign(score) * score * score
        return score
    if name in ("poisson", "gamma", "tweedie"):
        return jnp.exp(score)
    if name in ("binary",):
        sig = jnp.float32(getattr(objective, "sigmoid", 1.0))
        return 1.0 / (1.0 + jnp.exp(-sig * score))
    if name in ("cross_entropy", "xentropy"):
        return 1.0 / (1.0 + jnp.exp(-score))
    if name in ("cross_entropy_lambda", "xentlambda"):
        return jnp.log1p(jnp.exp(score))
    return None


# ---------------------------------------------------------------------------
# Regression metrics (ref: regression_metric.hpp — average of PointLoss)
# ---------------------------------------------------------------------------

class _PointwiseMetric(Metric):
    """Average pointwise loss with objective transform applied first."""

    def transform(self, score, objective):
        if objective is not None:
            return objective.convert_output(score)
        return score

    def point_loss(self, pred, label):
        raise NotImplementedError

    def eval(self, score, objective=None) -> MetricResult:
        pred = self.transform(np.asarray(score, np.float64), objective)
        losses = self.point_loss(pred, self.label)
        if self.weight is not None:
            value = float(np.sum(losses * self.weight) / self.sum_weights)
        else:
            value = float(np.mean(losses))
        return [(self.NAME, self.finalize(value), self.HIGHER_BETTER)]

    def finalize(self, value: float) -> float:
        return value

    # subclasses with a jnp point loss opt into the device path
    def point_loss_dev(self, pred, label):
        return None

    def finalize_dev(self, value):
        return value

    def transform_dev(self, score, objective):
        return _dev_convert(score, objective)

    def eval_device(self, score, objective=None):
        label, weight = self._dev_arrays()
        if label is None:
            return None
        pred = self.transform_dev(score, objective)
        if pred is None:
            return None
        losses = self.point_loss_dev(pred, label)
        if losses is None:
            return None
        value = self.finalize_dev(self._dev_mean(losses, weight))
        return [(self.NAME, value, self.HIGHER_BETTER)]


class L2Metric(_PointwiseMetric):
    NAME = "l2"

    def point_loss(self, pred, label):
        d = pred - label
        return d * d

    def point_loss_dev(self, pred, label):
        d = pred - label
        return d * d


class RMSEMetric(L2Metric):
    NAME = "rmse"

    def finalize(self, value):
        return math.sqrt(value)

    def finalize_dev(self, value):
        import jax.numpy as jnp
        return jnp.sqrt(value)


class L1Metric(_PointwiseMetric):
    NAME = "l1"

    def point_loss(self, pred, label):
        return np.abs(pred - label)

    def point_loss_dev(self, pred, label):
        import jax.numpy as jnp
        return jnp.abs(pred - label)


class QuantileMetric(_PointwiseMetric):
    NAME = "quantile"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def point_loss(self, pred, label):
        d = label - pred
        return np.where(d >= 0, self.alpha * d, (self.alpha - 1.0) * d)


class HuberMetric(_PointwiseMetric):
    NAME = "huber"

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.alpha)

    def point_loss(self, pred, label):
        d = np.abs(pred - label)
        return np.where(d <= self.alpha, 0.5 * d * d,
                        self.alpha * (d - 0.5 * self.alpha))


class FairMetric(_PointwiseMetric):
    NAME = "fair"

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def point_loss(self, pred, label):
        x = np.abs(pred - label)
        return self.c * x - self.c * self.c * np.log1p(x / self.c)


class PoissonMetric(_PointwiseMetric):
    NAME = "poisson"

    def point_loss(self, pred, label):
        eps = 1e-10
        return pred - label * np.log(np.maximum(pred, eps))


class MAPEMetric(_PointwiseMetric):
    NAME = "mape"

    def point_loss(self, pred, label):
        return np.abs((label - pred) / np.maximum(1.0, np.abs(label)))


class GammaMetric(_PointwiseMetric):
    NAME = "gamma"

    def point_loss(self, pred, label):
        eps = 1e-10
        psi = label / np.maximum(pred, eps)
        theta = -1.0 / np.maximum(pred, eps)
        a = psi + np.log(-1.0 / theta)
        return psi * theta - a  # up to label-only constants (ref: GammaMetric)


class GammaDevianceMetric(_PointwiseMetric):
    NAME = "gamma_deviance"

    def point_loss(self, pred, label):
        eps = 1e-10
        frac = label / np.maximum(pred, eps)
        return 2.0 * (np.log(np.maximum(1.0 / np.maximum(frac, eps), eps) *
                             np.ones_like(frac)) * 0 +
                      (frac - np.log(np.maximum(frac, eps)) - 1.0))

    def eval(self, score, objective=None) -> MetricResult:
        # deviance sums rather than averages (ref: gamma_deviance_metric)
        pred = self.transform(np.asarray(score, np.float64), objective)
        eps = 1e-10
        frac = self.label / np.maximum(pred, eps)
        losses = 2.0 * (frac - np.log(np.maximum(frac, eps)) - 1.0)
        if self.weight is not None:
            value = float(np.sum(losses * self.weight) / self.sum_weights)
        else:
            value = float(np.mean(losses))
        return [(self.NAME, value, self.HIGHER_BETTER)]


class TweedieMetric(_PointwiseMetric):
    NAME = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def point_loss(self, pred, label):
        eps = 1e-10
        p = np.maximum(pred, eps)
        a = label * np.power(p, 1.0 - self.rho) / (1.0 - self.rho)
        b = np.power(p, 2.0 - self.rho) / (2.0 - self.rho)
        return -a + b


class R2Metric(_PointwiseMetric):
    NAME = "r2"
    HIGHER_BETTER = True

    def eval(self, score, objective=None) -> MetricResult:
        pred = self.transform(np.asarray(score, np.float64), objective)
        w = self.weight if self.weight is not None else np.ones(self.num_data)
        ybar = np.sum(self.label * w) / np.sum(w)
        ss_res = np.sum(w * (self.label - pred) ** 2)
        ss_tot = np.sum(w * (self.label - ybar) ** 2)
        value = 1.0 - ss_res / max(ss_tot, K_EPSILON)
        return [(self.NAME, float(value), True)]


# ---------------------------------------------------------------------------
# Binary metrics (ref: binary_metric.hpp)
# ---------------------------------------------------------------------------

class BinaryLoglossMetric(_PointwiseMetric):
    NAME = "binary_logloss"

    def point_loss(self, prob, label):
        eps = K_EPSILON
        p = np.clip(prob, eps, 1.0 - eps)
        return -(label * np.log(p) + (1.0 - label) * np.log(1.0 - p))

    def transform(self, score, objective):
        if objective is not None:
            return objective.convert_output(score)
        return 1.0 / (1.0 + np.exp(-score))

    def transform_dev(self, score, objective):
        if objective is None:
            import jax.numpy as jnp
            return 1.0 / (1.0 + jnp.exp(-score))
        return _dev_convert(score, objective)

    def point_loss_dev(self, prob, label):
        import jax.numpy as jnp
        # f32-representable clip: 1 - 1e-15 rounds to exactly 1.0 in
        # f32, which would turn saturated sigmoids into log(0) = -inf;
        # 1e-7 sits just above the f32 epsilon at 1.0, bounding the
        # device loss at ~16.1 (host f64 bounds at ~34.5)
        eps = jnp.float32(1e-7)
        p = jnp.clip(prob, eps, 1.0 - eps)
        return -(label * jnp.log(p) + (1.0 - label) * jnp.log(1.0 - p))


class BinaryErrorMetric(_PointwiseMetric):
    NAME = "binary_error"

    def transform(self, score, objective):
        if objective is not None:
            return objective.convert_output(score)
        return 1.0 / (1.0 + np.exp(-score))

    def point_loss(self, prob, label):
        pred_pos = prob > 0.5  # threshold on converted output
        actual_pos = label > 0
        return (pred_pos != actual_pos).astype(np.float64)

    def transform_dev(self, score, objective):
        if objective is None:
            import jax.numpy as jnp
            return 1.0 / (1.0 + jnp.exp(-score))
        return _dev_convert(score, objective)

    def point_loss_dev(self, prob, label):
        import jax.numpy as jnp
        return ((prob > 0.5) != (label > 0)).astype(jnp.float32)


def _auc(label_pos: np.ndarray, score: np.ndarray,
         weight: Optional[np.ndarray]) -> float:
    """Weighted AUC with tied-score grouping (ref: binary_metric.hpp:160
    AUCMetric::Eval)."""
    w = weight if weight is not None else np.ones(len(score), np.float64)
    order = np.argsort(score, kind="stable")  # ascending: count neg below pos
    s = score[order]
    pos = label_pos[order].astype(np.float64) * w[order]
    neg = (~label_pos[order]).astype(np.float64) * w[order]
    # group ties: same score => same rank block
    boundary = np.flatnonzero(np.diff(s) != 0)
    idx = np.concatenate([boundary + 1, [len(s)]])
    start = np.concatenate([[0], boundary + 1])
    cum_neg = 0.0
    accum = 0.0
    for a, b in zip(start, idx):
        bp = pos[a:b].sum()
        bn = neg[a:b].sum()
        accum += bp * (cum_neg + bn * 0.5)
        cum_neg += bn
    sum_pos = pos.sum()
    if sum_pos == 0 or cum_neg == 0:
        log.warning("AUC: data contains only one class")
        return 1.0
    return float(accum / (sum_pos * cum_neg))


class AUCMetric(Metric):
    NAME = "auc"
    HIGHER_BETTER = True

    def eval(self, score, objective=None) -> MetricResult:
        return [(self.NAME,
                 _auc(self.label > 0, np.asarray(score, np.float64),
                      self.weight), True)]

    def eval_device(self, score, objective=None):
        # vectorized tie-grouped weighted AUC ≡ _auc: sort ascending,
        # group equal scores (segment ids from boundary cumsum), then
        # accum = Σ_g bp_g · (cum_neg_before_g + bn_g/2)
        import jax
        import jax.numpy as jnp
        label, weight = self._dev_arrays()
        if label is None:
            return None
        n = score.shape[-1]
        if n > (1 << 24):
            # f32 running sums stay EXACT for unweighted counts only up
            # to 2^24; beyond that cumsum silently stops incrementing —
            # fall back to the f64 host path for huge valid sets
            return None
        w = weight if weight is not None else jnp.ones(n, jnp.float32)
        order = jnp.argsort(score)
        s = score[order]
        is_pos = label[order] > 0
        wo = w[order]
        pos = jnp.where(is_pos, wo, 0.0)
        neg = jnp.where(is_pos, 0.0, wo)
        gid = jnp.concatenate([
            jnp.zeros(1, jnp.int32),
            jnp.cumsum((s[1:] != s[:-1]).astype(jnp.int32))])
        bp = jax.ops.segment_sum(pos, gid, num_segments=n)
        bn = jax.ops.segment_sum(neg, gid, num_segments=n)
        cnb = jnp.cumsum(bn) - bn
        accum = jnp.sum(bp * (cnb + 0.5 * bn))
        sp, sn = jnp.sum(pos), jnp.sum(neg)
        auc = jnp.where((sp == 0) | (sn == 0), jnp.float32(1.0),
                        accum / jnp.maximum(sp * sn, K_EPSILON))
        return [(self.NAME, auc, True)]


class AveragePrecisionMetric(Metric):
    """ref: binary_metric.hpp AveragePrecisionMetric."""
    NAME = "average_precision"
    HIGHER_BETTER = True

    def eval(self, score, objective=None) -> MetricResult:
        w = self.weight if self.weight is not None else \
            np.ones(self.num_data, np.float64)
        order = np.argsort(-np.asarray(score, np.float64), kind="stable")
        pos = (self.label[order] > 0).astype(np.float64) * w[order]
        all_w = w[order]
        tp = np.cumsum(pos)
        total = np.cumsum(all_w)
        precision = tp / np.maximum(total, K_EPSILON)
        delta_recall = pos
        sum_pos = pos.sum()
        if sum_pos == 0:
            return [(self.NAME, 1.0, True)]
        ap = float(np.sum(precision * delta_recall) / sum_pos)
        return [(self.NAME, ap, True)]


# ---------------------------------------------------------------------------
# Multiclass metrics (ref: multiclass_metric.hpp)
# ---------------------------------------------------------------------------

class MultiLoglossMetric(Metric):
    NAME = "multi_logloss"

    def eval(self, score, objective=None) -> MetricResult:
        # score [K, N] raw -> per-row softmax prob of the true class
        score = np.asarray(score, np.float64)
        K, N = score.shape
        m = score.max(axis=0, keepdims=True)
        e = np.exp(score - m)
        p = e / e.sum(axis=0, keepdims=True)
        li = self.label.astype(np.int64)
        pt = np.clip(p[li, np.arange(N)], K_EPSILON, 1.0)
        losses = -np.log(pt)
        if self.weight is not None:
            value = float(np.sum(losses * self.weight) / self.sum_weights)
        else:
            value = float(np.mean(losses))
        return [(self.NAME, value, False)]

    def eval_device(self, score, objective=None):
        import jax.numpy as jnp
        label, weight = self._dev_arrays()
        if label is None or score.ndim != 2:
            return None
        n = score.shape[1]
        p = jnp.exp(score - score.max(axis=0, keepdims=True))
        p = p / p.sum(axis=0, keepdims=True)
        pt = jnp.clip(p[label.astype(jnp.int32), jnp.arange(n)],
                      K_EPSILON, 1.0)
        value = self._dev_mean(-jnp.log(pt), weight)
        return [(self.NAME, value, False)]


class MultiErrorMetric(Metric):
    NAME = "multi_error"

    def __init__(self, config):
        super().__init__(config)
        self.top_k = int(config.multi_error_top_k)

    def eval(self, score, objective=None) -> MetricResult:
        score = np.asarray(score, np.float64)
        K, N = score.shape
        li = self.label.astype(np.int64)
        true_score = score[li, np.arange(N)]
        # error if the true class's score is not within the top k
        rank = (score > true_score[None, :]).sum(axis=0)
        # ties: reference counts ties at equal score as within top-k if
        # fewer than k classes are strictly greater
        err = (rank >= self.top_k).astype(np.float64)
        if self.weight is not None:
            value = float(np.sum(err * self.weight) / self.sum_weights)
        else:
            value = float(np.mean(err))
        name = (self.NAME if self.top_k <= 1
                else f"multi_error@{self.top_k}")
        return [(name, value, False)]

    def eval_device(self, score, objective=None):
        import jax.numpy as jnp
        label, weight = self._dev_arrays()
        if label is None or score.ndim != 2:
            return None
        n = score.shape[1]
        li = label.astype(jnp.int32)
        true_score = score[li, jnp.arange(n)]
        rank = (score > true_score[None, :]).sum(axis=0)
        err = (rank >= self.top_k).astype(jnp.float32)
        value = self._dev_mean(err, weight)
        name = (self.NAME if self.top_k <= 1
                else f"multi_error@{self.top_k}")
        return [(name, value, False)]


class AucMuMetric(Metric):
    """Multiclass AUC-mu (ref: multiclass_metric.hpp auc_mu; Kleiman &
    Page 2019): average pairwise class separability."""
    NAME = "auc_mu"
    HIGHER_BETTER = True

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        aw = list(config.auc_mu_weights)
        if aw:
            self.W = np.asarray(aw, np.float64).reshape(
                self.num_class, self.num_class)
        else:
            self.W = np.ones((self.num_class, self.num_class)) - \
                np.eye(self.num_class)

    def eval(self, score, objective=None) -> MetricResult:
        score = np.asarray(score, np.float64)  # [K, N]
        K, N = score.shape
        li = self.label.astype(np.int64)
        w = self.weight if self.weight is not None else np.ones(N)
        total = 0.0
        npairs = 0
        for a in range(K):
            for b in range(a + 1, K):
                mask = (li == a) | (li == b)
                if not mask.any():
                    continue
                # partition by decision value difference weighted by W row
                # (ref uses v = S_a - S_b under weight vector w_{a,b})
                d = score[a, mask] - score[b, mask]
                is_a = li[mask] == a
                if is_a.all() or (~is_a).all():
                    continue
                total += _auc(is_a, d, w[mask])
                npairs += 1
        value = total / max(npairs, 1)
        return [(self.NAME, float(value), True)]


# ---------------------------------------------------------------------------
# Ranking metrics (ref: rank_metric.hpp NDCGMetric, map_metric.hpp)
# ---------------------------------------------------------------------------

class NDCGMetric(Metric):
    NAME = "ndcg"
    HIGHER_BETTER = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]
        lg = list(config.label_gain)
        self.label_gain = (np.asarray(lg, np.float64) if lg
                           else default_label_gain())

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        self.query_boundaries = metadata.query_boundaries
        # per-query weights: metadata weights are per-doc; reference uses
        # query weights — we use uniform query weights
        self.num_queries = len(self.query_boundaries) - 1

    @property
    def names(self):
        return [f"ndcg@{k}" for k in self.eval_at]

    def eval(self, score, objective=None) -> MetricResult:
        score = np.asarray(score, np.float64)
        gains = self.label_gain
        results = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            lbl = self.label[lo:hi].astype(np.int64)
            sc = score[lo:hi]
            order = np.argsort(-sc, kind="stable")
            sorted_gain = gains[lbl[order]]
            ideal_gain = np.sort(gains[lbl])[::-1]
            disc = 1.0 / np.log2(np.arange(len(lbl)) + 2.0)
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(lbl))
                max_dcg = float(np.sum(ideal_gain[:kk] * disc[:kk]))
                if max_dcg <= 0.0:
                    results[ki] += 1.0  # all-zero-label query counts as 1
                else:
                    dcg = float(np.sum(sorted_gain[:kk] * disc[:kk]))
                    results[ki] += dcg / max_dcg
        results /= max(self.num_queries, 1)
        return [(f"ndcg@{k}", float(results[ki]), True)
                for ki, k in enumerate(self.eval_at)]


class MapMetric(Metric):
    NAME = "map"
    HIGHER_BETTER = True

    def __init__(self, config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        self.query_boundaries = metadata.query_boundaries
        self.num_queries = len(self.query_boundaries) - 1

    @property
    def names(self):
        return [f"map@{k}" for k in self.eval_at]

    def eval(self, score, objective=None) -> MetricResult:
        score = np.asarray(score, np.float64)
        results = np.zeros(len(self.eval_at))
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            rel = self.label[lo:hi] > 0
            order = np.argsort(-score[lo:hi], kind="stable")
            rel_sorted = rel[order]
            hits = np.cumsum(rel_sorted)
            ranks = np.arange(1, len(rel_sorted) + 1)
            prec = hits / ranks
            for ki, k in enumerate(self.eval_at):
                kk = min(k, len(rel_sorted))
                nrel = rel_sorted[:kk].sum()
                if nrel > 0:
                    results[ki] += float(
                        np.sum(prec[:kk] * rel_sorted[:kk]) / nrel)
        results /= max(self.num_queries, 1)
        return [(f"map@{k}", float(results[ki]), True)
                for ki, k in enumerate(self.eval_at)]


# ---------------------------------------------------------------------------
# Cross-entropy metrics (ref: xentropy_metric.hpp)
# ---------------------------------------------------------------------------

class CrossEntropyMetric(_PointwiseMetric):
    NAME = "cross_entropy"

    def transform(self, score, objective):
        if objective is not None:
            return objective.convert_output(score)
        return 1.0 / (1.0 + np.exp(-score))

    def point_loss(self, p, label):
        eps = K_EPSILON
        p = np.clip(p, eps, 1.0 - eps)
        return -(label * np.log(p) + (1.0 - label) * np.log(1.0 - p))


class CrossEntropyLambdaMetric(_PointwiseMetric):
    NAME = "cross_entropy_lambda"

    def transform(self, score, objective):
        if objective is not None:
            return objective.convert_output(score)
        return np.log1p(np.exp(score))

    def point_loss(self, hhat, label):
        # loss = yhat*hhat - y*log(expm1(hhat)) ... (ref: XentLambdaMetric)
        eps = K_EPSILON
        hhat = np.maximum(hhat, eps)
        return (1.0 - label) * hhat - label * np.log(
            np.maximum(np.expm1(hhat), eps))


class KullbackLeiblerMetric(CrossEntropyMetric):
    NAME = "kullback_leibler"

    def point_loss(self, p, label):
        eps = K_EPSILON
        p = np.clip(p, eps, 1.0 - eps)
        y = np.clip(label, 0.0, 1.0)
        # KL(y || p) = xent(y, p) - H(y)
        hy = np.where((y > 0) & (y < 1),
                      -(y * np.log(y + eps) + (1 - y) * np.log(1 - y + eps)),
                      0.0)
        xent = -(y * np.log(p) + (1.0 - y) * np.log(1.0 - p))
        return xent - hy


# ---------------------------------------------------------------------------
# Factory (ref: metric.cpp:26 Metric::CreateMetric)
# ---------------------------------------------------------------------------

_METRICS = {
    "l1": L1Metric,
    "l2": L2Metric,
    "rmse": RMSEMetric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "r2": R2Metric,
    "binary_logloss": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "auc_mu": AucMuMetric,
    "multi_logloss": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "ndcg": NDCGMetric,
    "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerMetric,
}

# default metric per objective (ref: Config::GetMetricType — objective name
# doubles as the metric alias)
DEFAULT_METRIC_FOR_OBJECTIVE = {
    "regression": "l2",
    "regression_l1": "l1",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "quantile": "quantile",
    "mape": "mape",
    "gamma": "gamma",
    "tweedie": "tweedie",
    "binary": "binary_logloss",
    "multiclass": "multi_logloss",
    "multiclassova": "multi_logloss",
    "cross_entropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "lambdarank": "ndcg",
    "rank_xendcg": "ndcg",
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    from ..config import canonical_metric
    canonical = canonical_metric(name)
    base, _, at = canonical.partition("@")
    if base in ("none", "na", "null", "custom"):
        return None
    if base not in _METRICS:
        log.fatal(f"Unknown metric type name: {name}")
    cfg = config
    if at:
        cfg = config.copy()
        cfg.set("eval_at", [int(a) for a in at.split(",")])
    return _METRICS[base](cfg)


def metrics_for_config(config: Config, objective_name: str) -> List[Metric]:
    """Resolve the metric list, defaulting to the objective's own metric
    (ref: application.cpp/engine.py metric resolution)."""
    names = list(config.metric)
    if not names:
        default = DEFAULT_METRIC_FOR_OBJECTIVE.get(objective_name)
        names = [default] if default else []
    out = []
    seen = set()
    for n in names:
        if n in ("none", "null", "na", "custom", ""):
            continue
        if n in seen:
            continue
        seen.add(n)
        m = create_metric(n, config)
        if m is not None:
            out.append(m)
    return out
