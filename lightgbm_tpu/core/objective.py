"""Objective functions: score -> (gradient, hessian), fully jittable.

TPU-native equivalent of the reference objective layer
(ref: include/LightGBM/objective_function.h:20 ObjectiveFunction,
src/objective/objective_function.cpp:58 CreateObjectiveFunction factory,
src/objective/regression_objective.hpp, binary_objective.hpp,
multiclass_objective.hpp, xentropy_objective.hpp, rank_objective.hpp).

Design: each objective exposes ``get_gradients(score) -> (grad, hess)`` as a
pure function of device arrays so it fuses into the jitted boosting step —
the analogue of the reference's CUDA objectives writing grad/hess directly
into device buffers (ref: src/objective/cuda/*, gbdt.cpp:111 boosting_on_gpu_).
Host-side one-time setup (label stats, init score, percentile renewal) stays
numpy, exactly as the reference does it once per Init()/tree.

Score layout: [N] for single-model objectives, [K, N] class-major for
multiclass (matches the reference's ``num_data * k + i`` indexing).
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..utils import log

# ref: include/LightGBM/meta.h kEpsilon
K_EPSILON = 1e-15


def _percentile(values: np.ndarray, alpha: float) -> float:
    """Unweighted percentile (ref: regression_objective.hpp PercentileFun).

    LightGBM's scheme: pos = floor((n-1)*(1-alpha)) + 1 counted from the TOP
    of the descending order; equivalently an interpolated order statistic.
    """
    values = np.asarray(values, dtype=np.float64)
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    s = np.sort(values)[::-1]  # descending
    float_pos = (n - 1) * (1.0 - alpha)
    pos = int(float_pos) + 1
    if pos < 1:
        return float(s.min())
    if pos >= n:
        return float(s.max())
    bias = float_pos - (pos - 1)
    v1 = s[pos - 1]
    v2 = s[pos]
    return float(v1 - (v1 - v2) * bias)


def _weighted_percentile(values: np.ndarray, weights: np.ndarray,
                         alpha: float) -> float:
    """Weighted percentile (ref: regression_objective.hpp
    WeightedPercentileFun)."""
    values = np.asarray(values, dtype=np.float64)
    weights = np.asarray(weights, dtype=np.float64)
    n = len(values)
    if n == 0:
        return 0.0
    if n <= 1:
        return float(values[0])
    order = np.argsort(values, kind="stable")
    wcdf = np.cumsum(weights[order])
    threshold = wcdf[-1] * alpha
    pos = int(np.searchsorted(wcdf, threshold, side="right"))
    pos = min(pos, n - 1)
    if pos == 0 or pos == n - 1:
        return float(values[order[pos]])
    v1 = float(values[order[pos - 1]])
    v2 = float(values[order[pos]])
    if wcdf[pos] - wcdf[pos - 1] >= 1.0:
        return (threshold - wcdf[pos - 1]) / (wcdf[pos] - wcdf[pos - 1]) \
            * (v2 - v1) + v1
    return v1


class ObjectiveFunction:
    """Base objective (ref: objective_function.h:20)."""

    NAME = "custom"

    def __init__(self, config: Config):
        self.config = config
        self.num_data = 0
        self.label: Optional[np.ndarray] = None
        self.weight: Optional[np.ndarray] = None
        self._label_dev = None
        self._weight_dev = None

    # -- lifecycle ------------------------------------------------------
    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = metadata.label
        self.weight = metadata.weight
        self._label_dev = (jnp.asarray(self.label, jnp.float32)
                           if self.label is not None else None)
        self._weight_dev = (jnp.asarray(self.weight, jnp.float32)
                            if self.weight is not None else None)

    # -- hot path -------------------------------------------------------
    def get_gradients(self, score) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """score [N] (or [K, N]) -> (grad, hess) of the same shape.
        Pure & jittable; called inside the boosting step."""
        raise NotImplementedError

    def _apply_weight(self, grad, hess):
        if self._weight_dev is not None:
            grad = grad * self._weight_dev
            hess = hess * self._weight_dev
        return grad, hess

    # -- traits (ref: objective_function.h virtuals) --------------------
    @property
    def num_model_per_iteration(self) -> int:
        return 1

    @property
    def num_predict_one_row(self) -> int:
        return 1

    def is_constant_hessian(self) -> bool:
        return False

    def is_renew_tree_output(self) -> bool:
        return False

    def class_need_train(self, class_id: int) -> bool:
        return True

    def boost_from_score(self, class_id: int) -> float:
        return 0.0

    def convert_output(self, raw: np.ndarray) -> np.ndarray:
        """Raw score -> prediction space (ref: ConvertOutput)."""
        return raw

    def renew_tree_output(self, pred: np.ndarray, residual_fn,
                          leaf_index: np.ndarray, num_leaves: int
                          ) -> Optional[np.ndarray]:
        """Per-leaf output re-fit for L1-family (ref: RenewTreeOutput).
        Returns new leaf values [num_leaves] or None."""
        return None

    def to_string(self) -> str:
        return self.NAME

    def __str__(self) -> str:
        return self.to_string()


# ---------------------------------------------------------------------------
# Regression family (ref: regression_objective.hpp)
# ---------------------------------------------------------------------------

class RegressionL2(ObjectiveFunction):
    NAME = "regression"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = bool(config.reg_sqrt)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lbl = np.sign(self.label) * np.sqrt(np.abs(self.label))
            self.label = lbl.astype(np.float32)
            self._label_dev = jnp.asarray(self.label)

    def get_gradients(self, score):
        grad = score - self._label_dev
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def is_constant_hessian(self):
        return self.weight is None

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return float(np.sum(self.label * self.weight) /
                         np.sum(self.weight))
        return float(np.mean(self.label))

    def convert_output(self, raw):
        if self.sqrt:
            return np.sign(raw) * raw * raw
        return raw

    def to_string(self):
        return self.NAME + (" sqrt" if self.sqrt else "")


class RegressionL1(RegressionL2):
    NAME = "regression_l1"
    RENEW_ALPHA = 0.5

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def is_constant_hessian(self):
        return self.weight is None

    def is_renew_tree_output(self):
        return True

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return _weighted_percentile(self.label, self.weight,
                                        self.RENEW_ALPHA)
        return _percentile(self.label, self.RENEW_ALPHA)

    def _renew_weights(self, idx: np.ndarray) -> Optional[np.ndarray]:
        return None if self.weight is None else self.weight[idx]

    def renew_tree_output(self, pred, residual_fn, leaf_index, num_leaves):
        out = np.zeros(num_leaves, dtype=np.float64)
        residual = residual_fn()  # label - pred (before adding this tree)
        for leaf in range(num_leaves):
            idx = np.flatnonzero(leaf_index == leaf)
            if len(idx) == 0:
                continue
            w = self._renew_weights(idx)
            if w is None:
                out[leaf] = _percentile(residual[idx], self.RENEW_ALPHA)
            else:
                out[leaf] = _weighted_percentile(residual[idx], w,
                                                 self.RENEW_ALPHA)
        return out

    def to_string(self):
        return self.NAME


class RegressionHuber(RegressionL2):
    NAME = "huber"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.alpha = float(config.alpha)

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.clip(diff, -self.alpha, self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def to_string(self):
        return self.NAME


class RegressionFair(RegressionL2):
    NAME = "fair"

    def __init__(self, config: Config):
        super().__init__(config)
        self.c = float(config.fair_c)

    def get_gradients(self, score):
        x = score - self._label_dev
        denom = jnp.abs(x) + self.c
        grad = self.c * x / denom
        hess = self.c * self.c / (denom * denom)
        return self._apply_weight(grad, hess)

    def is_constant_hessian(self):
        return False

    def to_string(self):
        return self.NAME


class RegressionPoisson(RegressionL2):
    NAME = "poisson"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sqrt = False
        self.max_delta_step = float(config.poisson_max_delta_step)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.min(self.label) < 0.0:
            log.fatal(f"[{self.NAME}]: at least one target label is negative")
        if np.sum(self.label) == 0.0:
            log.fatal(f"[{self.NAME}]: sum of labels is zero")

    def get_gradients(self, score):
        exp_score = jnp.exp(score)
        grad = exp_score - self._label_dev
        hess = exp_score * math.exp(self.max_delta_step)
        return self._apply_weight(grad, hess)

    def is_constant_hessian(self):
        return False

    def boost_from_score(self, class_id):
        mean = RegressionL2.boost_from_score(self, class_id)
        return math.log(mean) if mean > 0 else math.log(K_EPSILON)

    def convert_output(self, raw):
        return np.exp(raw)

    def to_string(self):
        return self.NAME


class RegressionQuantile(RegressionL2):
    NAME = "quantile"

    def __init__(self, config: Config):
        super().__init__(config)
        self.alpha = float(config.alpha)
        if not (0.0 < self.alpha < 1.0):
            log.fatal("alpha must be in (0, 1) for quantile objective")

    def get_gradients(self, score):
        delta = score - self._label_dev
        grad = jnp.where(delta >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._apply_weight(grad, hess)

    def is_renew_tree_output(self):
        return True

    def boost_from_score(self, class_id):
        if self.weight is not None:
            return _weighted_percentile(self.label, self.weight, self.alpha)
        return _percentile(self.label, self.alpha)

    def renew_tree_output(self, pred, residual_fn, leaf_index, num_leaves):
        out = np.zeros(num_leaves, dtype=np.float64)
        residual = residual_fn()
        for leaf in range(num_leaves):
            idx = np.flatnonzero(leaf_index == leaf)
            if len(idx) == 0:
                continue
            if self.weight is None:
                out[leaf] = _percentile(residual[idx], self.alpha)
            else:
                out[leaf] = _weighted_percentile(residual[idx],
                                                 self.weight[idx], self.alpha)
        return out

    def to_string(self):
        return self.NAME


class RegressionMAPE(RegressionL1):
    NAME = "mape"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if np.any(np.abs(self.label) < 1):
            log.warning("Some label values are < 1 in absolute value. MAPE "
                        "is unstable with such values; rounding them to 1.0")
        lw = 1.0 / np.maximum(1.0, np.abs(self.label))
        if self.weight is not None:
            lw = lw * self.weight
        self.label_weight = lw.astype(np.float32)
        self._label_weight_dev = jnp.asarray(self.label_weight)

    def get_gradients(self, score):
        diff = score - self._label_dev
        grad = jnp.sign(diff) * self._label_weight_dev
        if self._weight_dev is not None:
            hess = self._weight_dev
        else:
            hess = jnp.ones_like(score)
        return grad, hess

    def is_constant_hessian(self):
        return True

    def boost_from_score(self, class_id):
        return _weighted_percentile(self.label, self.label_weight, 0.5)

    def _renew_weights(self, idx):
        return self.label_weight[idx]

    def renew_tree_output(self, pred, residual_fn, leaf_index, num_leaves):
        out = np.zeros(num_leaves, dtype=np.float64)
        residual = residual_fn()
        for leaf in range(num_leaves):
            idx = np.flatnonzero(leaf_index == leaf)
            if len(idx) == 0:
                continue
            out[leaf] = _weighted_percentile(residual[idx],
                                             self.label_weight[idx], 0.5)
        return out


class RegressionGamma(RegressionPoisson):
    NAME = "gamma"

    def get_gradients(self, score):
        exp_neg = jnp.exp(-score)
        grad = 1.0 - self._label_dev * exp_neg
        hess = self._label_dev * exp_neg
        return self._apply_weight(grad, hess)


class RegressionTweedie(RegressionPoisson):
    NAME = "tweedie"

    def __init__(self, config: Config):
        super().__init__(config)
        self.rho = float(config.tweedie_variance_power)

    def get_gradients(self, score):
        e1 = jnp.exp((1.0 - self.rho) * score)
        e2 = jnp.exp((2.0 - self.rho) * score)
        grad = -self._label_dev * e1 + e2
        hess = -self._label_dev * (1.0 - self.rho) * e1 + (2.0 - self.rho) * e2
        return self._apply_weight(grad, hess)


# ---------------------------------------------------------------------------
# Binary classification (ref: binary_objective.hpp)
# ---------------------------------------------------------------------------

class BinaryLogloss(ObjectiveFunction):
    NAME = "binary"

    def __init__(self, config: Config, is_pos=None):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal(f"Sigmoid parameter {self.sigmoid} should be > 0")
        self.is_unbalance = bool(config.is_unbalance)
        self.scale_pos_weight = float(config.scale_pos_weight)
        if self.is_unbalance and abs(self.scale_pos_weight - 1.0) > 1e-6:
            log.fatal("Cannot set is_unbalance and scale_pos_weight together")
        self.is_pos = is_pos or (lambda y: y > 0)
        self.need_train = True
        self.num_pos_data = 0

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        pos_mask = self.is_pos(self.label)
        cnt_pos = int(pos_mask.sum())
        cnt_neg = num_data - cnt_pos
        self.num_pos_data = cnt_pos
        self.need_train = cnt_pos > 0 and cnt_neg > 0
        if not self.need_train:
            log.warning("Contains only one class")
        log.info(f"Number of positive: {cnt_pos}, number of negative: {cnt_neg}")
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance and cnt_pos > 0 and cnt_neg > 0:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        w_pos *= self.scale_pos_weight
        # per-row signed label (+1/-1) and label weight, as device constants
        self._sign = jnp.where(jnp.asarray(pos_mask), 1.0, -1.0).astype(
            jnp.float32)
        self._lw = jnp.where(jnp.asarray(pos_mask), w_pos, w_neg).astype(
            jnp.float32)
        self._pos_mask = pos_mask

    def get_gradients(self, score):
        if not self.need_train:
            return jnp.zeros_like(score), jnp.zeros_like(score)
        response = -self._sign * self.sigmoid / (
            1.0 + jnp.exp(self._sign * self.sigmoid * score))
        abs_response = jnp.abs(response)
        grad = response * self._lw
        hess = abs_response * (self.sigmoid - abs_response) * self._lw
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        if self.weight is not None:
            suml = float(np.sum(self._pos_mask * self.weight))
            sumw = float(np.sum(self.weight))
        else:
            suml = float(np.sum(self._pos_mask))
            sumw = float(self.num_data)
        pavg = min(max(suml / sumw, K_EPSILON), 1.0 - K_EPSILON)
        initscore = math.log(pavg / (1.0 - pavg)) / self.sigmoid
        log.info(f"[{self.NAME}:BoostFromScore]: pavg={pavg:.6f} -> "
                 f"initscore={initscore:.6f}")
        return initscore

    def class_need_train(self, class_id):
        return self.need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"{self.NAME} sigmoid:{self.sigmoid:g}"


# ---------------------------------------------------------------------------
# Multiclass (ref: multiclass_objective.hpp)
# ---------------------------------------------------------------------------

class MulticlassSoftmax(ObjectiveFunction):
    NAME = "multiclass"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.factor = self.num_class / (self.num_class - 1.0)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        label_int = self.label.astype(np.int32)
        if label_int.min() < 0 or label_int.max() >= self.num_class:
            log.fatal(f"Label must be in [0, {self.num_class})")
        w = self.weight if self.weight is not None else np.ones(num_data)
        probs = np.zeros(self.num_class)
        np.add.at(probs, label_int, w)
        self.class_init_probs = probs / w.sum()
        # one-hot labels as a [K, N] device constant
        self._onehot = jnp.asarray(
            label_int[None, :] == np.arange(self.num_class)[:, None],
            jnp.float32)

    def get_gradients(self, score):
        # score [K, N]
        p = jax.nn.softmax(score, axis=0)
        grad = p - self._onehot
        hess = self.factor * p * (1.0 - p)
        if self._weight_dev is not None:
            grad = grad * self._weight_dev[None, :]
            hess = hess * self._weight_dev[None, :]
        return grad, hess

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class

    def boost_from_score(self, class_id):
        return math.log(max(K_EPSILON, self.class_init_probs[class_id]))

    def class_need_train(self, class_id):
        p = self.class_init_probs[class_id]
        return K_EPSILON < abs(p) < 1.0 - K_EPSILON

    def convert_output(self, raw):
        # raw [..., K] -> softmax over last axis
        e = np.exp(raw - raw.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def to_string(self):
        return f"{self.NAME} num_class:{self.num_class}"


class MulticlassOVA(ObjectiveFunction):
    NAME = "multiclassova"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)
        self.sigmoid = float(config.sigmoid)
        self.binary_losses = [
            BinaryLogloss(config,
                          is_pos=(lambda y, k=k: y.astype(np.int32) == k))
            for k in range(self.num_class)]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        for b in self.binary_losses:
            b.init(metadata, num_data)

    def get_gradients(self, score):
        grads, hesss = [], []
        for k in range(self.num_class):
            g, h = self.binary_losses[k].get_gradients(score[k])
            grads.append(g)
            hesss.append(h)
        return jnp.stack(grads), jnp.stack(hesss)

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class

    def boost_from_score(self, class_id):
        return self.binary_losses[class_id].boost_from_score(0)

    def class_need_train(self, class_id):
        return self.binary_losses[class_id].need_train

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-self.sigmoid * raw))

    def to_string(self):
        return f"{self.NAME} num_class:{self.num_class} sigmoid:{self.sigmoid:g}"


# ---------------------------------------------------------------------------
# Cross-entropy on [0,1] labels (ref: xentropy_objective.hpp)
# ---------------------------------------------------------------------------

class CrossEntropy(ObjectiveFunction):
    NAME = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0 or self.label.max() > 1:
            log.fatal("[cross_entropy]: label must be in [0, 1]")

    def get_gradients(self, score):
        z = jax.nn.sigmoid(score)
        grad = z - self._label_dev
        hess = z * (1.0 - z)
        return self._apply_weight(grad, hess)

    def boost_from_score(self, class_id):
        w = self.weight if self.weight is not None else np.ones(self.num_data)
        pavg = float(np.sum(self.label * w) / np.sum(w))
        pavg = min(max(pavg, K_EPSILON), 1.0 - K_EPSILON)
        initscore = math.log(pavg / (1.0 - pavg))
        log.info(f"[{self.NAME}:BoostFromScore]: pavg={pavg:.6f} -> "
                 f"initscore={initscore:.6f}")
        return initscore

    def convert_output(self, raw):
        return 1.0 / (1.0 + np.exp(-raw))


class CrossEntropyLambda(ObjectiveFunction):
    """Alternative parameterization with weights entering the link
    (ref: xentropy_objective.hpp:186 CrossEntropyLambda)."""
    NAME = "cross_entropy_lambda"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.min() < 0 or self.label.max() > 1:
            log.fatal("[cross_entropy_lambda]: label must be in [0, 1]")

    def get_gradients(self, score):
        if self._weight_dev is None:
            z = jax.nn.sigmoid(score)
            grad = z - self._label_dev
            hess = z * (1.0 - z)
            return grad, hess
        w = self._weight_dev
        y = self._label_dev
        epf = jnp.exp(score)
        enf = 1.0 / epf
        z = 1.0 - jnp.exp(-w * jnp.log1p(epf))
        grad = (1.0 - y / jnp.maximum(z, K_EPSILON)) * w / (1.0 + enf)
        c = 1.0 / (1.0 - jnp.minimum(z, 1.0 - K_EPSILON))
        b = 1.0 + w * epf - c
        a = w * epf / ((1.0 + epf) * (1.0 + epf))
        hess = a * (1.0 + y * b)
        return grad, hess

    def boost_from_score(self, class_id):
        w = self.weight if self.weight is not None else np.ones(self.num_data)
        havg = float(np.sum(self.label * w) / np.sum(w))
        initscore = math.log(math.expm1(max(havg, K_EPSILON)))
        log.info(f"[{self.NAME}:BoostFromScore]: havg={havg:.6f} -> "
                 f"initscore={initscore:.6f}")
        return initscore

    def convert_output(self, raw):
        return np.log1p(np.exp(raw))


# ---------------------------------------------------------------------------
# Ranking (ref: rank_objective.hpp LambdarankNDCG / RankXENDCG)
# ---------------------------------------------------------------------------

def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^i - 1 gains (ref: dcg_calculator.cpp DefaultLabelGain)."""
    return (np.power(2.0, np.arange(max_label + 1)) - 1.0)


class _QueryBucket:
    """One length-bucket of queries padded to a shared width."""

    def __init__(self, qids: np.ndarray, qb: np.ndarray, width: int,
                 label: np.ndarray):
        self.qids = qids                       # i64 [Qb] original query ids
        counts = (qb[qids + 1] - qb[qids]).astype(np.int64)
        Qb = len(qids)
        idx = np.zeros((Qb, width), np.int64)
        valid = np.zeros((Qb, width), bool)
        for r, q in enumerate(qids):
            c = counts[r]
            idx[r, :c] = np.arange(qb[q], qb[q + 1])
            valid[r, :c] = True
        self.idx = jnp.asarray(idx)            # [Qb, Mb]
        self.valid = jnp.asarray(valid)        # [Qb, Mb]
        self.label_q = jnp.asarray(
            np.where(valid, label[idx], 0.0), jnp.float32)


class _RankingObjective(ObjectiveFunction):
    """Shared padded-query machinery. Queries are grouped into pow2
    LENGTH BUCKETS and padded to the bucket width, so the per-query
    pairwise computation becomes a few dense [Qb, Mb, Mb] masked tensor
    ops — the TPU-native shape of the reference's per-query OMP loop
    (ref: rank_objective.hpp:56 GetGradients). Bucketing bounds both the
    padding waste (<2x rows) and the pairwise memory: one 10k-doc query
    no longer inflates every query's pair tensor to 10k x 10k
    (SURVEY.md §7 flagged the single-max-width formulation)."""

    MIN_BUCKET_WIDTH = 16

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        qb = metadata.query_boundaries.astype(np.int64)
        self.query_boundaries = qb
        self.num_queries = len(qb) - 1
        counts = np.diff(qb)
        self.max_query = int(counts.max())
        self._qcounts = counts
        # pow2 ceiling per query -> one bucket per distinct ceiling
        widths = np.maximum(self.MIN_BUCKET_WIDTH,
                            2 ** np.ceil(np.log2(np.maximum(counts, 1)))
                            .astype(np.int64))
        self.buckets = [
            _QueryBucket(np.flatnonzero(widths == w), qb, int(w), self.label)
            for w in np.unique(widths)]

    def scatter_back(self, parts) -> jnp.ndarray:
        """Per-bucket [Qb, Mb] padded doc values -> [N] flat."""
        flat = jnp.zeros(self.num_data, jnp.float32)
        for bk, padded in zip(self.buckets, parts):
            flat = flat.at[bk.idx.reshape(-1)].add(
                jnp.where(bk.valid, padded, 0.0).reshape(-1))
        return flat


class LambdarankNDCG(_RankingObjective):
    NAME = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal(f"Sigmoid param {self.sigmoid} should be > 0")
        self.norm = bool(config.lambdarank_norm)
        self.truncation_level = int(config.lambdarank_truncation_level)
        lg = list(config.label_gain)
        self.label_gain = (np.asarray(lg, np.float64) if lg
                           else default_label_gain())
        self._bias_reg = float(config.lambdarank_position_bias_regularization)
        self._bias_lr = float(config.learning_rate)
        self.positions = None

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.label.max() >= len(self.label_gain):
            log.fatal(f"Label {int(self.label.max())} exceeds label_gain "
                      "size; set label_gain explicitly")
        # per-query inverse max DCG at truncation level
        inv = np.zeros(self.num_queries)
        gains = self.label_gain
        for q in range(self.num_queries):
            lo, hi = self.query_boundaries[q], self.query_boundaries[q + 1]
            lbl = np.sort(self.label[lo:hi])[::-1][:self.truncation_level]
            dcg = np.sum(gains[lbl.astype(np.int64)] /
                         np.log2(np.arange(len(lbl)) + 2.0))
            inv[q] = 1.0 / dcg if dcg > 0 else 0.0
        for bk in self.buckets:
            bk.inv_max_dcg = jnp.asarray(inv[bk.qids], jnp.float32)
            bk.gain_q = jnp.asarray(
                self.label_gain[np.asarray(bk.label_q, np.int64)],
                jnp.float32)
        # position bias (ref: rank_objective.hpp:44-57 positions_/pos_biases_)
        if metadata.position is not None:
            self.positions = metadata.position.astype(np.int64)
            self.num_position_ids = int(self.positions.max()) + 1
            self.pos_biases = np.zeros(self.num_position_ids, np.float64)
            self._positions_dev = jnp.asarray(self.positions, jnp.int32)
            log.info(f"Using position bias correction with "
                     f"{self.num_position_ids} position ids")

    @property
    def uses_position_bias(self) -> bool:
        return self.positions is not None

    def update_position_bias(self, lambdas: np.ndarray,
                             hessians: np.ndarray) -> None:
        """Newton-Raphson update of per-position bias factors
        (ref: rank_objective.hpp:303 UpdatePositionBiasFactors)."""
        n = self.num_position_ids
        first = -np.bincount(self.positions, weights=lambdas, minlength=n)
        second = -np.bincount(self.positions, weights=hessians, minlength=n)
        counts = np.bincount(self.positions, minlength=n)
        first -= self.pos_biases * self._bias_reg * counts
        second -= self._bias_reg * counts
        self.pos_biases += self._bias_lr * first / (np.abs(second) + 0.001)

    def _bucket_gradients(self, bk: _QueryBucket, score):
        """All-pairs lambdas for one length bucket (ref:
        rank_objective.hpp:181 GetGradientsForOneQuery, exact sigmoid
        instead of the lookup table)."""
        Q, M = bk.idx.shape
        valid = bk.valid
        s = jnp.where(valid, score[bk.idx], -jnp.inf)          # [Q, M]
        lbl = bk.label_q
        gain = bk.gain_q

        # rank of each doc within its query by descending score (stable)
        order = jnp.argsort(-jnp.where(valid, s, -jnp.inf),
                            axis=1, stable=True)   # [Q, M] doc slot at rank r
        rank = jnp.zeros_like(order).at[
            jnp.arange(Q)[:, None], order].set(jnp.arange(M)[None, :])
        discount = 1.0 / jnp.log2(rank.astype(jnp.float32) + 2.0)

        pair_valid = (valid[:, :, None] & valid[:, None, :] &
                      (lbl[:, :, None] != lbl[:, None, :]))
        # truncation: pair needs at least one doc ranked < truncation_level
        in_trunc = rank < self.truncation_level
        pair_valid &= in_trunc[:, :, None] | in_trunc[:, None, :]
        # orient: i = high-label doc, j = low
        high_is_i = lbl[:, :, None] > lbl[:, None, :]

        delta_score = s[:, :, None] - s[:, None, :]            # s_i - s_j
        dcg_gap = gain[:, :, None] - gain[:, None, :]
        paired_discount = jnp.abs(discount[:, :, None] - discount[:, None, :])
        delta_ndcg = jnp.abs(dcg_gap) * paired_discount * \
            bk.inv_max_dcg[:, None, None]

        if self.norm:
            best = jnp.max(jnp.where(valid, s, -jnp.inf), axis=1)
            worst = jnp.min(jnp.where(valid, s, jnp.inf), axis=1)
            norm_ok = (best != worst)[:, None, None]
            delta_ndcg = jnp.where(
                norm_ok, delta_ndcg / (0.01 + jnp.abs(delta_score)),
                delta_ndcg)

        # signed delta from high to low: use delta for (high, low) pair
        hl_delta = jnp.where(high_is_i, delta_score, -delta_score)
        p = jax.nn.sigmoid(-self.sigmoid * hl_delta)       # 1/(1+e^{s_h-s_l})
        p_lambda = -self.sigmoid * delta_ndcg * p
        p_hess = self.sigmoid * self.sigmoid * delta_ndcg * p * (1.0 - p)

        pair_valid &= high_is_i  # count each unordered pair once, i as high
        p_lambda = jnp.where(pair_valid, p_lambda, 0.0)
        p_hess = jnp.where(pair_valid, p_hess, 0.0)

        # i (high) receives +lambda, j (low) receives -lambda
        lambdas = p_lambda.sum(axis=2) - p_lambda.sum(axis=1)
        hess = p_hess.sum(axis=2) + p_hess.sum(axis=1)
        sum_lambdas = -2.0 * p_lambda.sum(axis=(1, 2))

        if self.norm:
            nf = jnp.where(sum_lambdas > 0,
                           jnp.log2(1.0 + sum_lambdas) /
                           jnp.maximum(sum_lambdas, K_EPSILON), 1.0)
            lambdas = lambdas * nf[:, None]
            hess = hess * nf[:, None]
        return lambdas, hess

    def get_gradients(self, score, pos_biases=None):
        """Bucketed all-pairs lambdas. ``pos_biases`` (f32
        [num_position_ids]) adjusts scores before the pairwise computation
        (ref: rank_objective.hpp:69-74)."""
        if pos_biases is not None and self.positions is not None:
            score = score + pos_biases[self._positions_dev]
        parts = [self._bucket_gradients(bk, score) for bk in self.buckets]
        return (self.scatter_back([p[0] for p in parts]),
                self.scatter_back([p[1] for p in parts]))

    def to_string(self):
        return self.NAME


class RankXENDCG(_RankingObjective):
    """Cross-entropy surrogate for NDCG (ref: rank_objective.hpp RankXENDCG;
    Bruch et al., 'An Alternative Cross Entropy Loss for Learning-to-Rank')."""
    NAME = "rank_xendcg"

    def __init__(self, config: Config):
        super().__init__(config)
        self.seed = int(config.objective_seed)
        self._iter = 0

    def _bucket_gradients(self, bk: _QueryBucket, score, key):
        Q, M = bk.idx.shape
        valid = bk.valid
        s = jnp.where(valid, score[bk.idx], -jnp.inf)
        rho = jax.nn.softmax(jnp.where(valid, s, -jnp.inf), axis=1)
        rho = jnp.where(valid, rho, 0.0)
        # terms: phi(label, gumbel) = 2^label - gumbel
        gumbel = jax.random.gumbel(key, (Q, M))
        phi = jnp.power(2.0, bk.label_q) - gumbel
        phi = jnp.where(valid, phi, 0.0)
        phi_sum = jnp.maximum(phi.sum(axis=1, keepdims=True), K_EPSILON)
        ys = phi / phi_sum
        l1 = rho - ys
        # second-order correction terms (ref: rank_objective.hpp:400-430)
        l2_denom = jnp.maximum(1.0 - rho, K_EPSILON)
        params = (ys + l1 * rho / l2_denom)
        lambdas = l1 + rho * (params.sum(axis=1, keepdims=True) - params)
        hess = rho * (1.0 - rho)
        lambdas = jnp.where(valid, lambdas, 0.0)
        hess = jnp.where(valid, hess, 0.0)
        return lambdas, hess

    def get_gradients(self, score):
        # fresh gumbel noise per call (ref: Rands in GetGradientsForOneQuery)
        self._iter += 1
        keys = jax.random.split(jax.random.PRNGKey(self.seed + self._iter),
                                len(self.buckets))
        parts = [self._bucket_gradients(bk, score, k)
                 for bk, k in zip(self.buckets, keys)]
        return (self.scatter_back([p[0] for p in parts]),
                self.scatter_back([p[1] for p in parts]))

    def to_string(self):
        return self.NAME


# ---------------------------------------------------------------------------
# Custom objective adapter (fobj from Python callbacks)
# ---------------------------------------------------------------------------

class CustomObjective(ObjectiveFunction):
    """Gradients supplied by the caller (ref: gbdt.cpp:364-381 custom path,
    'custom'/'none' factory names objective_function.cpp:147)."""
    NAME = "custom"

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = int(config.num_class)

    def get_gradients(self, score):
        raise RuntimeError("custom objective: gradients must be passed to "
                           "Booster.update(train_set, fobj)")

    @property
    def num_model_per_iteration(self):
        return self.num_class

    @property
    def num_predict_one_row(self):
        return self.num_class


# ---------------------------------------------------------------------------
# Factory (ref: objective_function.cpp:58 CreateObjectiveFunction)
# ---------------------------------------------------------------------------

_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l1": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
    "custom": CustomObjective,
}


def create_objective(name: str, config: Config) -> ObjectiveFunction:
    from ..config import canonical_objective
    canonical = canonical_objective(name)
    if canonical not in _OBJECTIVES:
        log.fatal(f"Unknown objective type name: {name}")
    return _OBJECTIVES[canonical](config)
