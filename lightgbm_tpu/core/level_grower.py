"""Level-synchronous best-first tree grower (round-6 architecture).

The sequential grower (core/grower.py) mirrors the reference's
leaf-wise loop (ref: serial_tree_learner.cpp:183-249): num_leaves-1
dependent steps, each dispatching ~40 kernels through the device
tunnel. This grower instead:

1. grows the tree level by level — one segment-histogram pass,
   one vmapped split scan and one partition pass per DEPTH;
2. ranks every candidate node by e(v) = min(gain(u) for u on the
   root->v path) and keeps the top (num_leaves - 1): by the theorem
   validated in tests/test_levelwise_theory.py this reproduces the
   leaf-wise best-first tree exactly (expansion order = descending e,
   ties parent-first — stable argsort over heap ids gives both);
3. assembles TreeArrays + per-row leaf ids from the ranking with
   vectorized per-level slot/pointer passes — no sequential split
   loop at all.

Phase A (``make_level_grower``): the pure level mode for
``max_depth in [1, MAX_LEVEL_DEPTH]``. Phase B rides on the same
machinery: ``make_level_phase`` exposes the per-level
hist/scan/partition loop plus the heap-ordered candidate arrays so
core/hybrid_grower.py can run the level phase to a handoff depth D0
and seed the sequential grower's GrowState from it (per-leaf
stats/best rows from the level scans, histogram-pool rows from the
kept level hists, order/seg from a stable sort on leaf ids — the
design in docs/TPU_RUNBOOK.md round-6 §3), which serves the DEFAULT
255-leaf unbounded-depth config.

Admissions (round-7, previously phase-A exclusions):

- categorical features — the vmapped split scan already produces
  per-node category sets; the partition tests per-row set membership
  (≡ dense_bin.hpp SplitCategoricalInner) and the assembly scatters
  cat_count/cat_bins into TreeArrays like the sequential grower.
- EFB bundles — histograms run over PHYSICAL group columns [R, G] and
  expand to logical features per node at scan time with the node's own
  totals (io/bundling.make_expand_hist ≡ FixHistogram); partitions
  decode the group column through decode_logical_bin.
- quantized gradients — int8 gh rows accumulate into exact int32 level
  histograms, converted through the shared per-tree scales at scan
  time (core/grower.quantize_gradients — the SAME helper and rng the
  sequential grower uses, so a hybrid handoff sees bit-identical
  histograms on both sides of the cut).

Numerical note: per-node sums, outputs and child stats come from the
SAME SplitRecord fields the sequential grower uses, so the only
divergence channel is histogram accumulation order (level-batched vs
gathered-segment passes): bit-exact for dyadic gradients (e.g. a
binary objective's first tree) and for the quantized int32 path,
ordinary f32 reassociation noise otherwise — each node accumulates
only its own rows/blocks in every formulation here, so the error
scales with the node's own magnitude, not the dataset's. Exact fp
ties between UNRELATED candidate nodes break by heap order here vs
leaf-slot order sequentially (measure-zero on real-valued gains).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..ops.histogram import hist_rowmajor
from ..utils import log
from ..ops.split import (FeatureMeta, K_EPSILON, SplitHyperParams,
                         SplitRecord, best_split_for_leaf,
                         calculate_splitted_leaf_output,
                         meta_has_categorical, pack_record_rows)
from .grower import GrowerConfig, _go_left_bins, quantize_gradients
from .tree import TreeArrays

# dense level hists are [2^d, F, B, 3]: depth 10 = 1024 nodes is the
# last comfortable level at 28 x 256 (344 MB f32).
#
# Row-count bound (ADVICE r05): besides the hists, each level carries
# O(R) intermediates — the uint8 bins and their node-sorted copy
# (1 B/row/feature each; bins stay uint8 through the sort and the
# edge-window gathers, cast to int32 only per block INSIDE the kernel
# call), ~12 B/row of int32 heap/sort keys, and two [n_d, bs, F] edge
# windows with bs*n_d in [R, 2R) (2 B/row/feature uint8). Budget
# ~3 B/row/feature + ~16 B/row in flight per level: the 10.5M x 28
# driver shape costs ~1 GB next to 16 GB HBM. (The pre-round-7 int32
# [R, F] materialization + sorted copy was 8 B/row/feature — ~2.4 GB
# at 10.5M x 28 — and is exactly what this bound documents against.)
MAX_LEVEL_DEPTH = 10


# INFO-log a backend-resolution decision exactly once per process: the
# r05 A/B confusion started with an INVISIBLE mapping (pallas silently
# running as einsum under blocks mode), so every silent remap announces
# itself — once, not per-level/per-tree. One shared helper
# (utils/log.info_once) so the grower modules can't drift.
from ..utils.log import info_once as _log_once  # noqa: E402


def _resolve_rm_backend(requested: str) -> str:
    """Level-mode histogram kernel selection.

    "scatter": one global scatter-add per level over (node, f, bin)
    keys — the natural CPU kernel. "pallas_level": the ONE-launch
    sorted-segment Pallas kernel (ops/hist_level_pallas.py) — per-node
    VMEM accumulator banks over segment-aligned row blocks. Anything
    else runs the BLOCKS mode (rows sorted by node + batched
    whole-block histograms + masked edge windows — ~4 large batched
    kernels per level, the pre-round-10 MXU shape).

    ADVICE r05: blocks mode runs the row-major kernel under vmap with
    masked edge windows as small as bs=256 — a combination the pallas
    kernel has never been device-measured on (the r05 device A/B
    pinned einsum on both arms). A batching or small-block defect
    would corrupt level histograms silently, so a bare "pallas"
    request maps to einsum until pallas-under-level has device A/B
    coverage (the interpret-mode parity test
    tests/test_level_grower.py::test_pallas_blocks_parity_interpret
    exercises the real kernel under vmap via LGBM_TPU_LEVEL_PALLAS=1).
    The mapping is no longer silent: it logs once at INFO with the
    reason — invisibility is exactly how the r05 A/B confusion
    started.
    """
    if requested == "scatter":
        return "scatter"
    if requested == "pallas_level":
        return "pallas_level"
    if requested == "pallas":
        if os.environ.get("LGBM_TPU_LEVEL_PALLAS", "").lower() in (
                "1", "true", "yes"):
            return "pallas"
        _log_once(
            "level histograms: tpu_hist_kernel=pallas maps to einsum "
            "under blocks mode (pallas-under-vmap lacks device A/B "
            "coverage, ADVICE r05; set LGBM_TPU_LEVEL_PALLAS=1 to "
            "force, or use tpu_hist_kernel=pallas_level for the "
            "sorted-segment kernel)")
        return "einsum"
    if requested != "einsum":
        _log_once(
            f"level histograms: backend {requested!r} has no level-mode "
            "formulation; running blocks mode with einsum")
    return "einsum"


def effective_level_backend(cfg: "GrowerConfig") -> str:
    """The backend the level phase will actually run (after the
    pallas→einsum pin, legacy derivation, AND the VMEM-infeasibility
    fallback — which depends only on num_bin, so it is knowable here)
    — the ONE attribution string bench records carry so device numbers
    are traceable to a kernel config (r05 lesson: an invisible remap
    made two sessions' A/Bs unattributable). The per-depth padding-
    economy fallback (deep near-empty levels route to blocks) can
    still mix backends WITHIN a tree; that one is INFO-logged, not
    re-attributed."""
    resolved = _resolve_rm_backend(cfg.level_hist_backend or
                                   cfg.hist_rm_backend)
    if resolved == "pallas_level":
        from ..ops.hist_level_pallas import level_tiles
        if not level_tiles(8, int(cfg.num_bin), 512, 1, 1)[2]:
            return "einsum"        # what the fallback actually runs
    return resolved


def hist_level_scatter(bins_t, gh, lsafe, in_lvl, n_d, *, num_bin,
                       acc_dtype):
    """[n_d, Fp, B, 3] per-node histograms, scatter formulation.

    Streams per FEATURE: one [R] scatter into a cache-resident
    [n_d*B, 3] accumulator per column — the natural CPU kernel
    (measured ~2x over a single (node, f, bin)-keyed scatter at 1M
    rows on CPU, whose [R, Fp, 3] broadcast updates and multi-MB
    output thrash). ``bins_t`` is feature-major [Fp, R]."""
    Fp = bins_t.shape[0]
    ghm = (gh * in_lvl[:, None].astype(gh.dtype)).astype(acc_dtype)
    key_base = lsafe * num_bin

    def one_feature(col):
        return jnp.zeros((n_d * num_bin, 3), acc_dtype).at[
            key_base + col.astype(jnp.int32)].add(ghm)

    hist_raw = jax.lax.map(one_feature, bins_t)
    return hist_raw.reshape(Fp, n_d, num_bin, 3).transpose(1, 0, 2, 3)


# jaxlint: disable=JL002 — n_d/R/Fp are static Python ints at trace
# time (the per-level node count and row count specialize the
# program; one compile per level width, cached across trees)
def hist_level_blocks(bins_p, gh, local, in_lvl, n_d, R, Fp, *, num_bin,
                      input_dtype, rm_backend, acc_dtype):
    """[n_d, Fp, B, 3] per-node histograms, big-kernel formulation.

    Full blocks interior to a node are summed by a per-owner
    scatter over [G] block histograms (each node sums only its OWN
    blocks — no global prefix, so no cancellation error beyond the
    node's own magnitude); the two sub-block edges of every node
    come from fixed-size masked windows. ``bins_p`` stays uint8/16
    through the sort and the window gathers (the ADVICE r05 memory
    bound); the cast to int32 happens per block inside the kernel
    call, where it is fused and ephemeral."""
    B = num_bin
    rm_hist = jax.vmap(lambda b, g: hist_rowmajor(
        b.astype(jnp.int32), g, num_bin=B, dtype=input_dtype,
        backend=rm_backend))

    if n_d <= 2:
        # shallow levels: per-node masked full passes beat the
        # block/window machinery (n_d * R <= 2R vs ~3R rows); the
        # inline cast fuses into the one-hot compare
        return jnp.stack([
            hist_rowmajor(
                bins_p.astype(jnp.int32),
                gh * (in_lvl & (local == v))[:, None].astype(
                    gh.dtype),
                num_bin=B, dtype=input_dtype,
                backend=rm_backend)
            for v in range(n_d)]).astype(acc_dtype)

    key = jnp.where(in_lvl, local, n_d)
    order = jnp.argsort(key, stable=True)
    sb = bins_p[order]                             # [R, Fp] uint8
    sgh = gh[order] * (key[order] < n_d)[:, None].astype(gh.dtype)
    # PHYSICAL rows per node (counts incl. bagged-out rows)
    cnt = jnp.zeros(n_d + 1, jnp.int32).at[key].add(1)[:n_d]
    starts = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(cnt)])  # [n_d + 1]
    s_v, e_v = starts[:-1], starts[1:]
    # block size ~ mean segment, pow2
    bs = 256
    while bs * n_d < R:
        bs *= 2
    G = -(-R // bs)
    pad = G * bs - R
    sb = jnp.pad(sb, ((0, pad), (0, 0)))
    sgh = jnp.pad(sgh, ((0, pad), (0, 0)))
    bh = rm_hist(sb.reshape(G, bs, Fp), sgh.reshape(G, bs, 3))
    # owner of each block: the node containing its first row, kept
    # only when the whole block lies inside that node; straddling
    # and out-of-range blocks go to the dump slot (their rows are
    # exactly what the edge windows cover)
    b_start = jnp.arange(G, dtype=jnp.int32) * bs
    owner = (jnp.searchsorted(starts, b_start, side="right")
             .astype(jnp.int32) - 1)
    own_safe = jnp.clip(owner, 0, n_d - 1)
    interior = ((owner >= 0) & (owner < n_d) &
                (b_start + bs <= e_v[own_safe]) &
                (b_start >= s_v[own_safe]))
    tgt = jnp.where(interior, own_safe, n_d)       # dump slot n_d
    full = jnp.zeros((n_d + 1, Fp, B, 3), bh.dtype).at[tgt].add(
        bh)[:n_d]
    b0 = -(-s_v // bs)                             # ceil
    b1 = jnp.maximum(e_v // bs, b0)
    head_end = jnp.minimum(b0 * bs, e_v)
    tail_start = jnp.maximum(b1 * bs, head_end)

    def window_hist(w_start, w_len):
        idx = jnp.minimum(w_start[:, None] +
                          jnp.arange(bs, dtype=jnp.int32)[None, :],
                          G * bs - 1)              # [n_d, bs]
        wb = sb[idx]                               # [n_d, bs, Fp] u8
        wm = (jnp.arange(bs)[None, :] <
              w_len[:, None]).astype(gh.dtype)
        wg = sgh[idx] * wm[:, :, None]
        return rm_hist(wb, wg)

    head = window_hist(s_v, head_end - s_v)
    tail = window_hist(tail_start, e_v - tail_start)
    return (full + head + tail).astype(acc_dtype)


def make_level_phase(cfg: GrowerConfig, meta: FeatureMeta, depth: int,
                     scan_last: bool, bundle=None,
                     collect_hists: bool = False):
    """Build the level loop shared by the pure grower and the hybrid.

    Scans levels 0..depth-1 and — when ``scan_last`` — level ``depth``
    too; partitions rows after levels 0..depth-1 only, so rows never
    descend past level ``depth``. Heap arrays cover levels 0..depth
    (T = 2^(depth+1) - 1); without ``scan_last`` the last level is an
    e=-inf filler (the pure grower's never-scanned leaves), with it
    every node's gain/e is known exactly — the property the hybrid's
    commit cut relies on.

    Returns ``phase(bins_rm, gh, feature_mask, rng_key) -> dict`` with
    heap-ordered [T] candidate arrays (``e gain feat thr dl``), node
    stats (``sg sh cn out``), packed best rows ``rows`` [T, NB]
    (ops/split.pack_record_rows layout), cat fields ``ncat``/``catb``
    when categorical, the final per-row heap id ``heap`` [R], and —
    when ``collect_hists`` — the RAW (unconverted, physical-column)
    level histograms ``hists`` [T, Fp, B, 3] for pool seeding.
    """
    B = int(cfg.num_bin)
    hp: SplitHyperParams = cfg.hparams
    F = int(meta.num_bin.shape[0])          # logical feature count
    has_cat = meta_has_categorical(meta)
    MAXK = min(hp.max_cat_threshold, B) if has_cat else 0
    quantized = cfg.quantized
    hist_dtype = jnp.int32 if quantized else jnp.float32
    NEG = jnp.float32(-jnp.inf)
    n_scan = depth + (1 if scan_last else 0)

    bundled = bundle is not None
    if bundled:
        from ..io.bundling import decode_logical_bin, make_expand_hist
        expand_hist = make_expand_hist(bundle)
        b_group = jnp.asarray(bundle["group"], jnp.int32)        # [F]
        b_offset = jnp.asarray(bundle["offset"], jnp.int32)      # [F]
        b_nbin = jnp.asarray(bundle["num_bin"], jnp.int32)       # [F]
        b_default = jnp.asarray(bundle["default_bin"], jnp.int32)

    lvl_backend = _resolve_rm_backend(cfg.level_hist_backend or
                                      cfg.hist_rm_backend)
    use_scatter = lvl_backend == "scatter"
    use_pallas_level = lvl_backend == "pallas_level"
    use_blocks = not use_scatter
    # inner row-major backend for the blocks composition (also the
    # pallas_level fallback on tile-infeasible shapes)
    rm_backend = lvl_backend if lvl_backend in ("einsum", "pallas") \
        else "einsum"

    def scan_level(hist, sg, sh, cn, out, feature_mask):
        return jax.vmap(
            lambda hh, a, b, c, o: best_split_for_leaf(
                hh, a, b, c, o, meta, hp, feature_mask)
        )(hist, sg, sh, cn, out)

    # jaxlint: disable=JL002 — n_d/R/Fp are static Python ints at trace
    # time (the per-level node count and row count specialize the
    # program; one compile per level width, cached across trees)
    def level_hist(bins_p, gh, local, in_lvl, lsafe, bins_t, n_d, R, Fp):
        """Per-level [n_d, Fp, B, 3] dispatch over the three
        formulations; the pallas_level ladder falls back to blocks on
        tile-infeasible shapes (VMEM budget), loudly."""
        if use_pallas_level:
            from ..ops.hist_level_pallas import hist_level, level_tiles
            ft, br, ok = level_tiles(8, B, 512, n_d, R)
            # padding-economy bound: the segment-aligned layout carries
            # up to (n_d + 1) * br dead rows; when that exceeds ~4x the
            # real rows (deep near-empty levels, tiny datasets) the
            # kernel would mostly chew padding — the blocks composition
            # is strictly cheaper there
            if ok and (n_d + 1) * br <= 4 * R:
                g_in = gh
                if cfg.hist_dtype in ("bfloat16", "bf16") and \
                        gh.dtype == jnp.float32:
                    # the bf16 fast mode: gh rounded once, single-bf16
                    # contraction with f32 accumulation (same semantic
                    # as hist_rowmajor dtype="bfloat16"; f32 inputs
                    # otherwise take the exact bf16-triple path inside
                    # the kernel)
                    g_in = gh.astype(jnp.bfloat16)
                return hist_level(bins_p, g_in, local, in_lvl, n_d, B,
                                  block_rows=br,
                                  feature_tile=ft).astype(hist_dtype)
            _log_once(
                f"level histograms: pallas_level falls back to the "
                f"blocks composition with {rm_backend} "
                + (f"at num_bin={B} (VMEM budget)" if not ok else
                   f"for levels with >= {n_d} nodes at {R} rows "
                   "(alignment padding would dominate)"))
        if use_blocks:
            return hist_level_blocks(
                bins_p, gh, local, in_lvl, n_d, R, Fp, num_bin=B,
                input_dtype=cfg.hist_dtype, rm_backend=rm_backend,
                acc_dtype=hist_dtype)
        return hist_level_scatter(bins_t, gh, lsafe, in_lvl, n_d,
                                  num_bin=B, acc_dtype=hist_dtype)

    def phase(bins_rm, gh, feature_mask=None, rng_key=None):
        R, Fp = bins_rm.shape
        # scatter mode streams per FEATURE (one [R] scatter into a
        # cache-resident [n_d*B, 3] accumulator per column — measured
        # ~2x over a single (node, f, bin)-keyed scatter at 1M rows on
        # CPU, whose [R, Fp, 3] broadcast updates and multi-MB output
        # thrash); one uint8 transpose per tree feeds it
        bins_t = bins_rm.T if not use_blocks else None   # [Fp, R]

        if quantized:
            # shared helper => the SAME int8 rows and scales the
            # sequential tail derives from (rng_key included), so the
            # int32 histograms match bit for bit across the handoff
            gh, conv = quantize_gradients(cfg, gh, rng_key)
        else:
            conv = lambda hh: hh

        # ---- root stats (identical formulas to the sequential grower)
        if quantized:
            sums = conv(gh.sum(axis=0, dtype=jnp.int32))
        else:
            sums = gh.sum(axis=0)
        root_g, root_h, root_c = sums[0], sums[1], sums[2]
        root_out = calculate_splitted_leaf_output(
            root_g, root_h + 2 * K_EPSILON, hp, root_c, jnp.float32(0.0))

        heap = jnp.zeros(R, jnp.int32)   # per-row current heap node
        sg_d = root_g[None]
        sh_d = root_h[None]
        cn_d = root_c[None]
        out_d = root_out[None]
        e_par = None                      # e of this level's nodes

        # heap-ordered per-node collections (concatenated level lists)
        gain_l, e_l, feat_l, thr_l, dl_l, row_l = [], [], [], [], [], []
        sg_l, sh_l, cn_l, out_l = [sg_d], [sh_d], [cn_d], [out_d]
        ncat_l, catb_l = [], []
        hist_l = []

        for d in range(n_scan):
            n_d = 1 << d
            base = n_d - 1
            local = heap - base
            in_lvl = (local >= 0) & (local < n_d)
            lsafe = jnp.where(in_lvl, local, 0)

            # ---- segment histogram for every level-d node -----------
            # (physical columns; raw accumulator dtype)
            hist_raw = level_hist(bins_rm, gh, local, in_lvl, lsafe,
                                  bins_t, n_d, R, Fp)
            if collect_hists:
                hist_l.append(hist_raw)
            hist = conv(hist_raw)
            if bundled:
                # per-node logical expansion with the node's OWN totals
                # (≡ FixHistogram's default-bin reconstruction)
                hist = jax.vmap(expand_hist)(hist, sg_d, sh_d, cn_d)

            # ---- vmapped split scan --------------------------------
            recs = scan_level(hist, sg_d, sh_d, cn_d, out_d,
                              feature_mask)
            valid = recs.gain > 0.0
            e_d = (recs.gain if e_par is None
                   else jnp.minimum(recs.gain, e_par))
            e_d = jnp.where(valid, e_d, NEG)

            gain_l.append(recs.gain)
            e_l.append(e_d)
            feat_l.append(recs.feature)
            thr_l.append(recs.threshold)
            dl_l.append(recs.default_left)
            row_l.append(pack_record_rows(recs, has_cat))
            if has_cat:
                ncat_l.append(recs.num_cat)
                catb_l.append(recs.cat_bins)

            if d >= depth:
                break               # deepest scanned level: no descend

            # ---- children stats (heap order: left then right) -------
            sg_d = jnp.stack([recs.left_sum_gradient,
                              recs.right_sum_gradient], 1).reshape(-1)
            sh_d = jnp.stack([recs.left_sum_hessian,
                              recs.right_sum_hessian], 1).reshape(-1)
            cn_d = jnp.stack([recs.left_count,
                              recs.right_count], 1).reshape(-1)
            out_d = jnp.stack([recs.left_output,
                               recs.right_output], 1).reshape(-1)
            e_par = jnp.stack([e_d, e_d], 1).reshape(-1)
            sg_l.append(sg_d)
            sh_l.append(sh_d)
            cn_l.append(cn_d)
            out_l.append(out_d)

            # ---- partition: rows at valid nodes descend -------------
            f_row = jnp.maximum(recs.feature, 0)[lsafe]
            if bundled:
                col = jnp.take_along_axis(
                    bins_rm, b_group[f_row][:, None],
                    axis=1)[:, 0].astype(jnp.int32)
                col = decode_logical_bin(col, b_offset[f_row],
                                         b_nbin[f_row],
                                         b_default[f_row])
            else:
                col = jnp.take_along_axis(
                    bins_rm, f_row[:, None], axis=1)[:, 0].astype(
                        jnp.int32)
            go_left = _go_left_bins(col, recs.threshold[lsafe],
                                    recs.default_left[lsafe], f_row,
                                    meta)
            if has_cat:
                # per-row category sets: [R, MAXK] membership (the
                # per-node form of dense_bin.hpp SplitCategoricalInner;
                # bins not in the set, incl. bin 0, go right)
                in_set = jnp.any(
                    col[:, None] == recs.cat_bins[lsafe], axis=1)
                go_left = jnp.where(recs.num_cat[lsafe] > 0, in_set,
                                    go_left)
            descend = in_lvl & valid[lsafe]
            heap = jnp.where(
                descend,
                2 * heap + 1 + (~go_left).astype(jnp.int32), heap)

        if not scan_last:
            # depth-D nodes are never scanned: candidates with e = -inf
            n_leafrow = 1 << depth
            e_l.append(jnp.full(n_leafrow, NEG))
            gain_l.append(jnp.full(n_leafrow, NEG))
            feat_l.append(jnp.full(n_leafrow, -1, jnp.int32))
            thr_l.append(jnp.zeros(n_leafrow, jnp.int32))
            dl_l.append(jnp.zeros(n_leafrow, bool))
            inv = pack_record_rows(
                SplitRecord.invalid((), max_cat=MAXK), has_cat)
            row_l.append(jnp.broadcast_to(inv, (n_leafrow,) + inv.shape))
            if has_cat:
                ncat_l.append(jnp.zeros(n_leafrow, jnp.int32))
                catb_l.append(jnp.full((n_leafrow, MAXK), -1,
                                       jnp.int32))

        res = dict(
            heap=heap,
            e=jnp.concatenate(e_l),                    # [T]
            gain=jnp.concatenate(gain_l),
            feat=jnp.concatenate(feat_l),
            thr=jnp.concatenate(thr_l),
            dl=jnp.concatenate(dl_l),
            sg=jnp.concatenate(sg_l),
            sh=jnp.concatenate(sh_l),
            cn=jnp.concatenate(cn_l),
            out=jnp.concatenate(out_l),
            rows=jnp.concatenate(row_l),               # [T, NB]
        )
        if has_cat:
            res["ncat"] = jnp.concatenate(ncat_l)
            res["catb"] = jnp.concatenate(catb_l)      # [T, MAXK]
        if collect_hists:
            res["hists"] = jnp.concatenate(hist_l)     # [T, Fp, B, 3]
        return res

    return phase


def rank_and_slots(e_h, L: int, depth: int, cut_mask=None):
    """Rank heap candidates by e (descending, stable ties = heap order
    = parent-first) and run the per-level slot/eff propagation — the
    ONE place the leaf-numbering invariant lives (right child takes
    rank(parent) + 1 ≡ the sequential grower's ``new_leaf = i + 1``;
    ``eff[v]`` resolves to the slot of v's first non-selected
    ancestor-or-self). Shared by the pure grower (no cut) and the
    hybrid (``cut_mask`` = the depth-D0 node mask: the selected prefix
    additionally stops at the first rank held by a masked node — the
    exactness guard).

    Returns ``(rank, k, selected, slot, eff)`` where ``selected`` =
    rank < k over the [T] heap nodes (levels 0..depth).
    """
    T = int(e_h.shape[0])
    order = jnp.argsort(-e_h, stable=True)             # [T]
    rank = jnp.zeros(T, jnp.int32).at[order].set(
        jnp.arange(T, dtype=jnp.int32))
    k = jnp.minimum(jnp.int32(L - 1),
                    jnp.sum(e_h > 0.0).astype(jnp.int32))
    if cut_mask is not None:
        k = jnp.minimum(k, jnp.argmax(cut_mask[order]).astype(jnp.int32))
    selected = rank < k

    # slot[v]: the leaf slot v occupies while it is a leaf. left child
    # inherits the parent's slot; right child takes rank(parent) + 1.
    slot = jnp.full(T, -1, jnp.int32).at[0].set(0)
    # eff[v]: the FINAL leaf slot for rows whose node is v (or a
    # descendant of v once v stops splitting); -1 while still splitting
    eff = jnp.full(T, -1, jnp.int32).at[0].set(
        jnp.where(selected[0], -1, 0))
    for d in range(depth):
        base = (1 << d) - 1
        ids = base + jnp.arange(1 << d, dtype=jnp.int32)
        lc, rc = 2 * ids + 1, 2 * ids + 2
        ch = selected[ids]
        slot = slot.at[lc].set(jnp.where(ch, slot[ids], slot[lc]))
        slot = slot.at[rc].set(jnp.where(ch, rank[ids] + 1, slot[rc]))
        # resolved parents propagate; fresh leaves resolve unless they
        # are themselves selected
        par_eff = eff[ids]
        eff = eff.at[lc].set(jnp.where(
            par_eff >= 0, par_eff,
            jnp.where(ch & ~selected[lc], slot[ids], -1)))
        eff = eff.at[rc].set(jnp.where(
            par_eff >= 0, par_eff,
            jnp.where(ch & ~selected[rc], rank[ids] + 1, -1)))
    return rank, k, selected, slot, eff


def make_level_grower(cfg: GrowerConfig, meta: FeatureMeta, bundle=None):
    """Build ``grow(bins_rm, gh, feature_mask, cegb, rng_key)`` ->
    ``(TreeArrays, leaf_id)`` over row-major uint8/16 bins [R, F]
    ([R, G] physical groups when ``bundle`` is set) — the pure level
    mode for max_depth in [1, MAX_LEVEL_DEPTH]. Unbounded/deeper
    configs go through core/hybrid_grower.make_hybrid_grower. The row
    axis follows make_tree_grower's layout contract (pad/permute freely
    with gh = 0 on pad slots; sharded ingestion relies on it)."""
    L = int(cfg.num_leaves)
    D = int(cfg.max_depth)
    if not (1 <= D <= MAX_LEVEL_DEPTH):
        raise ValueError(
            f"pure level scheduling requires 1 <= max_depth <= "
            f"{MAX_LEVEL_DEPTH}, got {cfg.max_depth} (the hybrid "
            "grower serves deeper/unbounded configs)")
    hp = cfg.hparams
    B = int(cfg.num_bin)
    has_cat = meta_has_categorical(meta)
    MAXK = min(hp.max_cat_threshold, B) if has_cat else 0
    T_all = 2 ** (D + 1) - 1          # heap nodes incl. depth-D leaves
    phase = make_level_phase(cfg, meta, depth=D, scan_last=False,
                             bundle=bundle)

    def grow(bins_rm, gh, feature_mask=None, cegb=None, rng_key=None):
        del cegb                       # gated off by the engine
        R = bins_rm.shape[0]
        res = phase(bins_rm, gh, feature_mask, rng_key)
        heap = res["heap"]
        e_h, gain_h = res["e"], res["gain"]
        feat_h, thr_h, dl_h = res["feat"], res["thr"], res["dl"]
        sg_h, sh_h = res["sg"], res["sh"]
        cn_h, out_h = res["cn"], res["out"]

        # ---- rank by e + slot/eff propagation (shared helper) ------
        rank, k, chosen, slot, eff = rank_and_slots(e_h, L, D)

        leaf_id = jnp.maximum(eff[heap], 0)

        # ---- tree arrays -------------------------------------------
        # scatters use one extra DUMP slot for every unselected heap
        # node (duplicate dump writes carry only discarded garbage), so
        # real entries can never be clobbered
        ids_all = jnp.arange(T_all, dtype=jnp.int32)
        li = max(L - 1, 1)
        rk = jnp.where(chosen, rank, li)             # dump slot = li
        lc_all = jnp.minimum(2 * ids_all + 1, T_all - 1)
        rc_all = jnp.minimum(2 * ids_all + 2, T_all - 1)
        lptr = jnp.where(chosen[lc_all], rank[lc_all],
                         -(slot[lc_all] + 1))
        rptr = jnp.where(chosen[rc_all], rank[rc_all],
                         -(slot[rc_all] + 1))

        def node_scatter(vals, dtype=jnp.float32):
            return jnp.zeros(li + 1, dtype).at[rk].set(
                vals.astype(dtype))[:li]

        split_feature = node_scatter(feat_h, jnp.int32)
        threshold_bin = node_scatter(thr_h, jnp.int32)
        default_left = node_scatter(dl_h, bool)
        split_gain = node_scatter(gain_h)
        internal_value = node_scatter(out_h)
        internal_weight = node_scatter(sh_h)
        internal_count = node_scatter(cn_h)
        left_child = node_scatter(lptr, jnp.int32)
        right_child = node_scatter(rptr, jnp.int32)
        if has_cat:
            cat_count = node_scatter(res["ncat"], jnp.int32)
            tree_cat = jnp.full((li + 1, MAXK), -1, jnp.int32).at[
                rk].set(res["catb"])[:li]
        else:
            cat_count = None
            tree_cat = None

        # leaves: nodes with a chosen parent that are not chosen
        par_all = jnp.maximum((ids_all - 1) // 2, 0)
        is_leaf = (~chosen) & chosen[par_all] & (ids_all > 0)
        grew = k > 0
        lslot = jnp.where(is_leaf, slot, L)          # dump slot = L

        def leaf_scatter(vals, fill=0.0, dtype=jnp.float32):
            return jnp.full(L + 1, fill, dtype).at[lslot].set(
                vals.astype(dtype))[:L]

        zl = jnp.zeros(L, jnp.float32)
        leaf_value = jnp.where(grew, leaf_scatter(out_h), zl)
        leaf_weight = jnp.where(grew, leaf_scatter(sh_h), zl)
        leaf_count = jnp.where(grew, leaf_scatter(cn_h), zl)
        leaf_parent = jnp.where(
            grew, leaf_scatter(rank[par_all], fill=-1, dtype=jnp.int32),
            jnp.full(L, -1, jnp.int32))

        tree = TreeArrays(
            split_feature=split_feature,
            threshold_bin=threshold_bin,
            default_left=default_left,
            left_child=left_child,
            right_child=right_child,
            split_gain=split_gain,
            internal_value=internal_value,
            internal_weight=internal_weight,
            internal_count=internal_count,
            leaf_value=leaf_value,
            leaf_weight=leaf_weight,
            leaf_count=leaf_count,
            leaf_parent=leaf_parent,
            num_leaves=(k + 1).astype(jnp.int32),
            shrinkage=jnp.asarray(1.0, jnp.float32),
            cat_count=cat_count,
            cat_bins=tree_cat,
        )
        return tree, leaf_id

    return grow
