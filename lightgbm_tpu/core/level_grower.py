"""Level-synchronous best-first tree grower (round-6 architecture,
phase A: pure level mode for ``max_depth <= MAX_LEVEL_DEPTH``).

The sequential grower (core/grower.py) mirrors the reference's
leaf-wise loop (ref: serial_tree_learner.cpp:183-249): num_leaves-1
dependent steps, each dispatching ~40 kernels through the device
tunnel. This grower instead:

1. grows the FULL tree level by level — one segment-histogram pass,
   one vmapped split scan and one partition pass per DEPTH;
2. ranks every candidate node by e(v) = min(gain(u) for u on the
   root->v path) and keeps the top (num_leaves - 1): by the theorem
   validated in tests/test_levelwise_theory.py this reproduces the
   leaf-wise best-first tree exactly (expansion order = descending e,
   ties parent-first — stable argsort over heap ids gives both);
3. assembles TreeArrays + per-row leaf ids from the ranking with
   vectorized per-level slot/pointer passes — no sequential split
   loop at all.

Numerical note: per-node sums, outputs and child stats come from the
SAME SplitRecord fields the sequential grower uses, so the only
divergence channel is histogram accumulation order (level-batched vs
gathered-segment passes): bit-exact for dyadic gradients (e.g. a
binary objective's first tree), ordinary f32 reassociation noise
otherwise — each node accumulates only its own rows/blocks in every
formulation here, so the error scales with the node's own magnitude,
not the dataset's. Exact fp ties between UNRELATED candidate nodes
break by heap order here vs leaf-slot order sequentially (measure-zero
on real-valued gains).

Phase-A scope (the engine falls back to the sequential grower
otherwise): serial learner, numerical features, no EFB bundle, no
monotone/interaction/CEGB/forced/extra_trees/quantized, and
max_depth in [1, MAX_LEVEL_DEPTH] (the level hists are [nodes, F, B,
3]; past depth ~10 the dense node axis outgrows HBM — the hybrid
level+tail design in docs/TPU_RUNBOOK.md lifts this).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.histogram import hist_rowmajor
from ..ops.split import (FeatureMeta, SplitHyperParams, K_EPSILON,
                         best_split_for_leaf,
                         calculate_splitted_leaf_output)
from .grower import GrowerConfig, _go_left_bins
from .tree import TreeArrays

# dense level hists are [2^d, F, B, 3]: depth 10 = 1024 nodes is the
# last comfortable level at 28 x 256 (344 MB f32)
MAX_LEVEL_DEPTH = 10


def make_level_grower(cfg: GrowerConfig, meta: FeatureMeta):
    """Build ``grow(bins_rm, gh, feature_mask, cegb, rng_key)`` ->
    ``(TreeArrays, leaf_id)`` over row-major uint8/16 bins [R, F]."""
    L = int(cfg.num_leaves)
    D = int(cfg.max_depth)
    if not (1 <= D <= MAX_LEVEL_DEPTH):
        raise ValueError(
            f"level scheduling requires 1 <= max_depth <= "
            f"{MAX_LEVEL_DEPTH}, got {cfg.max_depth}")
    B = int(cfg.num_bin)
    hp: SplitHyperParams = cfg.hparams
    F = int(meta.num_bin.shape[0])
    T_all = 2 ** (D + 1) - 1          # heap nodes incl. depth-D leaves
    NEG = jnp.float32(-jnp.inf)

    # "scatter": one global scatter-add per level over (node, f, bin)
    # keys — the natural CPU kernel. Anything else ("einsum"/"pallas"):
    # the BLOCKS mode — rows sorted by node, whole-block histograms via
    # the batched row-major kernel summed per owner node, and the two
    # sub-block edges of every node via fixed-size masked windows. A
    # level is then ~4 large batched kernels instead of a scatter —
    # the MXU-friendly shape (docs/TPU_RUNBOOK.md round-6 design).
    use_blocks = cfg.hist_rm_backend != "scatter"
    # ADVICE r05: blocks mode runs the row-major kernel under vmap with
    # masked edge windows as small as bs=256 — a combination the pallas
    # kernel has never been device-measured on (CPU tests cover only
    # scatter/einsum; the r05 device A/B pinned einsum on both arms). A
    # batching or small-block defect would corrupt level histograms
    # silently, so every non-scatter backend maps to einsum here until
    # pallas-under-level has device A/B coverage. Blocks mode already
    # treats all non-scatter backends identically in shape/scheduling,
    # so this changes the kernel only, not the algorithm.
    rm_backend = "einsum" if use_blocks else cfg.hist_rm_backend

    def scan_level(hist, sg, sh, cn, out, feature_mask):
        return jax.vmap(
            lambda hh, a, b, c, o: best_split_for_leaf(
                hh, a, b, c, o, meta, hp, feature_mask)
        )(hist, sg, sh, cn, out)

    # jaxlint: disable=JL002 — n_d/R are static Python ints at trace time
    # (the per-level node count and row count specialize the program; one
    # compile per level width, cached across trees)
    def hist_blocks(binsi, gh, local, in_lvl, n_d, R):
        """[n_d, F, B, 3] per-node histograms, big-kernel formulation.

        Full blocks interior to a node are summed by a per-owner
        scatter over [G] block histograms (each node sums only its OWN
        blocks — no global prefix, so no cancellation error beyond the
        node's own magnitude); the two sub-block edges of every node
        come from fixed-size masked windows."""
        rm_hist = jax.vmap(lambda b, g: hist_rowmajor(
            b, g, num_bin=B, dtype=cfg.hist_dtype, backend=rm_backend))

        if n_d <= 2:
            # shallow levels: per-node masked full passes beat the
            # block/window machinery (n_d * R <= 2R vs ~3R rows)
            return jnp.stack([
                hist_rowmajor(
                    binsi,
                    gh * (in_lvl & (local == v))[:, None].astype(
                        gh.dtype),
                    num_bin=B, dtype=cfg.hist_dtype,
                    backend=rm_backend)
                for v in range(n_d)]).astype(jnp.float32)

        key = jnp.where(in_lvl, local, n_d)
        order = jnp.argsort(key, stable=True)
        sb = binsi[order]                              # [R, F]
        sgh = gh[order] * (key[order] < n_d)[:, None].astype(gh.dtype)
        # PHYSICAL rows per node (counts incl. bagged-out rows)
        cnt = jnp.zeros(n_d + 1, jnp.int32).at[key].add(1)[:n_d]
        starts = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(cnt)])  # [n_d + 1]
        s_v, e_v = starts[:-1], starts[1:]
        # block size ~ mean segment, pow2
        bs = 256
        while bs * n_d < R:
            bs *= 2
        G = -(-R // bs)
        pad = G * bs - R
        sb = jnp.pad(sb, ((0, pad), (0, 0)))
        sgh = jnp.pad(sgh, ((0, pad), (0, 0)))
        bh = rm_hist(sb.reshape(G, bs, F), sgh.reshape(G, bs, 3))
        # owner of each block: the node containing its first row, kept
        # only when the whole block lies inside that node; straddling
        # and out-of-range blocks go to the dump slot (their rows are
        # exactly what the edge windows cover)
        b_start = jnp.arange(G, dtype=jnp.int32) * bs
        owner = (jnp.searchsorted(starts, b_start, side="right")
                 .astype(jnp.int32) - 1)
        own_safe = jnp.clip(owner, 0, n_d - 1)
        interior = ((owner >= 0) & (owner < n_d) &
                    (b_start + bs <= e_v[own_safe]) &
                    (b_start >= s_v[own_safe]))
        tgt = jnp.where(interior, own_safe, n_d)       # dump slot n_d
        full = jnp.zeros((n_d + 1, F, B, 3), bh.dtype).at[tgt].add(
            bh)[:n_d]
        b0 = -(-s_v // bs)                             # ceil
        b1 = jnp.maximum(e_v // bs, b0)
        head_end = jnp.minimum(b0 * bs, e_v)
        tail_start = jnp.maximum(b1 * bs, head_end)

        def window_hist(w_start, w_len):
            idx = jnp.minimum(w_start[:, None] +
                              jnp.arange(bs, dtype=jnp.int32)[None, :],
                              G * bs - 1)              # [n_d, bs]
            wb = sb[idx]                               # [n_d, bs, F]
            wm = (jnp.arange(bs)[None, :] <
                  w_len[:, None]).astype(gh.dtype)
            wg = sgh[idx] * wm[:, :, None]
            return rm_hist(wb, wg)

        head = window_hist(s_v, head_end - s_v)
        tail = window_hist(tail_start, e_v - tail_start)
        return (full + head + tail).astype(jnp.float32)

    def grow(bins_rm, gh, feature_mask=None, cegb=None, rng_key=None):
        del cegb, rng_key             # gated off by the engine
        R = bins_rm.shape[0]
        binsi = bins_rm.astype(jnp.int32)             # [R, F]
        f_idx = jnp.arange(F, dtype=jnp.int32)

        # ---- root stats (identical formulas to the sequential grower)
        sums = gh.sum(axis=0)
        root_g, root_h, root_c = sums[0], sums[1], sums[2]
        root_out = calculate_splitted_leaf_output(
            root_g, root_h + 2 * K_EPSILON, hp, root_c, jnp.float32(0.0))

        heap = jnp.zeros(R, jnp.int32)   # per-row current heap node
        sg_d = root_g[None]
        sh_d = root_h[None]
        cn_d = root_c[None]
        out_d = root_out[None]
        e_par = None                      # e of this level's nodes

        # heap-ordered per-node collections (concatenated level lists)
        gain_l, e_l, feat_l, thr_l, dl_l = [], [], [], [], []
        sg_l, sh_l, cn_l, out_l = [sg_d], [sh_d], [cn_d], [out_d]

        for d in range(D):
            n_d = 1 << d
            base = n_d - 1
            local = heap - base
            in_lvl = (local >= 0) & (local < n_d)
            lsafe = jnp.where(in_lvl, local, 0)

            # ---- segment histogram for every level-d node -----------
            if use_blocks:
                hist = hist_blocks(binsi, gh, local, in_lvl, n_d, R)
            else:
                ghm = gh * in_lvl[:, None].astype(gh.dtype)
                keys = (lsafe[:, None] * F + f_idx[None, :]) * B + binsi
                vals = jnp.broadcast_to(ghm[:, None, :], (R, F, 3))
                hist = jnp.zeros((n_d * F * B, 3), jnp.float32).at[
                    keys.reshape(-1)].add(vals.reshape(-1, 3))
                hist = hist.reshape(n_d, F, B, 3)

            # ---- vmapped split scan --------------------------------
            recs = scan_level(hist, sg_d, sh_d, cn_d, out_d,
                              feature_mask)
            valid = recs.gain > 0.0
            e_d = (recs.gain if e_par is None
                   else jnp.minimum(recs.gain, e_par))
            e_d = jnp.where(valid, e_d, NEG)

            gain_l.append(recs.gain)
            e_l.append(e_d)
            feat_l.append(recs.feature)
            thr_l.append(recs.threshold)
            dl_l.append(recs.default_left)

            # ---- children stats (heap order: left then right) -------
            sg_d = jnp.stack([recs.left_sum_gradient,
                              recs.right_sum_gradient], 1).reshape(-1)
            sh_d = jnp.stack([recs.left_sum_hessian,
                              recs.right_sum_hessian], 1).reshape(-1)
            cn_d = jnp.stack([recs.left_count,
                              recs.right_count], 1).reshape(-1)
            out_d = jnp.stack([recs.left_output,
                               recs.right_output], 1).reshape(-1)
            e_par = jnp.stack([e_d, e_d], 1).reshape(-1)
            sg_l.append(sg_d)
            sh_l.append(sh_d)
            cn_l.append(cn_d)
            out_l.append(out_d)

            # ---- partition: rows at valid nodes descend -------------
            f_row = jnp.maximum(recs.feature, 0)[lsafe]
            col = jnp.take_along_axis(binsi, f_row[:, None],
                                      axis=1)[:, 0]
            go_left = _go_left_bins(col, recs.threshold[lsafe],
                                    recs.default_left[lsafe], f_row,
                                    meta)
            descend = in_lvl & valid[lsafe]
            heap = jnp.where(
                descend,
                2 * heap + 1 + (~go_left).astype(jnp.int32), heap)

        # depth-D nodes are never scanned: candidates with e = -inf
        n_leafrow = 1 << D
        e_l.append(jnp.full(n_leafrow, NEG))
        gain_l.append(jnp.full(n_leafrow, NEG))
        feat_l.append(jnp.full(n_leafrow, -1, jnp.int32))
        thr_l.append(jnp.zeros(n_leafrow, jnp.int32))
        dl_l.append(jnp.zeros(n_leafrow, bool))

        e_h = jnp.concatenate(e_l)                     # [T_all]
        gain_h = jnp.concatenate(gain_l)
        feat_h = jnp.concatenate(feat_l)
        thr_h = jnp.concatenate(thr_l)
        dl_h = jnp.concatenate(dl_l)
        sg_h = jnp.concatenate(sg_l)
        sh_h = jnp.concatenate(sh_l)
        cn_h = jnp.concatenate(cn_l)
        out_h = jnp.concatenate(out_l)

        # ---- rank by e desc; stable ties keep heap order, which is
        # exactly parent-first-then-smaller-id ------------------------
        order = jnp.argsort(-e_h, stable=True)         # [T_all]
        rank = jnp.zeros(T_all, jnp.int32).at[order].set(
            jnp.arange(T_all, dtype=jnp.int32))
        k = jnp.minimum(jnp.int32(L - 1),
                        jnp.sum(e_h > 0.0).astype(jnp.int32))
        chosen = rank < k

        # ---- slots: per-level top-down -----------------------------
        # slot[v]: the leaf slot v occupies while it is a leaf. left
        # child inherits the parent's slot; right child takes
        # rank(parent) + 1 (the sequential grower's new_leaf = i + 1).
        slot = jnp.full(T_all, -1, jnp.int32).at[0].set(0)
        # eff[v]: the FINAL leaf slot for rows whose node is v (or a
        # descendant of v once v stops splitting)
        eff = jnp.full(T_all, -1, jnp.int32).at[0].set(
            jnp.where(chosen[0], -1, 0))
        for d in range(D):
            base = (1 << d) - 1
            ids = base + jnp.arange(1 << d, dtype=jnp.int32)
            lc, rc = 2 * ids + 1, 2 * ids + 2
            ch = chosen[ids]
            slot = slot.at[lc].set(
                jnp.where(ch, slot[ids], slot[lc]))
            slot = slot.at[rc].set(
                jnp.where(ch, rank[ids] + 1, slot[rc]))
            # resolved parents propagate; fresh leaves resolve unless
            # they are themselves chosen
            par_eff = eff[ids]
            eff = eff.at[lc].set(jnp.where(
                par_eff >= 0, par_eff,
                jnp.where(ch & ~chosen[lc], slot[ids], -1)))
            eff = eff.at[rc].set(jnp.where(
                par_eff >= 0, par_eff,
                jnp.where(ch & ~chosen[rc], rank[ids] + 1, -1)))

        leaf_id = jnp.maximum(eff[heap], 0)

        # ---- tree arrays -------------------------------------------
        # scatters use one extra DUMP slot for every unselected heap
        # node (duplicate dump writes carry only discarded garbage), so
        # real entries can never be clobbered
        ids_all = jnp.arange(T_all, dtype=jnp.int32)
        li = max(L - 1, 1)
        rk = jnp.where(chosen, rank, li)             # dump slot = li
        lc_all = jnp.minimum(2 * ids_all + 1, T_all - 1)
        rc_all = jnp.minimum(2 * ids_all + 2, T_all - 1)
        lptr = jnp.where(chosen[lc_all], rank[lc_all],
                         -(slot[lc_all] + 1))
        rptr = jnp.where(chosen[rc_all], rank[rc_all],
                         -(slot[rc_all] + 1))

        def node_scatter(vals, dtype=jnp.float32):
            return jnp.zeros(li + 1, dtype).at[rk].set(
                vals.astype(dtype))[:li]

        split_feature = node_scatter(feat_h, jnp.int32)
        threshold_bin = node_scatter(thr_h, jnp.int32)
        default_left = node_scatter(dl_h, bool)
        split_gain = node_scatter(gain_h)
        internal_value = node_scatter(out_h)
        internal_weight = node_scatter(sh_h)
        internal_count = node_scatter(cn_h)
        left_child = node_scatter(lptr, jnp.int32)
        right_child = node_scatter(rptr, jnp.int32)

        # leaves: nodes with a chosen parent that are not chosen
        par_all = jnp.maximum((ids_all - 1) // 2, 0)
        is_leaf = (~chosen) & chosen[par_all] & (ids_all > 0)
        grew = k > 0
        lslot = jnp.where(is_leaf, slot, L)          # dump slot = L

        def leaf_scatter(vals, fill=0.0, dtype=jnp.float32):
            return jnp.full(L + 1, fill, dtype).at[lslot].set(
                vals.astype(dtype))[:L]

        zl = jnp.zeros(L, jnp.float32)
        leaf_value = jnp.where(grew, leaf_scatter(out_h), zl)
        leaf_weight = jnp.where(grew, leaf_scatter(sh_h), zl)
        leaf_count = jnp.where(grew, leaf_scatter(cn_h), zl)
        leaf_parent = jnp.where(
            grew, leaf_scatter(rank[par_all], fill=-1, dtype=jnp.int32),
            jnp.full(L, -1, jnp.int32))

        tree = TreeArrays(
            split_feature=split_feature,
            threshold_bin=threshold_bin,
            default_left=default_left,
            left_child=left_child,
            right_child=right_child,
            split_gain=split_gain,
            internal_value=internal_value,
            internal_weight=internal_weight,
            internal_count=internal_count,
            leaf_value=leaf_value,
            leaf_weight=leaf_weight,
            leaf_count=leaf_count,
            leaf_parent=leaf_parent,
            num_leaves=(k + 1).astype(jnp.int32),
            shrinkage=jnp.asarray(1.0, jnp.float32),
        )
        return tree, leaf_id

    return grow
