"""TreeSHAP feature contributions (pred_contrib).

TPU-native equivalent of the reference SHAP path
(ref: include/LightGBM/tree.h ExpectedValue/TreeSHAP declarations,
src/io/tree.cpp TreeSHAP recursion — Lundberg & Lee's exact polynomial-time
algorithm over decision paths; exposed via predict(pred_contrib=True),
c_api.cpp PredictType kPredictContrib).

Implementation is the standard EXTEND/UNWIND path-polynomial recursion,
written against our structure-of-arrays HostTree.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .tree import HostTree


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, f=-1, z=1.0, o=1.0, w=1.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w


def _extend(path: List[_PathElement], unique_depth: int,
            zero_fraction: float, one_fraction: float,
            feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight *
                           (unique_depth - i) / (unique_depth + 1))


def _unwind(path: List[_PathElement], unique_depth: int,
            path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - path[i].pweight * zero_fraction * \
                (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1) /
                               (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * \
                ((unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / zero_fraction) / \
                ((unique_depth - i) / (unique_depth + 1))
    return total


def _expected_value(t: HostTree, node: int) -> float:
    """Weighted mean of leaf values below node (ref: Tree::ExpectedValue)."""
    if node < 0:
        return float(t.leaf_value[-(node + 1)])
    lw = _subtree_weight(t, int(t.left_child[node]))
    rw = _subtree_weight(t, int(t.right_child[node]))
    tot = lw + rw
    if tot <= 0:
        return 0.0
    return (lw * _expected_value(t, int(t.left_child[node])) +
            rw * _expected_value(t, int(t.right_child[node]))) / tot


def _subtree_weight(t: HostTree, node: int) -> float:
    if node < 0:
        return float(t.leaf_count[-(node + 1)])
    return float(t.internal_count[node])


def _decision_path(t: HostTree, node: int, x: np.ndarray) -> bool:
    """Which child does row x take at internal node? (hot/cold)."""
    f = int(t.split_feature[node])
    dt = int(t.decision_type[node])
    val = x[f]
    isnan = np.isnan(val)
    dl = bool(dt & 2)
    mtype = (dt >> 2) & 3
    if dt & 1:  # categorical: bitset membership on the raw value
        return bool(t._cat_in_bitset(
            np.asarray([node]), np.asarray([0.0 if isnan else val]),
            np.asarray([isnan]))[0])
    if mtype == 2 and isnan:
        return dl
    v0 = 0.0 if isnan else val
    if mtype == 1 and abs(v0) <= 1e-35:
        return dl
    return v0 <= t.threshold_real[node]


def _tree_shap(t: HostTree, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int) -> None:
    """ref: Tree::TreeSHAP recursion (src/io/tree.cpp)."""
    path = [
        _PathElement() for _ in range(unique_depth + 1)
    ]
    for i in range(unique_depth):
        src = parent_path[i]
        path[i].feature_index = src.feature_index
        path[i].zero_fraction = src.zero_fraction
        path[i].one_fraction = src.one_fraction
        path[i].pweight = src.pweight
    _extend(path, unique_depth, parent_zero_fraction, parent_one_fraction,
            parent_feature_index)

    if node < 0:  # leaf
        leaf = -(node + 1)
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction -
                                          el.zero_fraction) * \
                float(t.leaf_value[leaf])
        return

    hot_left = _decision_path(t, node, x)
    hot = int(t.left_child[node]) if hot_left else int(t.right_child[node])
    cold = int(t.right_child[node]) if hot_left else int(t.left_child[node])
    w_node = _subtree_weight(t, node)
    hot_zero_fraction = _subtree_weight(t, hot) / w_node if w_node else 0.0
    cold_zero_fraction = _subtree_weight(t, cold) / w_node if w_node else 0.0
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # dedup features on the path
    f = int(t.split_feature[node])
    path_index = next((i for i in range(unique_depth + 1)
                       if path[i].feature_index == f), unique_depth + 1)
    if path_index <= unique_depth:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(t, x, phi, hot, unique_depth + 1, path,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, f)
    _tree_shap(t, x, phi, cold, unique_depth + 1, path,
               cold_zero_fraction * incoming_zero_fraction, 0.0, f)


def shap_one_tree(t: HostTree, x: np.ndarray, num_features: int
                  ) -> np.ndarray:
    """phi[num_features + 1]; last slot is the expected value (bias)."""
    phi = np.zeros(num_features + 1)
    if t.num_leaves <= 1:
        phi[-1] += float(t.leaf_value[0])
        return phi
    phi[-1] += _expected_value(t, 0)
    _tree_shap(t, x, phi, 0, 0, [], 1.0, 1.0, -1)
    return phi


# ---------------------------------------------------------------------------
# Row-batched TreeSHAP
# ---------------------------------------------------------------------------
# The reference runs the per-row recursion under OMP
# (src/application/predictor.hpp:31 kPredictContrib). The same exact
# algorithm vectorizes over rows instead: the recursion's branch
# structure, zero_fractions (cover ratios) and feature dedup depend only
# on the TREE, while each row contributes exactly (a) which child is
# "hot" at every node and (b) {0,1} one_fraction products — so one DFS
# per tree carrying [N]-shaped pweight/one_fraction arrays reproduces
# _tree_shap for all rows at once (numpy does the row loop in C).


def _decisions_all(t: HostTree, X: np.ndarray) -> np.ndarray:
    """bool [I, N]: does each row go LEFT at each internal node?
    (vectorized _decision_path; same missing/categorical rules)."""
    n_int = len(t.split_feature)
    N = X.shape[0]
    out = np.zeros((n_int, N), bool)
    for node in range(n_int):
        f = int(t.split_feature[node])
        dt = int(t.decision_type[node])
        v = X[:, f].astype(np.float64)
        isnan = np.isnan(v)
        dl = bool(dt & 2)
        mtype = (dt >> 2) & 3
        v0 = np.where(isnan, 0.0, v)
        if dt & 1:  # categorical: bitset membership on the raw value
            out[node] = t._cat_in_bitset(
                np.full(N, node, np.int64), v0, isnan)
            continue
        res = v0 <= t.threshold_real[node]
        if mtype == 1:
            res = np.where(np.abs(v0) <= 1e-35, dl, res)
        elif mtype == 2:
            res = np.where(isnan, dl, res)
        out[node] = res
    return out


def shap_tree_batch(t: HostTree, X: np.ndarray, num_features: int,
                    goes_left: np.ndarray = None) -> np.ndarray:
    """Exact TreeSHAP for all rows of X against one tree: [N, F+1].

    ``goes_left`` (bool [I, N], the ``_decisions_all`` matrix) lets a
    caller that walks the SAME rows repeatedly — start/num_iteration
    windows over one matrix, the serving host oracle replaying a
    request — pay the decision sweep once instead of per call; omitted,
    it is computed here (the original behavior, bit-identical)."""
    N = X.shape[0]
    phi = np.zeros((N, num_features + 1))
    if t.num_leaves <= 1:
        phi[:, -1] += float(t.leaf_value[0])
        return phi
    phi[:, -1] += _expected_value(t, 0)
    if goes_left is None:
        goes_left = _decisions_all(t, X)

    def recurse(node, d, feats, zf, of, pw, pz, po, pf):
        # copy-extend the parent path (siblings must not see mutations);
        # feats/zf are per-element scalars, of/pw are [N] rows
        feats = np.concatenate([feats[:d], [pf]])
        zf = np.concatenate([zf[:d], [pz]])
        of = np.vstack([of[:d], po[None, :]])
        pw = np.vstack([pw[:d], np.full((1, N), 1.0 if d == 0 else 0.0)])
        # EXTEND (scalar _extend, pweights vectorized over rows)
        for i in range(d - 1, -1, -1):
            pw[i + 1] += po * pw[i] * ((i + 1) / (d + 1))
            pw[i] = pz * pw[i] * ((d - i) / (d + 1))

        if node < 0:  # leaf: UNWOUND path sums -> contributions
            leaf_val = float(t.leaf_value[-(node + 1)])
            for pi in range(1, d + 1):
                one = of[pi]
                zero = zf[pi]
                next_one = pw[d].copy()
                total = np.zeros(N)
                nz = one != 0
                for i in range(d - 1, -1, -1):
                    # rows with one==0 use the zero-division-free branch
                    tmp = np.where(
                        nz, next_one * ((d + 1) / ((i + 1))), 0.0)
                    tmp = np.divide(tmp, np.where(nz, one, 1.0))
                    total += np.where(
                        nz, tmp,
                        pw[i] / (zero * ((d - i) / (d + 1))))
                    next_one = np.where(
                        nz, pw[i] - tmp * zero * ((d - i) / (d + 1)),
                        next_one)
                phi[:, feats[pi]] += (total * (one - zero) * leaf_val)
            return

        w_node = _subtree_weight(t, node)
        lc = int(t.left_child[node])
        rc = int(t.right_child[node])
        z_l = _subtree_weight(t, lc) / w_node if w_node else 0.0
        z_r = _subtree_weight(t, rc) / w_node if w_node else 0.0
        inc_z = 1.0
        inc_o = np.ones(N)
        f = int(t.split_feature[node])
        # dedup: UNWIND a previous occurrence of this feature
        pi = next((i for i in range(d + 1) if feats[i] == f), d + 1)
        if pi <= d:
            inc_z = zf[pi]
            inc_o = of[pi].copy()
            # vectorized _unwind
            one = of[pi]
            zero = zf[pi]
            nz = one != 0
            next_one = pw[d].copy()
            for i in range(d - 1, -1, -1):
                tmp_pw = pw[i].copy()
                a = np.divide(next_one * ((d + 1) / (i + 1)),
                              np.where(nz, one, 1.0))
                b = tmp_pw * ((d + 1) / (zero * (d - i)))
                pw[i] = np.where(nz, a, b)
                next_one = np.where(
                    nz, tmp_pw - pw[i] * zero * ((d - i) / (d + 1)),
                    next_one)
            feats[pi:d] = feats[pi + 1:d + 1].copy()
            zf[pi:d] = zf[pi + 1:d + 1].copy()
            of[pi:d] = of[pi + 1:d + 1].copy()
            d -= 1

        left_hot = goes_left[node]
        recurse(lc, d + 1, feats, zf, of, pw,
                z_l * inc_z, inc_o * left_hot, f)
        recurse(rc, d + 1, feats, zf, of, pw,
                z_r * inc_z, inc_o * ~left_hot, f)

    # rows with one_fraction==0 evaluate (and discard) the other
    # branch's division — identical inf/0 algebra to the scalar code,
    # without the warnings
    with np.errstate(divide="ignore", invalid="ignore"):
        recurse(0, 0, np.zeros(0, np.int64), np.zeros(0),
                np.zeros((0, N)), np.zeros((0, N)), 1.0, np.ones(N), -1)
    return phi


def _native_tree_shap(t: HostTree, X64: np.ndarray, out: np.ndarray,
                      base: int, lib) -> bool:
    """Accumulate one tree's contributions via the C++ kernel
    (native/shap.cpp — the reference's OMP-predictor architecture,
    predictor.hpp:31). Returns False if the tree shape can't go native
    (caller falls back to the numpy batch)."""
    import ctypes
    n_int = len(t.split_feature)
    if n_int == 0:
        return False
    if getattr(t, "is_linear", False):
        return False  # keep whatever the python path does for linear
    c_i32 = ctypes.POINTER(ctypes.c_int32)
    c_f64 = ctypes.POINTER(ctypes.c_double)
    c_u32 = ctypes.POINTER(ctypes.c_uint32)
    as_ = lambda a, dt: np.ascontiguousarray(a, dtype=dt)
    sf = as_(t.split_feature, np.int32)
    th = as_(t.threshold_real, np.float64)
    dt_ = as_(t.decision_type, np.int32)
    lc = as_(t.left_child, np.int32)
    rc = as_(t.right_child, np.int32)
    lv = as_(t.leaf_value, np.float64)
    lcnt = as_(t.leaf_count, np.float64)
    icnt = as_(t.internal_count, np.float64)
    num_cat = int(getattr(t, "num_cat", 0) or 0)
    if num_cat > 0:
        cb = as_(t.cat_boundaries, np.int32)
        ct = as_(t.cat_threshold, np.uint32)
        n_words = len(ct)
        cb_p = cb.ctypes.data_as(c_i32)
        ct_p = ct.ctypes.data_as(c_u32)
    else:
        cb = ct = None
        n_words = 0
        cb_p = ctypes.cast(None, c_i32)
        ct_p = ctypes.cast(None, c_u32)
    # bias column excluded: out_stride walks full rows, base offsets the
    # class block; the expected value is added by the caller
    sub = out[:, base:]
    rc_code = lib.lgbm_tree_shap_batch(
        sf.ctypes.data_as(c_i32), th.ctypes.data_as(c_f64),
        dt_.ctypes.data_as(c_i32), lc.ctypes.data_as(c_i32),
        rc.ctypes.data_as(c_i32), lv.ctypes.data_as(c_f64),
        lcnt.ctypes.data_as(c_f64), icnt.ctypes.data_as(c_f64),
        np.int32(n_int), cb_p, ct_p, np.int32(num_cat),
        np.int32(n_words), X64.ctypes.data_as(c_f64),
        np.int64(X64.shape[0]), np.int32(X64.shape[1]),
        sub.ctypes.data_as(c_f64), np.int64(out.strides[0] // 8),
        np.int32(0))
    return rc_code == 0


def predict_contrib(engine, X: np.ndarray, start_iteration: int,
                    end_iteration: int, row_chunk: int = 16384,
                    decisions: dict = None) -> np.ndarray:
    """SHAP contributions [N, (F+1)*K] (ref: PredictType kPredictContrib,
    layout matches the reference: per-class blocks of F+1).

    Dispatch: the C++ row-parallel kernel when the native library is
    available (1M-row scale), else the numpy row-batched DFS in chunks
    (path copies hold O(depth^2 * chunk) floats). Both reproduce the
    scalar recursion exactly in f64.

    ``decisions`` maps model index (``it * K + k``) to that tree's
    ``_decisions_all`` bool [I, N] matrix over the SAME rows as ``X``
    — the numpy path slices it per row chunk instead of re-walking
    every internal node's split per call (the ISSUE 20 fix for callers
    that explain one matrix across several iteration windows). The
    native kernel computes decisions in C and ignores it."""
    K = engine.num_tree_per_iteration
    F = engine.max_feature_idx + 1
    N = X.shape[0]
    out = np.zeros((N, (F + 1) * K))
    lib = None
    try:
        from ..native import get_lib
        lib = get_lib()
        if lib is not None and not hasattr(lib, "lgbm_tree_shap_batch"):
            lib = None
    except Exception:
        lib = None
    X64 = np.ascontiguousarray(X, dtype=np.float64) if lib is not None \
        else None
    for it in range(start_iteration, end_iteration):
        for k in range(K):
            t = engine.models[it * K + k]
            base = k * (F + 1)
            if t.num_leaves <= 1:
                out[:, base + F] += float(t.leaf_value[0])
                continue
            if lib is not None and _native_tree_shap(t, X64, out, base,
                                                     lib):
                out[:, base + F] += _expected_value(t, 0)
                continue
            gl = None if decisions is None else \
                decisions.get(it * K + k)
            for lo in range(0, N, row_chunk):
                hi = min(lo + row_chunk, N)
                Xc = np.ascontiguousarray(X[lo:hi])
                out[lo:hi, base:base + F + 1] += shap_tree_batch(
                    t, Xc, F,
                    None if gl is None else gl[:, lo:hi])
    return out.reshape(N, -1) if K > 1 else out
