"""TreeSHAP feature contributions (pred_contrib).

TPU-native equivalent of the reference SHAP path
(ref: include/LightGBM/tree.h ExpectedValue/TreeSHAP declarations,
src/io/tree.cpp TreeSHAP recursion — Lundberg & Lee's exact polynomial-time
algorithm over decision paths; exposed via predict(pred_contrib=True),
c_api.cpp PredictType kPredictContrib).

Implementation is the standard EXTEND/UNWIND path-polynomial recursion,
written against our structure-of-arrays HostTree.
"""
from __future__ import annotations

from typing import List

import numpy as np

from .tree import HostTree


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction",
                 "pweight")

    def __init__(self, f=-1, z=1.0, o=1.0, w=1.0):
        self.feature_index = f
        self.zero_fraction = z
        self.one_fraction = o
        self.pweight = w


def _extend(path: List[_PathElement], unique_depth: int,
            zero_fraction: float, one_fraction: float,
            feature_index: int) -> None:
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += (one_fraction * path[i].pweight * (i + 1)
                                / (unique_depth + 1))
        path[i].pweight = (zero_fraction * path[i].pweight *
                           (unique_depth - i) / (unique_depth + 1))


def _unwind(path: List[_PathElement], unique_depth: int,
            path_index: int) -> None:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = (next_one_portion * (unique_depth + 1)
                               / ((i + 1) * one_fraction))
            next_one_portion = tmp - path[i].pweight * zero_fraction * \
                (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = (path[i].pweight * (unique_depth + 1) /
                               (zero_fraction * (unique_depth - i)))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int,
                      path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = (next_one_portion * (unique_depth + 1)
                   / ((i + 1) * one_fraction))
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * \
                ((unique_depth - i) / (unique_depth + 1))
        else:
            total += (path[i].pweight / zero_fraction) / \
                ((unique_depth - i) / (unique_depth + 1))
    return total


def _expected_value(t: HostTree, node: int) -> float:
    """Weighted mean of leaf values below node (ref: Tree::ExpectedValue)."""
    if node < 0:
        return float(t.leaf_value[-(node + 1)])
    lw = _subtree_weight(t, int(t.left_child[node]))
    rw = _subtree_weight(t, int(t.right_child[node]))
    tot = lw + rw
    if tot <= 0:
        return 0.0
    return (lw * _expected_value(t, int(t.left_child[node])) +
            rw * _expected_value(t, int(t.right_child[node]))) / tot


def _subtree_weight(t: HostTree, node: int) -> float:
    if node < 0:
        return float(t.leaf_count[-(node + 1)])
    return float(t.internal_count[node])


def _decision_path(t: HostTree, node: int, x: np.ndarray) -> bool:
    """Which child does row x take at internal node? (hot/cold)."""
    f = int(t.split_feature[node])
    dt = int(t.decision_type[node])
    val = x[f]
    isnan = np.isnan(val)
    dl = bool(dt & 2)
    mtype = (dt >> 2) & 3
    if dt & 1:  # categorical: bitset membership on the raw value
        return bool(t._cat_in_bitset(
            np.asarray([node]), np.asarray([0.0 if isnan else val]),
            np.asarray([isnan]))[0])
    if mtype == 2 and isnan:
        return dl
    v0 = 0.0 if isnan else val
    if mtype == 1 and abs(v0) <= 1e-35:
        return dl
    return v0 <= t.threshold_real[node]


def _tree_shap(t: HostTree, x: np.ndarray, phi: np.ndarray, node: int,
               unique_depth: int, parent_path: List[_PathElement],
               parent_zero_fraction: float, parent_one_fraction: float,
               parent_feature_index: int) -> None:
    """ref: Tree::TreeSHAP recursion (src/io/tree.cpp)."""
    path = [
        _PathElement() for _ in range(unique_depth + 1)
    ]
    for i in range(unique_depth):
        src = parent_path[i]
        path[i].feature_index = src.feature_index
        path[i].zero_fraction = src.zero_fraction
        path[i].one_fraction = src.one_fraction
        path[i].pweight = src.pweight
    _extend(path, unique_depth, parent_zero_fraction, parent_one_fraction,
            parent_feature_index)

    if node < 0:  # leaf
        leaf = -(node + 1)
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction -
                                          el.zero_fraction) * \
                float(t.leaf_value[leaf])
        return

    hot_left = _decision_path(t, node, x)
    hot = int(t.left_child[node]) if hot_left else int(t.right_child[node])
    cold = int(t.right_child[node]) if hot_left else int(t.left_child[node])
    w_node = _subtree_weight(t, node)
    hot_zero_fraction = _subtree_weight(t, hot) / w_node if w_node else 0.0
    cold_zero_fraction = _subtree_weight(t, cold) / w_node if w_node else 0.0
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # dedup features on the path
    f = int(t.split_feature[node])
    path_index = next((i for i in range(unique_depth + 1)
                       if path[i].feature_index == f), unique_depth + 1)
    if path_index <= unique_depth:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap(t, x, phi, hot, unique_depth + 1, path,
               hot_zero_fraction * incoming_zero_fraction,
               incoming_one_fraction, f)
    _tree_shap(t, x, phi, cold, unique_depth + 1, path,
               cold_zero_fraction * incoming_zero_fraction, 0.0, f)


def shap_one_tree(t: HostTree, x: np.ndarray, num_features: int
                  ) -> np.ndarray:
    """phi[num_features + 1]; last slot is the expected value (bias)."""
    phi = np.zeros(num_features + 1)
    if t.num_leaves <= 1:
        phi[-1] += float(t.leaf_value[0])
        return phi
    phi[-1] += _expected_value(t, 0)
    _tree_shap(t, x, phi, 0, 0, [], 1.0, 1.0, -1)
    return phi


def predict_contrib(engine, X: np.ndarray, start_iteration: int,
                    end_iteration: int) -> np.ndarray:
    """SHAP contributions [N, (F+1)*K] (ref: PredictType kPredictContrib,
    layout matches the reference: per-class blocks of F+1)."""
    K = engine.num_tree_per_iteration
    F = engine.max_feature_idx + 1
    N = X.shape[0]
    out = np.zeros((N, (F + 1) * K))
    for it in range(start_iteration, end_iteration):
        for k in range(K):
            t = engine.models[it * K + k]
            base = k * (F + 1)
            for r in range(N):
                out[r, base:base + F + 1] += shap_one_tree(t, X[r], F)
    return out.reshape(N, -1) if K > 1 else out
