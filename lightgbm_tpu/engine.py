"""Training entry points: train() and cv().

TPU-native equivalent of python-package/lightgbm/engine.py
(ref: train() :109-353 — param normalization, callback orchestration,
early-stopping injection :275-288, update loop :310-323; cv()/CVBooster
:356+).
"""
from __future__ import annotations

import collections
import copy
import json
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from . import callback as callback_module
from .basic import Booster, Dataset, LightGBMError
from .callback import CallbackEnv, EarlyStopException
from .config import Config, _ConfigAliases
from .utils import log

__all__ = ["train", "cv", "CVBooster"]


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[List[Dataset]] = None,
          valid_names: Optional[List[str]] = None,
          feval=None, init_model: Optional[Union[str, Booster]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[List[Callable]] = None,
          resume_from: Optional[str] = None) -> Booster:
    """Train one model (ref: engine.py:109).

    ``resume_from``: directory of checkpoints written by
    ``callback.checkpoint_callback``. The newest CRC-valid checkpoint
    is loaded (corrupt/partial files are skipped with a warning) and
    training continues from its iteration; ``num_boost_round`` is the
    TOTAL round target, so the same ``train(...)`` call can be re-run
    verbatim after a crash and it finishes the originally requested
    run. With no valid checkpoint in the directory, training starts
    fresh. See README "Fault tolerance & checkpointing".
    """
    params = copy.deepcopy(params) if params else {}
    # persistent compile cache (ISSUE 4): point XLA at the configured
    # on-disk cache BEFORE any program compiles, so a relaunched/resumed
    # run (crash recovery, supervisor retry) skips the multi-minute
    # grower compile instead of repaying it. Env-driven supervisors
    # (LGBM_TPU_COMPILE_CACHE / legacy LGBM_TPU_JIT_CACHE) win over
    # nothing; the explicit param wins over both.
    import os as _os
    from .utils.jit_cache import (ENV_COMPILE_CACHE, ENV_JIT_CACHE,
                                  enable_persistent_cache)
    _cache_dir = str(params.get("tpu_compile_cache_dir") or "")
    if _cache_dir or _os.environ.get(ENV_COMPILE_CACHE) or \
            _os.environ.get(ENV_JIT_CACHE):
        enable_persistent_cache(_cache_dir or None)
    # resolve num_boost_round aliases (ref: engine.py:149-160)
    for alias in _ConfigAliases.get("num_iterations"):
        if alias in params and alias != "num_iterations":
            num_boost_round = int(params.pop(alias))
            log.warning(f"Found '{alias}' in params. Will use it instead of "
                        "'num_boost_round' argument")
        elif alias == "num_iterations" and alias in params:
            num_boost_round = int(params.pop(alias))
    # early stopping from params (ref: engine.py:275)
    early_stopping_round = None
    for alias in _ConfigAliases.get("early_stopping_round"):
        if alias in params and params[alias] is not None:
            early_stopping_round = int(params[alias])
    first_metric_only = bool(params.get("first_metric_only", False))

    fobj = None
    obj = params.get("objective")
    for alias in _ConfigAliases.get("objective"):
        if alias in params:
            obj = params[alias]
    if callable(obj):
        fobj = obj
        for alias in _ConfigAliases.get("objective"):
            params.pop(alias, None)
        params["objective"] = "custom"

    if not isinstance(train_set, Dataset):
        raise TypeError("train() only accepts Dataset object")

    # graceful degradation: with tpu_fallback_to_cpu, prove the device
    # is reachable (under the shared retry policy) BEFORE any dataset
    # construction touches the backend; on terminal failure the run
    # continues on CPU with a loud warning instead of aborting
    if str(params.get("tpu_fallback_to_cpu", "")).lower() in \
            ("1", "true", "yes", "on"):
        from .robustness.retry import ensure_device_or_fallback
        ensure_device_or_fallback(fallback=True)

    # crash recovery: newest valid checkpoint wins over init_model
    resumed_state = None
    if resume_from:
        from .robustness.checkpoint import latest_valid_checkpoint
        found = latest_valid_checkpoint(resume_from)
        if found is not None:
            ckpt_path, resumed_state = found
            if init_model is not None:
                log.warning("resume_from checkpoint found; ignoring "
                            "init_model")
            init_model = Booster(model_str=resumed_state["model"])
            log.info(f"Resuming from checkpoint {ckpt_path} "
                     f"(iteration {resumed_state['iteration']})")
        else:
            log.info(f"resume_from={resume_from!r}: no valid "
                     "checkpoint; starting fresh")

    train_set._update_params(params)
    train_set.construct()

    # gang-coordinated resume (ISSUE 10): in a sharded world the
    # checkpoint set must be proven to belong to THIS sharding, and
    # resume must anchor at the newest COMMITTED (manifested) iteration
    # so every rank — and every auto-relaunch — agrees on the restart
    # point. Runs SPMD on all ranks; the decision depends only on the
    # shared directory and the allgathered ShardInfo, so ranks cannot
    # disagree. Refuses torn/mixed-world sets loudly.
    if resume_from and str(params.get("tpu_gang_manifest", "true")
                           ).strip().lower() not in ("0", "false",
                                                     "off", "no"):
        shard = getattr(getattr(train_set, "_binned", None), "shard",
                        None)
        if shard is not None:
            from .robustness.gang import validate_and_select_resume
            anchored = validate_and_select_resume(
                resume_from, shard, resumed_state)
            if anchored is not resumed_state:
                resumed_state = anchored
                init_model = (Booster(model_str=anchored["model"])
                              if anchored is not None else None)

    # continued training (ref: engine.py:233-244)
    if isinstance(init_model, (str,)):
        predictor = Booster(model_file=init_model)
    elif isinstance(init_model, Booster):
        predictor = init_model
    else:
        predictor = None

    booster = Booster(params=params, train_set=train_set)
    if predictor is not None:
        booster._engine.init_from_model(predictor._engine)

    eval_train_name = None
    if valid_sets is not None:
        if isinstance(valid_sets, Dataset):
            valid_sets = [valid_sets]
        if valid_names is None:
            valid_names = [f"valid_{i}" for i in range(len(valid_sets))]
        for vs, name in zip(valid_sets, valid_names):
            if vs is train_set:
                eval_train_name = name
            else:
                booster.add_valid(vs, name)

    if num_boost_round <= 0:
        raise ValueError("num_boost_round must be greater than 0")
    cbs = set(callbacks or [])
    if resumed_state is not None:
        from .robustness.checkpoint import restore_into_booster
        restore_into_booster(booster, resumed_state)
        # resume semantics: num_boost_round is the TOTAL target
        done = int(resumed_state.get("iteration",
                                     booster.current_iteration()))
        remaining = num_boost_round - done
        # hand the persisted eval history back to the checkpoint
        # callback so later checkpoints carry the whole run's history
        for cb in cbs:
            seed = getattr(cb, "_ckpt_seed_state", None)
            if seed is not None:
                seed(resumed_state)
        if remaining <= 0:
            log.info(f"checkpoint already at iteration {done} >= "
                     f"num_boost_round={num_boost_round}; nothing to "
                     "train")
            if not keep_training_booster:
                booster.free_dataset()
            return booster
        num_boost_round = remaining
    if early_stopping_round is not None and early_stopping_round > 0:
        verbosity = 1
        for alias in _ConfigAliases.get("verbosity"):
            if params.get(alias) is not None:
                verbosity = int(params[alias])
        min_delta = params.get("early_stopping_min_delta")
        cbs.add(callback_module.early_stopping(
            early_stopping_round, first_metric_only,
            verbose=verbosity >= 1,
            min_delta=float(min_delta) if min_delta is not None else 0.0))
    callbacks_before = [cb for cb in cbs
                        if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in cbs
                       if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    if eval_train_name is not None:
        booster.train_data_name = eval_train_name
    init_iteration = booster.current_iteration()
    booster.best_iteration = -1
    evaluation_result_list = []

    import jax

    profile_dir = str(booster._engine.config.tpu_profile_dir or "")
    if profile_dir:
        # device trace of the whole boosting loop (SURVEY §5: the TPU
        # counterpart of USE_TIMETAG; open the capture with xprof)
        jax.profiler.start_trace(profile_dir)
    try:
        for i in range(init_iteration, init_iteration + num_boost_round):
            for cb in callbacks_before:
                cb(CallbackEnv(model=booster, params=params, iteration=i,
                               begin_iteration=init_iteration,
                               end_iteration=init_iteration + num_boost_round,
                               evaluation_result_list=None))
            finished = booster.update(fobj=fobj)

            evaluation_result_list = []
            if eval_train_name is not None or \
                    booster._engine.config.is_provide_training_metric:
                name = eval_train_name or "training"
                evaluation_result_list.extend(
                    (name, n, v, h)
                    for _, n, v, h in booster.eval_train(feval))
            if booster.valid_sets:
                evaluation_result_list.extend(booster.eval_valid(feval))
            try:
                for cb in callbacks_after:
                    cb(CallbackEnv(
                        model=booster, params=params, iteration=i,
                        begin_iteration=init_iteration,
                        end_iteration=init_iteration + num_boost_round,
                        evaluation_result_list=evaluation_result_list))
            except EarlyStopException as earlyStopException:
                booster.best_iteration = \
                    earlyStopException.best_iteration + 1
                evaluation_result_list = earlyStopException.best_score
                break
            if finished:
                break
    finally:
        if profile_dir:
            jax.profiler.stop_trace()

    booster.best_score = collections.defaultdict(collections.OrderedDict)
    for item in evaluation_result_list:
        if len(item) == 4:
            booster.best_score[item[0]][item[1]] = item[2]
    if not keep_training_booster:
        booster.free_dataset()
    return booster


class CVBooster:
    """Container of k boosters from cv() (ref: engine.py:356 CVBooster)."""

    def __init__(self, model_file: Optional[str] = None):
        self.boosters: List[Booster] = []
        self.best_iteration = -1
        if model_file is not None:
            with open(model_file) as f:
                self._from_dict(json.load(f))

    def _append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def _to_dict(self, num_iteration, start_iteration, importance_type):
        """ref: CVBooster._to_dict — per-fold model strings + metadata."""
        return {"boosters": [
                    b.model_to_string(num_iteration=num_iteration,
                                      start_iteration=start_iteration,
                                      importance_type=importance_type)
                    for b in self.boosters],
                "best_iteration": self.best_iteration}

    def _from_dict(self, models: dict) -> None:
        self.best_iteration = models.get("best_iteration", -1)
        self.boosters = [Booster(model_str=s)
                         for s in models.get("boosters", [])]

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        """All folds as one JSON string (ref: CVBooster.model_to_string)."""
        return json.dumps(self._to_dict(num_iteration, start_iteration,
                                        importance_type))

    def model_from_string(self, model_str: str) -> "CVBooster":
        """Load the folds back from a JSON string."""
        self._from_dict(json.loads(model_str))
        return self

    def save_model(self, filename, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> "CVBooster":
        """ref: CVBooster.save_model."""
        with open(str(filename), "w") as f:
            f.write(self.model_to_string(num_iteration, start_iteration,
                                         importance_type))
        return self

    def __getattr__(self, name: str):
        if name.startswith("__"):  # keep copy/pickle/introspection sane
            raise AttributeError(name)

        def handler_function(*args: Any, **kwargs: Any) -> List[Any]:
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]
        return handler_function


def _make_n_folds(full_data: Dataset, folds, nfold: int, params: Dict,
                  seed: int, stratified: bool, shuffle: bool):
    """ref: engine.py _make_n_folds."""
    full_data.construct()
    num_data = full_data.num_data()
    if folds is not None:
        if not hasattr(folds, "__iter__") and not hasattr(folds, "split"):
            raise AttributeError(
                "folds should be a generator or iterator of (train_idx, "
                "test_idx) tuples or scikit-learn splitter object")
        if hasattr(folds, "split"):
            group_info = full_data.get_group()
            if group_info is not None:
                group_info = np.asarray(group_info, np.int64)
                flatted_group = np.repeat(
                    np.arange(len(group_info)), repeats=group_info)
            else:
                flatted_group = np.zeros(num_data, dtype=np.int64)
            folds = folds.split(X=np.empty(num_data),
                                y=full_data.get_label(),
                                groups=flatted_group)
    else:
        rng = np.random.default_rng(seed)
        group = full_data.get_group()
        if group is not None:
            # group-aware folds: split whole queries
            ngroups = len(group)
            gidx = np.arange(ngroups)
            if shuffle:
                rng.shuffle(gidx)
            gfolds = np.array_split(gidx, nfold)
            boundaries = np.concatenate([[0], np.cumsum(group)])
            folds = []
            for gf in gfolds:
                test_rows = np.concatenate(
                    [np.arange(boundaries[g], boundaries[g + 1])
                     for g in gf]) if len(gf) else np.zeros(0, np.int64)
                train_rows = np.setdiff1d(np.arange(num_data), test_rows)
                folds.append((train_rows, test_rows))
        elif stratified:
            label = np.asarray(full_data.get_label())
            folds = []
            # within each class, (optionally shuffled) round-robin deal so
            # every fold gets the same class proportions
            assignment = np.zeros(num_data, np.int64)
            for cls in np.unique(label):
                rows = np.flatnonzero(label == cls)
                if shuffle:
                    rng.shuffle(rows)
                assignment[rows] = np.arange(len(rows)) % nfold
            for f in range(nfold):
                test_rows = np.flatnonzero(assignment == f)
                train_rows = np.flatnonzero(assignment != f)
                folds.append((train_rows, test_rows))
        else:
            idx = np.arange(num_data)
            if shuffle:
                rng.shuffle(idx)
            parts = np.array_split(idx, nfold)
            folds = [(np.setdiff1d(np.arange(num_data), p), p)
                     for p in parts]
    return folds


def _agg_cv_result(raw_results):
    """ref: engine.py _agg_cv_result — mean/std across folds."""
    cvmap = collections.OrderedDict()
    metric_type = {}
    for one_result in raw_results:
        for one_line in one_result:
            key = f"{one_line[0]} {one_line[1]}"
            metric_type[key] = one_line[3]
            cvmap.setdefault(key, [])
            cvmap[key].append(one_line[2])
    return [("cv_agg", k, float(np.mean(v)), metric_type[k],
             float(np.std(v))) for k, v in cvmap.items()]


def cv(params: Dict[str, Any], train_set: Dataset,
       num_boost_round: int = 100, folds=None, nfold: int = 5,
       stratified: bool = True, shuffle: bool = True,
       metrics=None, feval=None,
       init_model: Optional[Union[str, Booster]] = None,
       fpreproc=None, seed: int = 0, callbacks=None,
       eval_train_metric: bool = False,
       return_cvbooster: bool = False) -> Dict[str, Any]:
    """Cross-validation (ref: engine.py:356 cv)."""
    params = copy.deepcopy(params) if params else {}
    if not isinstance(train_set, Dataset):
        raise TypeError("cv() only accepts Dataset object")
    for alias in _ConfigAliases.get("num_iterations"):
        if alias in params:
            num_boost_round = int(params.pop(alias))
    early_stopping_round = None
    for alias in _ConfigAliases.get("early_stopping_round"):
        if alias in params and params[alias] is not None:
            early_stopping_round = int(params[alias])
    if metrics is not None:
        params["metric"] = metrics
    obj = params.get("objective")
    fobj = None
    if callable(obj):
        fobj = obj
        params["objective"] = "custom"
    # stratification only makes sense for classification
    cfg_probe = Config({k: v for k, v in params.items()
                        if not callable(v)})
    if cfg_probe.objective not in ("binary", "multiclass", "multiclassova"):
        stratified = False

    train_set._update_params(params)
    train_set.construct()
    folds = _make_n_folds(train_set, folds, nfold, params, seed, stratified,
                          shuffle)

    cvbooster = CVBooster()
    boosters_env = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, params.copy())
        b = Booster(params=params, train_set=tr)
        b.add_valid(te, "valid")
        cvbooster._append(b)
        boosters_env.append(b)

    cbs = set(callbacks or [])
    if early_stopping_round is not None and early_stopping_round > 0:
        min_delta = params.get("early_stopping_min_delta")
        cbs.add(callback_module.early_stopping(
            early_stopping_round,
            bool(params.get("first_metric_only", False)), verbose=False,
            min_delta=float(min_delta) if min_delta is not None else 0.0))
    callbacks_before = sorted(
        [cb for cb in cbs if getattr(cb, "before_iteration", False)],
        key=lambda cb: getattr(cb, "order", 0))
    callbacks_after = sorted(
        [cb for cb in cbs if not getattr(cb, "before_iteration", False)],
        key=lambda cb: getattr(cb, "order", 0))

    results = collections.defaultdict(list)
    for i in range(num_boost_round):
        for cb in callbacks_before:
            cb(CallbackEnv(model=cvbooster, params=params, iteration=i,
                           begin_iteration=0, end_iteration=num_boost_round,
                           evaluation_result_list=None))
        for b in boosters_env:
            b.update(fobj=fobj)
        raw = []
        for b in boosters_env:
            one = []
            if eval_train_metric:
                one.extend(b.eval_train(feval))
            one.extend(b.eval_valid(feval))
            raw.append(one)
        res = _agg_cv_result(raw)
        for _, key, mean, _, std in res:
            results[f"{key}-mean"].append(mean)
            results[f"{key}-stdv"].append(std)
        try:
            for cb in callbacks_after:
                cb(CallbackEnv(model=cvbooster, params=params, iteration=i,
                               begin_iteration=0,
                               end_iteration=num_boost_round,
                               evaluation_result_list=res))
        except EarlyStopException as e:
            cvbooster.best_iteration = e.best_iteration + 1
            for bst in boosters_env:
                bst.best_iteration = cvbooster.best_iteration
            for k in results:
                results[k] = results[k][:cvbooster.best_iteration]
            break

    out: Dict[str, Any] = dict(results)
    if return_cvbooster:
        out["cvbooster"] = cvbooster
    return out
