"""Distributed training over jax device meshes.

TPU-native replacement for the reference's parallel learners + Network stack
(ref: src/treelearner/parallel_tree_learner.h, src/network/ — SURVEY.md §2.3,
§2.4). machine_list/ports become a `Mesh`; socket/MPI collectives become XLA
collectives over ICI/DCN.
"""
from .mesh import (DATA_AXIS, FEATURE_AXIS, build_mesh, feature_tile,
                   pad_rows_np, padded_rows, replicated, row_sharding)
from .data_parallel import (make_data_parallel_grower,
                            make_distributed_train_step,
                            make_feature_window, make_global_best_combine)
from .feature_parallel import (make_feature_parallel_grower,
                               pad_feature_meta, padded_features)
from .voting_parallel import make_voting_parallel_grower

__all__ = [
    "DATA_AXIS", "FEATURE_AXIS", "build_mesh", "padded_rows", "pad_rows_np",
    "row_sharding", "replicated", "feature_tile",
    "make_data_parallel_grower", "make_distributed_train_step",
    "make_feature_window", "make_global_best_combine",
    "make_feature_parallel_grower", "pad_feature_meta", "padded_features",
    "make_voting_parallel_grower",
]
