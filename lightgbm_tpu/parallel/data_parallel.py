"""Row-sharded (data-parallel) tree growing over a device mesh.

TPU-native equivalent of DataParallelTreeLearner
(ref: src/treelearner/data_parallel_tree_learner.cpp; comm pattern per
SURVEY.md §3.3: local histograms → ReduceScatter → local best split on owned
features → SyncUpGlobalBestSplit → every machine applies the identical split).

The TPU formulation runs the *same* leaf-wise grower program on every device
under `shard_map`, with rows sharded over the mesh's data axis:

- per-leaf histograms are built from local rows then `psum` over the data
  axis (≡ ReduceScatter+Allgather fused by XLA; the reference's explicit
  buffer layout `PrepareBufferPos` disappears — XLA lays out the collective);
- root grad/hess/count sums `psum` (≡ Network::Allreduce of the root tuples,
  data_parallel_tree_learner.cpp:170,201);
- the split scan then runs on the replicated histogram, so every device
  computes the *identical* best split and tree — no split broadcast needed,
  exactly like the reference where all machines apply the global split
  locally (SURVEY.md §3.3 last line);
- the per-row `leaf_id` partition stays sharded: each device partitions only
  its rows (≡ DataPartition::Split on the local shard).

Gradient computation and score updates are elementwise over the sharded row
axis and need no collectives at all (the reference likewise keeps
scores/gradients fully local per machine).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map as _shard_map

    def _make_sharded(fn, mesh, in_specs, out_specs):
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
except ImportError:  # pre-0.8 jax: experimental API, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def _make_sharded(fn, mesh, in_specs, out_specs):
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

from ..core.grower import GrowerConfig, make_tree_grower
from ..ops.split import FeatureMeta
from .mesh import DATA_AXIS


def make_data_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                              mesh: Mesh, data_axis: str = DATA_AXIS,
                              forced=None, bundle=None,
                              fetch_bin_column=None,
                              prepare_split_hist=None,
                              prepare_is_pure: bool = False,
                              bins_spec=None):
    """Build `grow(bins_t, gh, feature_mask, cegb) -> (TreeArrays, leaf_id)`
    where `bins_t` [F, R] and `gh` [R, 3] are sharded over `data_axis` on
    their row dimension; R must be divisible by the axis size (pad upstream
    with gh rows of zeros). The returned tree is replicated; `leaf_id` is
    sharded. ``feature_mask``/``cegb`` match the serial grower's arguments
    (replicated); ``forced`` bakes a forced-split prefix like the serial
    grower (valid here because the histogram pool holds GLOBAL sums).

    Multi-value sparse storage composes by passing the multival hooks
    plus a SparseBins ``bins_spec`` (idx/binv row-sharded): the column
    accessor and per-leaf gathers are shard-local, local scatter
    histograms psum like the dense path, and the default-bin fix runs
    in the split scan AFTER the psum against the GLOBAL leaf sums — the
    same algebra as the reference's distributed FixHistogram.
    """
    grow = make_tree_grower(
        cfg, meta,
        reduce_hist=lambda h, ctx=None: lax.psum(h, data_axis),
        reduce_sums=lambda s: lax.psum(s, data_axis),
        # global quantization scales + per-shard rounding noise (see
        # grower.py quantized block)
        reduce_max=lambda x: lax.pmax(x, data_axis),
        localize_key=lambda k: jax.random.fold_in(
            k, lax.axis_index(data_axis)),
        forced=forced, bundle=bundle,
        fetch_bin_column=fetch_bin_column,
        prepare_split_hist=prepare_split_hist,
        prepare_is_pure=prepare_is_pure)

    def wrapped(bins_t, gh, feature_mask, cegb_const, cegb_count, rng_key):
        return grow(bins_t, gh, feature_mask, (cegb_const, cegb_count),
                    rng_key)

    # compact scheduling takes ROW-major [R, F] bins (rows sharded on dim
    # 0); full mode takes feature-major [F, R] (rows sharded on dim 1).
    # A caller-provided bins_spec (pytree, e.g. SparseBins of specs)
    # overrides for non-dense storages.
    if bins_spec is None:
        bins_spec = (P(data_axis, None) if cfg.row_sched == "compact"
                     else P(None, data_axis))
    sharded = _make_sharded(
        wrapped, mesh,
        in_specs=(bins_spec, P(data_axis, None), P(), P(), P(), P()),
        out_specs=(P(), P(data_axis)))

    F = int(meta.num_bin.shape[0])

    def grow_fn(bins_t, gh, feature_mask: Optional[jnp.ndarray] = None,
                cegb=None, rng_key=None):
        if feature_mask is None:
            feature_mask = jnp.ones(F, bool)
        if cegb is None:
            cegb = (jnp.zeros(F, jnp.float32), jnp.zeros(F, jnp.float32))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        return sharded(bins_t, gh, feature_mask, cegb[0], cegb[1], rng_key)

    return grow_fn


def make_distributed_train_step(cfg: GrowerConfig, meta: FeatureMeta,
                                mesh: Mesh, grad_fn: Callable,
                                learning_rate: float,
                                data_axis: str = DATA_AXIS,
                                tree_learner: str = "data",
                                top_k: int = 20):
    """One full boosting iteration as a single jittable program over the mesh
    (≡ GBDT::TrainOneIter on every machine, gbdt.cpp:353 — gradients,
    tree growth with collective histogram reduction, score update).

    grad_fn(score, label) -> (grad, hess), elementwise over rows.
    Returns step(bins_t, label, score, row_mask) -> (new_score, tree,
    leaf_id). ``row_mask`` (f32 0/1 [R]) zeroes padding rows so they carry
    gh = (0, 0, 0) and never count toward histograms, hessians or
    min_data_in_leaf (see mesh.pad_rows_np); pass all-ones when R divides
    the mesh evenly.
    """
    if tree_learner in ("data", "serial"):
        grow = make_data_parallel_grower(cfg, meta, mesh, data_axis)
    elif tree_learner == "voting":
        from .voting_parallel import make_voting_parallel_grower
        grow = make_voting_parallel_grower(cfg, meta, mesh, top_k=top_k,
                                           data_axis=data_axis)
    else:
        raise ValueError(
            f"tree_learner={tree_learner!r}; row-sharded step supports "
            "'data' and 'voting' (feature-parallel shards features — use "
            "make_feature_parallel_grower)")

    def step(bins_t, label, score, row_mask):
        grad, hess = grad_fn(score, label)
        gh = jnp.stack([grad * row_mask, hess * row_mask, row_mask], axis=1)
        tree, leaf_id = grow(bins_t, gh, None)
        leaf_value = tree.leaf_value * jnp.float32(learning_rate)
        new_score = score + leaf_value[leaf_id]
        return new_score, tree, leaf_id

    return step
