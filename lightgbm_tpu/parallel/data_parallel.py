"""Row-sharded (data-parallel) tree growing over a device mesh.

TPU-native equivalent of DataParallelTreeLearner
(ref: src/treelearner/data_parallel_tree_learner.cpp; comm pattern per
SURVEY.md §3.3: local histograms → ReduceScatter → local best split on owned
features → SyncUpGlobalBestSplit → every machine applies the identical split).

The TPU formulation runs the *same* leaf-wise grower program on every device
under `shard_map`, with rows sharded over the mesh's data axis:

- per-leaf histograms are built from local rows then `psum` over the data
  axis (≡ ReduceScatter+Allgather fused by XLA; the reference's explicit
  buffer layout `PrepareBufferPos` disappears — XLA lays out the collective);
- root grad/hess/count sums `psum` (≡ Network::Allreduce of the root tuples,
  data_parallel_tree_learner.cpp:170,201);
- the split scan then runs on the replicated histogram, so every device
  computes the *identical* best split and tree — no split broadcast needed,
  exactly like the reference where all machines apply the global split
  locally (SURVEY.md §3.3 last line);
- the per-row `leaf_id` partition stays sharded: each device partitions only
  its rows (≡ DataPartition::Split on the local shard).

Gradient computation and score updates are elementwise over the sharded row
axis and need no collectives at all (the reference likewise keeps
scores/gradients fully local per machine).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map as _shard_map

    def _make_sharded(fn, mesh, in_specs, out_specs):
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)
except ImportError:  # pre-0.8 jax: experimental API, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def _make_sharded(fn, mesh, in_specs, out_specs):
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

import numpy as np

from ..core.grower import (B_DL, B_FEAT, B_GAIN, B_LG, B_LH, B_LC, B_LO,
                           B_RG, B_RH, B_RC, B_RO, B_THR, GrowerConfig,
                           make_tree_grower)
from ..ops.split import FeatureMeta, SplitRecord, pack_record_rows
from ..utils.log import info_once as _log_once
from .mesh import DATA_AXIS, feature_tile


def make_global_best_combine(axis: str):
    """Deterministic cross-device best-split combine for feature-sharded
    split scanning (≡ SyncUpGlobalBestSplit, parallel_tree_learner.h:210:
    the reference allgathers packed SplitInfo buffers and argmaxes).

    Each device contributes its window winner as ONE packed f32 [12] row
    (gain, feat, thr, dl, left/right sums — the ops/split.pack_record_rows
    layout); the global winner is picked by (max gain, then SMALLEST
    global feature id) so byte-equal gain ties on different shards can
    never disagree with the serial scan's first-seen argmax, and the
    winning row is replicated by indexing one tiny all_gather (an
    indexed pick, NOT a masked psum: `psum(-0.0, 0.0, ...)` rounds to
    +0.0 and a winner's -0.0 leaf output must survive the combine
    bit-exactly). Comm per combine is a handful of scalars + one
    [D, 12] gather — the small-record half of the reduce-scatter
    contract (the big histograms never travel whole).
    """
    def select_best(rec: SplitRecord) -> SplitRecord:
        big = jnp.int32(2 ** 30)
        row = pack_record_rows(rec, False)                      # [12]
        gmax = lax.pmax(rec.gain, axis)
        at_max = rec.gain == gmax
        win_fid = lax.pmin(jnp.where(at_max, rec.feature, big), axis)
        mine = at_max & (rec.feature == win_fid)
        # a global feature lives in exactly one window, so `mine` holds
        # on one device — EXCEPT when no device found a valid split
        # (every record is gain=-inf/feature=-1 and all devices match);
        # win_dev then resolves to rank 0's identical invalid record
        idx = lax.axis_index(axis)
        win_dev = lax.pmin(jnp.where(mine, idx, big), axis)
        rows = lax.all_gather(row, axis)                   # [D, 12]
        row_g = rows[jnp.clip(win_dev, 0, rows.shape[0] - 1)]
        i32 = lambda c: row_g[c].astype(jnp.int32)
        return SplitRecord(
            gain=row_g[B_GAIN], feature=i32(B_FEAT),
            threshold=i32(B_THR), default_left=row_g[B_DL] > 0.5,
            left_sum_gradient=row_g[B_LG], left_sum_hessian=row_g[B_LH],
            left_count=row_g[B_LC], left_output=row_g[B_LO],
            right_sum_gradient=row_g[B_RG], right_sum_hessian=row_g[B_RH],
            right_count=row_g[B_RC], right_output=row_g[B_RO])
    return select_best


def _window_meta(meta: FeatureMeta, Ft: int, pad: int):
    """Per-device FeatureMeta window factory for contiguous feature tiles.

    Uniform concrete metas (the dense numerical case) fold to STATIC
    [Ft] constants — every device's window is the same three values, so
    the split scan keeps its trace-time optimizations (dead-forward-scan
    elision, _feature_meta_scalars constant folding) under sharding.
    Ragged metas pad with 1-bin never-splittable slots and dynamic-slice
    per device (traced; results identical, the dead direction just runs).
    Categorical/monotone features are ineligible for windows (callers
    resolve those to allreduce), so those fields are fixed empty.
    """
    uniform = False
    if meta.penalty is None:
        try:
            nb = np.asarray(meta.num_bin)
            mt = np.asarray(meta.missing_type)
            db = np.asarray(meta.default_bin)
            uniform = (nb.max() == nb.min() and mt.max() == mt.min()
                       and db.max() == db.min())
        except Exception:
            uniform = False  # traced meta — dynamic window
    if uniform:
        w = FeatureMeta(
            num_bin=jnp.full((Ft,), int(nb[0]), jnp.int32),
            missing_type=jnp.full((Ft,), int(mt[0]), jnp.int32),
            default_bin=jnp.full((Ft,), int(db[0]), jnp.int32),
            is_categorical=jnp.zeros((Ft,), bool))
        return lambda start: w

    def pad1(a, fill, dtype):
        if a is None:
            return None
        a = jnp.asarray(a, dtype)
        if pad:
            a = jnp.concatenate([a, jnp.full((pad,), fill, dtype)])
        return a
    nb_p = pad1(meta.num_bin, 1, jnp.int32)      # 1-bin: never splittable
    mt_p = pad1(meta.missing_type, 0, jnp.int32)
    db_p = pad1(meta.default_bin, 0, jnp.int32)
    pen_p = pad1(meta.penalty, 1.0, jnp.float32)

    def at(start):
        sl = lambda a: (None if a is None
                        else lax.dynamic_slice_in_dim(a, start, Ft, 0))
        return FeatureMeta(
            num_bin=sl(nb_p), missing_type=sl(mt_p),
            default_bin=sl(db_p),
            is_categorical=jnp.zeros((Ft,), bool),
            penalty=sl(pen_p))
    return at


def make_feature_window(meta: FeatureMeta, num_shards: int, axis: str):
    """(reduce_hist, scan_window) hook pair for
    ``tpu_hist_reduce=reduce_scatter`` over contiguous feature tiles.

    reduce_hist: pads the [Fp, B, 3] partial histogram to a
    mesh-divisible feature count and ``lax.psum_scatter``s it over the
    data axis — each device keeps the GLOBAL sums of one contiguous
    feature slice ([Ft, B, 3]). Bytes on the wire per reduction drop
    from allreduce's 2(N-1)/N·|H| to (N-1)/N·|H|
    (≡ Network::ReduceScatter, network.h:90-276), and the downstream
    O(F·B) split scan divides by the mesh size instead of running
    replicated N times.

    scan_window: maps the per-feature mask/penalty/rand vectors into the
    device's window with globally-correct feature ids (pad slots masked
    off); pairs with make_global_best_combine as the grower's
    select_best.
    """
    Fp = int(meta.num_bin.shape[0])
    Ft = feature_tile(Fp, num_shards)
    pad = Ft * num_shards - Fp
    meta_at = _window_meta(meta, Ft, pad)

    def reduce_hist(h, ctx=None):
        if pad:
            h = jnp.pad(h, ((0, pad),) + ((0, 0),) * (h.ndim - 1))
        return lax.psum_scatter(h, axis, scatter_dimension=0, tiled=True)

    def scan_window(hist, ctx, feature_mask, gain_penalty, rand_u):
        start = lax.axis_index(axis) * Ft
        fids = start + jnp.arange(Ft, dtype=jnp.int32)
        in_table = fids < Fp

        def sl(a, fill):
            if a is None:
                return None
            if pad:
                a = jnp.concatenate(
                    [a, jnp.full((pad,), fill, a.dtype)], axis=0)
            return lax.dynamic_slice_in_dim(a, start, Ft, 0)
        fm = (in_table if feature_mask is None
              else in_table & sl(feature_mask, False))
        return (hist, meta_at(start), fids, fm,
                sl(gain_penalty, 0.0), sl(rand_u, 0.0))
    return reduce_hist, scan_window


def make_data_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                              mesh: Mesh, data_axis: str = DATA_AXIS,
                              forced=None, bundle=None,
                              fetch_bin_column=None,
                              prepare_split_hist=None,
                              prepare_is_pure: bool = False,
                              bins_spec=None,
                              hist_reduce: str = "allreduce"):
    """Build `grow(bins_t, gh, feature_mask, cegb) -> (TreeArrays, leaf_id)`
    where `bins_t` [F, R] and `gh` [R, 3] are sharded over `data_axis` on
    their row dimension; R must be divisible by the axis size (pad upstream
    with gh rows of zeros). The returned tree is replicated; `leaf_id` is
    sharded. ``feature_mask``/``cegb`` match the serial grower's arguments
    (replicated); ``forced`` bakes a forced-split prefix like the serial
    grower (valid here because the histogram pool holds GLOBAL sums).

    Multi-value sparse storage composes by passing the multival hooks
    plus a SparseBins ``bins_spec`` (idx/binv row-sharded): the column
    accessor and per-leaf gathers are shard-local, local scatter
    histograms psum like the dense path, and the default-bin fix runs
    in the split scan AFTER the psum against the GLOBAL leaf sums — the
    same algebra as the reference's distributed FixHistogram.

    ``hist_reduce`` selects the histogram collective (tpu_hist_reduce):

    - "allreduce": ``psum`` — the pool holds GLOBAL hists replicated on
      every device and the split scan runs replicated (the pre-existing
      contract above).
    - "reduce_scatter": ``psum_scatter`` — each device keeps one
      contiguous feature slice of the summed histogram, scans only its
      window, and the winners merge through the tiny packed-record
      combine (make_global_best_combine ≡ SyncUpGlobalBestSplit). Halves
      collective bytes per reduction and divides the O(F·B) scan by the
      mesh size; trees stay bit-identical (exact int32 psum_scatter
      under quantized gradients; f32 ties resolve by global feature id).
      Dense numerical only — models/gbdt resolves ineligible configs
      (EFB, multival, forced, categorical, monotone) back to allreduce.
    """
    if hist_reduce not in ("allreduce", "reduce_scatter"):
        raise ValueError(f"hist_reduce={hist_reduce!r}; expected "
                         "'allreduce' or 'reduce_scatter' (resolve "
                         "'auto' upstream)")
    scan_window = select_best = None
    if hist_reduce == "reduce_scatter":
        reduce_hist, scan_window = make_feature_window(
            meta, int(mesh.shape[data_axis]), data_axis)
        select_best = make_global_best_combine(data_axis)
    else:
        reduce_hist = lambda h, ctx=None: lax.psum(h, data_axis)
    grow = make_tree_grower(
        cfg, meta,
        reduce_hist=reduce_hist,
        reduce_sums=lambda s: lax.psum(s, data_axis),
        # global quantization scales + per-shard rounding noise (see
        # grower.py quantized block)
        reduce_max=lambda x: lax.pmax(x, data_axis),
        localize_key=lambda k: jax.random.fold_in(
            k, lax.axis_index(data_axis)),
        forced=forced, bundle=bundle,
        fetch_bin_column=fetch_bin_column,
        prepare_split_hist=prepare_split_hist,
        prepare_is_pure=prepare_is_pure,
        scan_window=scan_window, select_best=select_best)

    def wrapped(bins_t, gh, feature_mask, cegb_const, cegb_count, rng_key):
        return grow(bins_t, gh, feature_mask, (cegb_const, cegb_count),
                    rng_key)

    # compact scheduling takes ROW-major [R, F] bins (rows sharded on dim
    # 0); full mode takes feature-major [F, R] (rows sharded on dim 1).
    # A caller-provided bins_spec (pytree, e.g. SparseBins of specs)
    # overrides for non-dense storages.
    if bins_spec is None:
        bins_spec = (P(data_axis, None) if cfg.row_sched == "compact"
                     else P(None, data_axis))
    sharded = _make_sharded(
        wrapped, mesh,
        in_specs=(bins_spec, P(data_axis, None), P(), P(), P(), P()),
        out_specs=(P(), P(data_axis)))

    F = int(meta.num_bin.shape[0])

    def grow_fn(bins_t, gh, feature_mask: Optional[jnp.ndarray] = None,
                cegb=None, rng_key=None):
        if feature_mask is None:
            feature_mask = jnp.ones(F, bool)
        if cegb is None:
            cegb = (jnp.zeros(F, jnp.float32), jnp.zeros(F, jnp.float32))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        return sharded(bins_t, gh, feature_mask, cegb[0], cegb[1], rng_key)

    return grow_fn


def make_distributed_train_step(cfg: GrowerConfig, meta: FeatureMeta,
                                mesh: Mesh, grad_fn: Callable,
                                learning_rate: float,
                                data_axis: str = DATA_AXIS,
                                tree_learner: str = "data",
                                top_k: int = 20,
                                hist_reduce: str = "allreduce"):
    """One full boosting iteration as a single jittable program over the mesh
    (≡ GBDT::TrainOneIter on every machine, gbdt.cpp:353 — gradients,
    tree growth with collective histogram reduction, score update).

    grad_fn(score, label) -> (grad, hess), elementwise over rows.
    Returns step(bins_t, label, score, row_mask) -> (new_score, tree,
    leaf_id). ``row_mask`` (f32 0/1 [R]) zeroes padding rows so they carry
    gh = (0, 0, 0) and never count toward histograms, hessians or
    min_data_in_leaf (see mesh.pad_rows_np); pass all-ones when R divides
    the mesh evenly.
    """
    if tree_learner in ("data", "serial"):
        if tree_learner == "serial":
            # NOT silent (r05/PR6 rule: invisible remaps make numbers
            # unattributable): the serial program is not mesh-aware, so
            # a mesh-shaped step runs the row-sharded data-parallel
            # grower — same trees as serial up to f32 psum reassociation
            # (exact under quantized gradients)
            _log_once(
                "make_distributed_train_step: tree_learner='serial' over "
                f"a {int(mesh.shape[data_axis])}-device mesh runs the "
                "row-sharded DATA-parallel grower (the serial program is "
                "not mesh-aware); pass tree_learner='data' to say so "
                "explicitly")
        grow = make_data_parallel_grower(cfg, meta, mesh, data_axis,
                                         hist_reduce=hist_reduce)
    elif tree_learner == "voting":
        from .voting_parallel import make_voting_parallel_grower
        grow = make_voting_parallel_grower(cfg, meta, mesh, top_k=top_k,
                                           data_axis=data_axis,
                                           hist_reduce=hist_reduce)
    else:
        raise ValueError(
            f"tree_learner={tree_learner!r}; row-sharded step supports "
            "'data' and 'voting' (feature-parallel shards features — use "
            "make_feature_parallel_grower)")

    def step(bins_t, label, score, row_mask):
        grad, hess = grad_fn(score, label)
        gh = jnp.stack([grad * row_mask, hess * row_mask, row_mask], axis=1)
        tree, leaf_id = grow(bins_t, gh, None)
        leaf_value = tree.leaf_value * jnp.float32(learning_rate)
        new_score = score + leaf_value[leaf_id]
        return new_score, tree, leaf_id

    return step
