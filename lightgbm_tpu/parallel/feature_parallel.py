"""Feature-sharded (feature-parallel) tree growing over a device mesh.

TPU-native equivalent of FeatureParallelTreeLearner
(ref: src/treelearner/feature_parallel_tree_learner.cpp,
parallel_tree_learner.h:26-46; comm pattern per SURVEY.md §2.3: every
machine holds ALL rows, scans its FEATURE slice for the best split, the
global best is picked by an argmax reduction over machines
(SyncUpGlobalBestSplit), and everyone applies the identical split locally).

The TPU formulation shards `bins_t` over the mesh axis on the FEATURE
dimension. Per split step, each device:

1. builds the histogram of its feature slice only (the hot op scales
   1/D — the whole point of feature-parallel for wide data);
2. runs the split scan on its slice (local FeatureMeta slice);
3. `all_gather`s the D candidate SplitRecords and takes the argmax —
   gathered in device order, so for contiguous (unbundled) slices ties
   resolve to the smaller global feature index exactly like
   SplitInfo::operator>; under EFB the scan order is the group layout,
   so exact-gain ties may resolve to a different (equally optimal)
   feature than the serial scan;
4. broadcasts the winning feature's bin column with a one-hot psum
   (the owner contributes the column, everyone else zeros) and
   partitions its full local row set — no split-result broadcast of row
   masks needed, mirroring the reference where all data is local.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.grower import GrowerConfig, make_tree_grower
from ..ops.split import FeatureMeta, SplitRecord
from .data_parallel import _make_sharded
from .mesh import FEATURE_AXIS


from .mesh import padded_rows as _pad_to_multiple


def padded_features(num_features: int, num_shards: int) -> int:
    return _pad_to_multiple(num_features, num_shards)


def padded_groups(num_groups: int, num_shards: int) -> int:
    """Padded PHYSICAL group count for the EFB-sharded feature learner
    (single source of truth for gbdt's bin padding and shard_bundle's
    per-shard layout)."""
    return _pad_to_multiple(num_groups, num_shards)


def pad_feature_meta(meta: FeatureMeta, target_f: int) -> FeatureMeta:
    """Pad meta arrays with trivial 1-bin features (never splittable)."""
    F = meta.num_bin.shape[0]
    if F == target_f:
        return meta
    pad = target_f - F

    def pad1(a, fill):
        return jnp.concatenate(
            [a, jnp.full((pad,), fill, a.dtype)]) if a is not None else None
    return FeatureMeta(
        num_bin=pad1(meta.num_bin, 1),
        missing_type=pad1(meta.missing_type, 0),
        default_bin=pad1(meta.default_bin, 0),
        is_categorical=pad1(meta.is_categorical, False),
        monotone=pad1(meta.monotone, 0),
        penalty=pad1(meta.penalty, 1.0),
    )


def shard_bundle(bundle: dict, meta: FeatureMeta, num_shards: int,
                 B: int):
    """Host-side EFB layout for the feature learner: physical GROUPS
    shard contiguously ([Gd] per device); each device's LOGICAL
    features (those living in its groups) are padded to a common width
    Fd with 1-bin never-splittable dummies. Returns the stacked
    per-shard meta/bundle arrays, the local->global logical id map, and
    the padded group count (for padding the packed bins).
    """
    group = np.asarray(bundle["group"], np.int64)          # [F] global
    offset = np.asarray(bundle["offset"], np.int64)
    default_bin = np.asarray(bundle["default_bin"], np.int64)
    num_bin_l = np.asarray(bundle["num_bin"], np.int64)
    G = int(bundle["num_groups"])
    D = num_shards
    Gd = padded_groups(G, D) // D
    feats = [np.where((group >= d * Gd) & (group < (d + 1) * Gd))[0]
             for d in range(D)]
    Fd = max(max((len(f) for f in feats), default=1), 1)

    glob_ids = np.full((D, Fd), -1, np.int32)
    l_group = np.zeros((D, Fd), np.int32)
    l_offset = np.zeros((D, Fd), np.int32)
    l_default = np.zeros((D, Fd), np.int32)
    l_nbin = np.ones((D, Fd), np.int32)
    l_gmap = np.full((D, Fd, B), -1, np.int32)
    m_nbin = np.ones((D, Fd), np.int32)
    m_miss = np.zeros((D, Fd), np.int32)
    m_dflt = np.zeros((D, Fd), np.int32)
    m_cat = np.zeros((D, Fd), bool)
    m_mono = (np.zeros((D, Fd), np.int32)
              if meta.monotone is not None else None)
    m_pen = (np.ones((D, Fd), np.float32)
             if meta.penalty is not None else None)
    nb_np = np.asarray(meta.num_bin)
    ms_np = np.asarray(meta.missing_type)
    df_np = np.asarray(meta.default_bin)
    ct_np = np.asarray(meta.is_categorical)
    mono_np = None if m_mono is None else np.asarray(meta.monotone)
    pen_np = None if m_pen is None else np.asarray(meta.penalty)
    gmap_global = np.asarray(bundle["gather_map"], np.int32)  # [F, B]
    for d in range(D):
        for j, f in enumerate(feats[d]):
            gl = int(group[f]) - d * Gd                   # LOCAL group
            glob_ids[d, j] = f
            l_group[d, j] = gl
            l_offset[d, j] = offset[f]
            l_default[d, j] = default_bin[f]
            l_nbin[d, j] = num_bin_l[f]
            # local flat indices into the shard's [Gd*B] hist: the
            # global map's rows shift by the shard's group base (single
            # source of truth: BundleInfo.build_gather_map)
            gm = gmap_global[f]
            l_gmap[d, j] = np.where(gm >= 0, gm - d * Gd * B, -1)
            m_nbin[d, j] = nb_np[f]
            m_miss[d, j] = ms_np[f]
            m_dflt[d, j] = df_np[f]
            m_cat[d, j] = ct_np[f]
            if m_mono is not None:
                m_mono[d, j] = mono_np[f]
            if m_pen is not None:
                m_pen[d, j] = pen_np[f]
    meta_stacked = FeatureMeta(
        num_bin=jnp.asarray(m_nbin), missing_type=jnp.asarray(m_miss),
        default_bin=jnp.asarray(m_dflt), is_categorical=jnp.asarray(m_cat),
        monotone=None if m_mono is None else jnp.asarray(m_mono),
        penalty=None if m_pen is None else jnp.asarray(m_pen))
    bundle_stacked = dict(
        gather_map=jnp.asarray(l_gmap), group=jnp.asarray(l_group),
        offset=jnp.asarray(l_offset), default_bin=jnp.asarray(l_default),
        num_bin=jnp.asarray(l_nbin))
    return (meta_stacked, bundle_stacked, jnp.asarray(glob_ids),
            D * Gd, feats, Fd)


def make_feature_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                                 mesh: Mesh,
                                 feature_axis: str = FEATURE_AXIS,
                                 bundle: Optional[dict] = None):
    """Build grow(bins_t, gh) with bins sharded on the FEATURE dim over
    `feature_axis` (F must divide the axis size — pad with
    pad_feature_meta / zero bin rows): [F, R] in full mode, row-major
    [R, F] under compact scheduling (the partition column then arrives
    via the once-per-split owner broadcast). gh is replicated. Returns a
    replicated tree and leaf_id.

    With ``bundle`` (EFB), the sharded storage axis is PHYSICAL GROUPS
    (pad the packed bins to the returned padded group count); each
    device expands its group histograms to its own logical features and
    scans those, the winner's local logical index translates to the
    TRUE global feature id, and the owner broadcasts the DECODED
    logical column for partitioning. ``feature_mask``/``cegb`` stay in
    GLOBAL logical order; grow_fn permutes them into the shard layout.
    """
    D = mesh.shape[feature_axis]
    F_total = int(meta.num_bin.shape[0])
    bundled = bundle is not None
    if bundled:
        (meta_stacked, bundle_stacked, glob_ids, _G_pad, _feats,
         Fd) = shard_bundle(bundle, meta, D, cfg.num_bin)
        # the shard layout's global-logical permutation IS glob_ids
        perm_j = glob_ids.reshape(-1)
        Fd_shard = Fd
    else:
        assert F_total % D == 0, \
            "pad features to a multiple of the axis size"
        Fd_shard = F_total // D

        def shard_meta(m):
            return jax.tree.map(
                lambda a: a.reshape(D, Fd_shard, *a.shape[1:])
                if a is not None else None, m)

        meta_stacked = shard_meta(meta)

    def make_local_grow():
        def local_meta():
            idx = lax.axis_index(feature_axis)
            return jax.tree.map(
                lambda a: a[idx] if a is not None else None, meta_stacked)

        if bundled:
            def local_ids():
                return glob_ids[lax.axis_index(feature_axis)]

            def select_best(rec: SplitRecord) -> SplitRecord:
                ids = local_ids()
                fsafe = jnp.clip(rec.feature, 0, Fd_shard - 1)
                rec_g = rec._replace(feature=jnp.where(
                    rec.feature >= 0, ids[fsafe], -1))
                allr = jax.tree.map(
                    lambda a: lax.all_gather(a, feature_axis), rec_g)
                win = jnp.argmax(allr.gain).astype(jnp.int32)
                return jax.tree.map(lambda a: a[win], allr)

            def fetch_bin_column(bins_local, f_global):
                # owner finds its local logical slot, decodes the
                # group column to the LOGICAL bin, and broadcasts
                ids = local_ids()
                hit = ids == jnp.maximum(f_global, 0)
                own = jnp.any(hit) & (f_global >= 0)
                f_local = jnp.argmax(hit).astype(jnp.int32)
                bs = bundle_stacked
                d = lax.axis_index(feature_axis)
                g_local = bs["group"][d, f_local]
                axis = 1 if cfg.row_sched == "compact" else 0
                col_phys = jnp.take(bins_local, g_local,
                                    axis=axis).astype(jnp.int32)
                from ..io.bundling import decode_logical_bin
                col = decode_logical_bin(col_phys,
                                         bs["offset"][d, f_local],
                                         bs["num_bin"][d, f_local],
                                         bs["default_bin"][d, f_local])
                col = jnp.where(own, col, 0)
                return lax.psum(col, feature_axis)

            def local_bundle():
                d = lax.axis_index(feature_axis)
                return {k: v[d] for k, v in bundle_stacked.items()}

            return make_tree_grower(
                cfg, local_meta(),
                select_best=select_best,
                fetch_bin_column=fetch_bin_column,
                partition_meta=meta,
                bundle=local_bundle())

        def select_best(rec: SplitRecord) -> SplitRecord:
            offset = lax.axis_index(feature_axis) * Fd_shard
            rec_g = rec._replace(feature=jnp.where(
                rec.feature >= 0, rec.feature + offset, -1))
            # [D] per-leaf candidates in device (= feature-offset) order
            allr = jax.tree.map(
                lambda a: lax.all_gather(a, feature_axis), rec_g)
            win = jnp.argmax(allr.gain).astype(jnp.int32)
            return jax.tree.map(lambda a: a[win], allr)

        def fetch_bin_column(bins_local, f_global):
            offset = lax.axis_index(feature_axis) * Fd_shard
            f_local = f_global - offset
            own = (f_local >= 0) & (f_local < Fd_shard) & (f_global >= 0)
            # full mode stores [F_local, R]; compact stores row-major
            # [R, F_local]
            axis = 1 if cfg.row_sched == "compact" else 0
            col = jnp.take(bins_local, jnp.clip(f_local, 0, Fd_shard - 1),
                           axis=axis).astype(jnp.int32)
            col = jnp.where(own, col, 0)
            # owner broadcast (≡ "no broadcast needed" in the reference
            # because all rows are local — only the column is exchanged)
            return lax.psum(col, feature_axis)

        def localize_feature(f_global):
            """Global logical feature -> (local index, owned?) for the
            monotone-box geometry ([L, F_local] per shard)."""
            off = lax.axis_index(feature_axis) * Fd_shard
            f_local = f_global - off
            own = (f_local >= 0) & (f_local < Fd_shard) & (f_global >= 0)
            return f_local, own

        return make_tree_grower(
            cfg, local_meta(),
            select_best=select_best,
            fetch_bin_column=fetch_bin_column,
            partition_meta=meta,
            # refined monotone modes: separator counts/selectors psum
            # over the feature shards; the rescan's all_gather runs
            # under a REPLICATED cond predicate (uniform collectives)
            reduce_box=lambda x: lax.psum(x, feature_axis),
            localize_feature=localize_feature,
            mc_rescan_hooks_ok=True)

    def sharded_grow(bins_t, gh, feature_mask, cegb_const, cegb_count,
                     rng_key):
        # quantization scales need no reduce here: rows are REPLICATED, so
        # every device computes identical scales (and, with the replicated
        # key, identical quantized gh) from the full gradient vector
        grow = make_local_grow()
        return grow(bins_t, gh, feature_mask, (cegb_const, cegb_count),
                    rng_key)

    # feature_mask / cegb are per-feature → sharded over the feature axis
    # alongside the bins (each device masks/penalizes its own slice);
    # bynode masks are [2L, F] so the feature dim moves to position 1
    fm_spec = P(None, feature_axis) if cfg.bynode_mask else P(feature_axis)
    bins_spec = (P(None, feature_axis) if cfg.row_sched == "compact"
                 else P(feature_axis, None))
    sharded = _make_sharded(
        sharded_grow, mesh,
        in_specs=(bins_spec, P(None, None), fm_spec,
                  P(feature_axis), P(feature_axis), P()),
        out_specs=(P(), P()))

    def grow_fn(bins_t, gh, feature_mask: Optional[jnp.ndarray] = None,
                cegb=None, rng_key=None):
        if feature_mask is None:
            shape = (2 * cfg.num_leaves, F_total) if cfg.bynode_mask \
                else (F_total,)
            feature_mask = jnp.ones(shape, bool)
        if cegb is None:
            cegb = (jnp.zeros(F_total, jnp.float32),
                    jnp.zeros(F_total, jnp.float32))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        if bundled:
            # global-logical-order vectors -> the shard layout (padded
            # slots masked off / zero-penalized)
            pad_ok = perm_j >= 0
            psafe = jnp.maximum(perm_j, 0)
            if feature_mask.ndim == 2:
                feature_mask = jnp.where(pad_ok[None, :],
                                         feature_mask[:, psafe], False)
            else:
                feature_mask = jnp.where(pad_ok, feature_mask[psafe],
                                         False)
            cegb = (jnp.where(pad_ok, cegb[0][psafe], 0.0),
                    jnp.where(pad_ok, cegb[1][psafe], 0.0))
        return sharded(bins_t, gh, feature_mask, cegb[0], cegb[1], rng_key)

    return grow_fn
