"""Feature-sharded (feature-parallel) tree growing over a device mesh.

TPU-native equivalent of FeatureParallelTreeLearner
(ref: src/treelearner/feature_parallel_tree_learner.cpp,
parallel_tree_learner.h:26-46; comm pattern per SURVEY.md §2.3: every
machine holds ALL rows, scans its FEATURE slice for the best split, the
global best is picked by an argmax reduction over machines
(SyncUpGlobalBestSplit), and everyone applies the identical split locally).

The TPU formulation shards `bins_t` over the mesh axis on the FEATURE
dimension. Per split step, each device:

1. builds the histogram of its feature slice only (the hot op scales
   1/D — the whole point of feature-parallel for wide data);
2. runs the split scan on its slice (local FeatureMeta slice);
3. `all_gather`s the D candidate SplitRecords and takes the argmax —
   gathered in device order, so ties resolve to the smaller global
   feature index exactly like SplitInfo::operator>;
4. broadcasts the winning feature's bin column with a one-hot psum
   (the owner contributes the column, everyone else zeros) and
   partitions its full local row set — no split-result broadcast of row
   masks needed, mirroring the reference where all data is local.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.grower import GrowerConfig, make_tree_grower
from ..ops.split import FeatureMeta, SplitRecord
from .data_parallel import _make_sharded
from .mesh import FEATURE_AXIS


from .mesh import padded_rows as _pad_to_multiple


def padded_features(num_features: int, num_shards: int) -> int:
    return _pad_to_multiple(num_features, num_shards)


def pad_feature_meta(meta: FeatureMeta, target_f: int) -> FeatureMeta:
    """Pad meta arrays with trivial 1-bin features (never splittable)."""
    F = meta.num_bin.shape[0]
    if F == target_f:
        return meta
    pad = target_f - F

    def pad1(a, fill):
        return jnp.concatenate(
            [a, jnp.full((pad,), fill, a.dtype)]) if a is not None else None
    return FeatureMeta(
        num_bin=pad1(meta.num_bin, 1),
        missing_type=pad1(meta.missing_type, 0),
        default_bin=pad1(meta.default_bin, 0),
        is_categorical=pad1(meta.is_categorical, False),
        monotone=pad1(meta.monotone, 0),
        penalty=pad1(meta.penalty, 1.0),
    )


def make_feature_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                                 mesh: Mesh,
                                 feature_axis: str = FEATURE_AXIS):
    """Build grow(bins_t, gh) with bins sharded on the FEATURE dim over
    `feature_axis` (F must divide the axis size — pad with
    pad_feature_meta / zero bin rows): [F, R] in full mode, row-major
    [R, F] under compact scheduling (the partition column then arrives
    via the once-per-split owner broadcast). gh is replicated. Returns a
    replicated tree and leaf_id.
    """
    D = mesh.shape[feature_axis]
    F_total = int(meta.num_bin.shape[0])
    assert F_total % D == 0, "pad features to a multiple of the axis size"
    Fd = F_total // D

    def shard_meta(m):
        return jax.tree.map(
            lambda a: a.reshape(D, Fd, *a.shape[1:]) if a is not None
            else None, m)

    meta_stacked = shard_meta(meta)

    def make_local_grow():
        def local_meta():
            idx = lax.axis_index(feature_axis)
            return jax.tree.map(
                lambda a: a[idx] if a is not None else None, meta_stacked)

        def select_best(rec: SplitRecord) -> SplitRecord:
            offset = lax.axis_index(feature_axis) * Fd
            rec_g = rec._replace(feature=jnp.where(
                rec.feature >= 0, rec.feature + offset, -1))
            # [D] per-leaf candidates in device (= feature-offset) order
            allr = jax.tree.map(
                lambda a: lax.all_gather(a, feature_axis), rec_g)
            win = jnp.argmax(allr.gain).astype(jnp.int32)
            return jax.tree.map(lambda a: a[win], allr)

        def fetch_bin_column(bins_local, f_global):
            offset = lax.axis_index(feature_axis) * Fd
            f_local = f_global - offset
            own = (f_local >= 0) & (f_local < Fd) & (f_global >= 0)
            # full mode stores [F_local, R]; compact stores row-major
            # [R, F_local]
            axis = 1 if cfg.row_sched == "compact" else 0
            col = jnp.take(bins_local, jnp.clip(f_local, 0, Fd - 1),
                           axis=axis).astype(jnp.int32)
            col = jnp.where(own, col, 0)
            # owner broadcast (≡ "no broadcast needed" in the reference
            # because all rows are local — only the column is exchanged)
            return lax.psum(col, feature_axis)

        return make_tree_grower(
            cfg, local_meta(),
            select_best=select_best,
            fetch_bin_column=fetch_bin_column,
            partition_meta=meta)

    def sharded_grow(bins_t, gh, feature_mask, cegb_const, cegb_count,
                     rng_key):
        # quantization scales need no reduce here: rows are REPLICATED, so
        # every device computes identical scales (and, with the replicated
        # key, identical quantized gh) from the full gradient vector
        grow = make_local_grow()
        return grow(bins_t, gh, feature_mask, (cegb_const, cegb_count),
                    rng_key)

    # feature_mask / cegb are per-feature → sharded over the feature axis
    # alongside the bins (each device masks/penalizes its own slice);
    # bynode masks are [2L, F] so the feature dim moves to position 1
    fm_spec = P(None, feature_axis) if cfg.bynode_mask else P(feature_axis)
    bins_spec = (P(None, feature_axis) if cfg.row_sched == "compact"
                 else P(feature_axis, None))
    sharded = _make_sharded(
        sharded_grow, mesh,
        in_specs=(bins_spec, P(None, None), fm_spec,
                  P(feature_axis), P(feature_axis), P()),
        out_specs=(P(), P()))

    def grow_fn(bins_t, gh, feature_mask: Optional[jnp.ndarray] = None,
                cegb=None, rng_key=None):
        if feature_mask is None:
            shape = (2 * cfg.num_leaves, F_total) if cfg.bynode_mask \
                else (F_total,)
            feature_mask = jnp.ones(shape, bool)
        if cegb is None:
            cegb = (jnp.zeros(F_total, jnp.float32),
                    jnp.zeros(F_total, jnp.float32))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        return sharded(bins_t, gh, feature_mask, cegb[0], cegb[1], rng_key)

    return grow_fn
