"""Voting-parallel (PV-Tree) tree growing over a device mesh.

TPU-native equivalent of VotingParallelTreeLearner
(ref: src/treelearner/voting_parallel_tree_learner.cpp,
parallel_tree_learner.h:126-207; SURVEY.md §2.3): rows are sharded like
data-parallel, but instead of reducing FULL histograms, each device votes
its top-k features by LOCAL split gain; the global vote selects the top-2k
features; only THOSE features' histograms are aggregated — communication
per split drops from O(F·B) to O(k·B) (docs/Features.rst:78+).

Mapping onto the grower hooks:
- reduce_hist = identity → the histogram pool stays LOCAL and sibling
  subtraction happens on local sums (≡ the reference's local
  smaller/larger arrays + FeatureHistogram::Subtract,
  voting_parallel_tree_learner.cpp:338);
- prepare_split_hist = vote → aggregate: local per-feature best gains
  (per_feature_net_gains ≡ local SplitInfo gains), top-k one-hot vote,
  psum of votes (≡ Allgather of votes + GlobalVoting :152,373), top-2k
  selection, selective psum of the chosen histograms (≡ CopyLocalHistogram
  + ReduceScatter :396), and a feature mask restricting the split scan to
  aggregated features;
- reduce_sums = psum (root tuple Allreduce, like data-parallel).

The global vote is identical on every device (computed from the psum'd
vote counts), so all devices select the same features and find the same
split — no further sync needed.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..core.grower import GrowerConfig, make_tree_grower
from ..ops.split import FeatureMeta, per_feature_net_gains
from .data_parallel import _make_sharded, make_global_best_combine
from .mesh import DATA_AXIS, feature_tile


def make_voting_parallel_grower(cfg: GrowerConfig, meta: FeatureMeta,
                                mesh: Mesh, top_k: int = 20,
                                data_axis: str = DATA_AXIS,
                                bundle=None, fetch_bin_column=None,
                                bins_spec=None, pre_fix=None,
                                hist_reduce: str = "allreduce"):
    """Build grow(bins_t, gh, feature_mask) with rows sharded over
    `data_axis` ([F, R] on dim 1, gh on dim 0), aggregating only the
    globally voted 2*top_k features per leaf (top_k ≡ config.top_k,
    config.h "top_k"/"topk").

    Composition (the reference's learners are storage-agnostic —
    feature_histogram.hpp constraints/scans are identical under every
    learner — so these must compose here too):
    - ``bundle``: EFB — the grower expands physical-group hists to
      logical features with LOCAL totals (local-sums channel) before the
      vote, so gains rank true local logical histograms.
    - ``fetch_bin_column`` + ``bins_spec`` + ``pre_fix``: multi-value
      sparse storage — ``pre_fix(hist, (lsg, lsh, lcnt))`` adds each
      feature's missing default-bin mass from the LOCAL leaf totals
      before the vote; the psum of locally-fixed hists is the correctly
      fixed global histogram (the fix is linear in the totals).
    """
    F = int(meta.num_bin.shape[0])
    k = max(1, min(top_k, F))
    k2 = min(2 * k, F)
    hp = cfg.hparams
    if hist_reduce not in ("allreduce", "reduce_scatter"):
        raise ValueError(f"hist_reduce={hist_reduce!r}; expected "
                         "'allreduce' or 'reduce_scatter' (resolve "
                         "'auto' upstream)")
    use_rs = hist_reduce == "reduce_scatter"
    if use_rs and (bundle is not None or fetch_bin_column is not None or
                   pre_fix is not None):
        raise ValueError(
            "tpu_hist_reduce=reduce_scatter voting supports dense "
            "numerical storage only (EFB/multival resolve to allreduce "
            "in models/gbdt)")

    def vote(hist_local, ctx, feature_mask):
        """Local top-k vote -> replicated global top-2k selection [k2]
        (≡ local SplitInfo gains -> Allgather votes -> GlobalVoting,
        voting_parallel_tree_learner.cpp:152,373). Shared verbatim by
        both reduce modes, so their candidate sets cannot drift."""
        parent_out = ctx[3]
        # the LOCAL vote ranks by LOCAL gains (ref: voting learner votes
        # with this->smaller_leaf_splits_, the local sums) — the
        # grower's local-sums channel carries the shard totals (ctx
        # entries 4..6); any-feature bin sums would break for sparse
        # storages whose default-bin mass is not stored
        local_sg, local_sh, local_cnt = ctx[4], ctx[5], ctx[6]
        if pre_fix is not None:
            hist_local = pre_fix(hist_local,
                                 (local_sg, local_sh, local_cnt))
        gains = per_feature_net_gains(hist_local, local_sg, local_sh,
                                      local_cnt, parent_out, meta, hp)  # [F]
        if feature_mask is not None:
            # col sampling applies BEFORE the vote (ref: voting learner
            # checks is_feature_used_bytree before computing local splits)
            gains = jnp.where(feature_mask, gains, -jnp.inf)
        _, local_top = lax.top_k(gains, k)
        votes = jnp.zeros(F, jnp.float32).at[local_top].add(1.0)
        votes = lax.psum(votes, data_axis)
        # deterministic global tie-break toward smaller feature index
        # (GlobalVoting keeps the first-seen max like ArgMax); integer key
        # keeps ordering exact for any F with votes bounded by mesh size
        keyed = (votes.astype(jnp.int32) * F
                 + (F - 1 - jnp.arange(F, dtype=jnp.int32)))
        _, sel = lax.top_k(keyed, k2)                               # [k2]
        return hist_local, sel.astype(jnp.int32)

    def prepare(hist_local, ctx, feature_mask=None):
        hist_local, sel = vote(hist_local, ctx, feature_mask)
        hist_sel = lax.psum(hist_local[sel], data_axis)         # [k2, B, 3]
        hist_global = jnp.zeros_like(hist_local).at[sel].set(hist_sel)
        sel_mask = jnp.zeros(F, bool).at[sel].set(True)
        return hist_global, sel_mask

    n_shards = int(mesh.shape[data_axis])
    k2l = feature_tile(k2, n_shards)       # selected features per device
    k2p = k2l * n_shards

    def scan_window(hist_local, ctx, feature_mask, gain_penalty, rand_u):
        """reduce_scatter composition: the voted top-2k histograms
        reduce-scatter over the mesh instead of psum+replicate — each
        device keeps GLOBAL sums for k2/D of the selected features and
        scans only those (with their true global ids; the combine merges
        winners). Same vote, same candidate set, same per-feature sums
        as the allreduce path — only the layout of who holds/scans what
        changes, so trees stay bit-identical."""
        hist_local, sel = vote(hist_local, ctx, feature_mask)
        if k2p > k2:
            # pad the selection to a mesh-divisible tile; sentinel id F
            # is masked off below (its gathered hist is garbage by
            # construction and never scanned as valid)
            sel = jnp.concatenate(
                [sel, jnp.full((k2p - k2,), F, jnp.int32)])
        ssafe = jnp.clip(sel, 0, F - 1)
        hist_w = lax.psum_scatter(hist_local[ssafe], data_axis,
                                  scatter_dimension=0,
                                  tiled=True)               # [k2l, B, 3]
        i = lax.axis_index(data_axis)
        fids = lax.dynamic_slice_in_dim(sel, i * k2l, k2l, 0)
        valid = fids < F
        fsafe = jnp.clip(fids, 0, F - 1)
        gather = lambda a: None if a is None else a[fsafe]
        meta_w = FeatureMeta(
            num_bin=meta.num_bin[fsafe],
            missing_type=meta.missing_type[fsafe],
            default_bin=meta.default_bin[fsafe],
            is_categorical=jnp.zeros((k2l,), bool),
            penalty=gather(meta.penalty))
        fm_w = (valid if feature_mask is None
                else valid & feature_mask[fsafe])
        return (hist_w, meta_w, fids, fm_w, gather(gain_penalty),
                gather(rand_u))

    grow = make_tree_grower(
        cfg, meta,
        reduce_hist=lambda h, ctx=None: h,      # pool stays LOCAL
        reduce_sums=lambda s: lax.psum(s, data_axis),
        reduce_max=lambda x: lax.pmax(x, data_axis),
        localize_key=lambda k: jax.random.fold_in(
            k, lax.axis_index(data_axis)),
        prepare_split_hist=None if use_rs else prepare,
        scan_window=scan_window if use_rs else None,
        select_best=make_global_best_combine(data_axis) if use_rs
        else None,
        bundle=bundle, fetch_bin_column=fetch_bin_column,
        local_pool=True,
        # the vote/psum is a pure function of (hist, ctx, mask) and the
        # rescan's cond predicate is replicated -> collectives execute
        # uniformly on every device (refined monotone modes compose)
        mc_rescan_hooks_ok=True)

    def wrapped(bins_t, gh, feature_mask, cegb_const, cegb_count, rng_key):
        return grow(bins_t, gh, feature_mask, (cegb_const, cegb_count),
                    rng_key)

    if bins_spec is None:
        bins_spec = (P(data_axis, None) if cfg.row_sched == "compact"
                     else P(None, data_axis))
    sharded = _make_sharded(
        wrapped, mesh,
        in_specs=(bins_spec, P(data_axis, None), P(), P(), P(), P()),
        out_specs=(P(), P(data_axis)))

    def grow_fn(bins_t, gh, feature_mask: Optional[jnp.ndarray] = None,
                cegb=None, rng_key=None):
        if feature_mask is None:
            feature_mask = jnp.ones(F, bool)
        if cegb is None:
            cegb = (jnp.zeros(F, jnp.float32), jnp.zeros(F, jnp.float32))
        if rng_key is None:
            rng_key = jax.random.PRNGKey(0)
        return sharded(bins_t, gh, feature_mask, cegb[0], cegb[1], rng_key)

    return grow_fn
