"""Device-mesh helpers for distributed training.

TPU-native replacement for the reference's Network bootstrap
(ref: src/network/network.cpp Network::Init, linkers_socket.cpp TCP mesh,
linkers_mpi.cpp). Where the reference builds a socket/MPI world from
`machine_list_file` + `local_listen_port` (config.h:1092-1112), the TPU
framework's "world" is a `jax.sharding.Mesh` over the visible devices;
collectives ride ICI/DCN via XLA (`psum`, `psum_scatter`, `all_gather`)
instead of hand-written Bruck/recursive-halving algorithms
(network.cpp:160-320).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"      # row sharding  ≡ tree_learner=data
FEATURE_AXIS = "feature"  # feature sharding ≡ tree_learner=feature


def build_mesh(num_devices: Optional[int] = None,
               axis_names: Sequence[str] = (DATA_AXIS,),
               shape: Optional[Sequence[int]] = None) -> Mesh:
    """Build a mesh over the first `num_devices` visible devices.

    ``shape`` gives the per-axis sizes; default puts everything on the first
    axis (pure data-parallel, the reference's dominant distributed mode —
    Criteo 1.7B scaling, docs/Experiments.rst:228-242).
    """
    devs = jax.devices()
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devs)} visible")
        devs = devs[:num_devices]
    n = len(devs)
    if shape is None:
        shape = [n] + [1] * (len(axis_names) - 1)
    arr = np.asarray(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def padded_rows(num_rows: int, num_shards: int) -> int:
    """Rows after padding to an even multiple of the data-axis size."""
    return ((num_rows + num_shards - 1) // num_shards) * num_shards


def feature_tile(num_features: int, num_shards: int) -> int:
    """Per-device feature-window width under reduce-scatter histogram
    aggregation (tpu_hist_reduce=reduce_scatter): Fp padded up to a
    mesh-divisible tile, then split evenly — the TPU expression of
    Network::ReduceScatter's per-machine buffer blocks
    (ref: network.h:90-276 PrepareBufferPos block layout)."""
    return padded_rows(num_features, num_shards) // num_shards


def pad_rows_np(arr: np.ndarray, target: int, axis: int,
                fill=0) -> np.ndarray:
    """Pad `arr` along `axis` to `target` length with `fill` (host side).

    Padded rows carry gh = (0, 0, 0) so they are invisible to histograms,
    split stats and counts — the same trick the reference uses for bagging
    (zero-hessian rows simply don't contribute).
    """
    n = arr.shape[axis]
    if n == target:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - n)
    return np.pad(arr, widths, constant_values=fill)


def row_sharding(mesh: Mesh, row_dim: int, ndim: int,
                 axis: str = DATA_AXIS) -> NamedSharding:
    """NamedSharding that shards dimension `row_dim` of an ndim-array over
    the data axis, replicating the rest."""
    spec = [None] * ndim
    spec[row_dim] = axis
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
