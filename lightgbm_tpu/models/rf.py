"""Random Forest mode.

TPU-native equivalent of src/boosting/rf.hpp:26 — no shrinkage, bagging or
feature sampling required, gradients computed ONCE from the constant init
score, score maintained as the running average of tree outputs
(MultiplyScore trick, rf.hpp TrainOneIter), prediction averages trees
(average_output_).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..config import Config
from ..core.objective import K_EPSILON
from ..core.tree import HostTree
from ..utils import log
from .gbdt import GBDT


class RF(GBDT):
    NAME = "rf"

    def __init__(self, config: Config, train_set, objective):
        if str(config.data_sample_strategy).lower() == "bagging":
            ok = ((config.bagging_freq > 0 and
                   0.0 < config.bagging_fraction < 1.0) or
                  0.0 < config.feature_fraction < 1.0)
            if not ok:
                log.fatal("RF mode requires bagging "
                          "(bagging_freq>0 and bagging_fraction in (0,1)) "
                          "or feature_fraction in (0,1)")
        super().__init__(config, train_set, objective)
        self.average_output = True
        self.shrinkage_rate = 1.0
        # gradients from the constant init score, computed once (ref: rf.hpp
        # Boosting())
        K = self.num_tree_per_iteration
        self.init_scores = [0.0] * K
        if self.objective is not None:
            for k in range(K):
                if self.config.boost_from_average:
                    self.init_scores[k] = self._obtain_init_score(k)
            const_score = jnp.asarray(
                np.repeat(np.asarray(self.init_scores, np.float32)[:, None],
                          self.num_data, axis=1))
            if self._pos_bias:
                import jax.numpy as _jnp
                grad, hess = self._gh_fn(const_score, _jnp.asarray(
                    self.objective.pos_biases, _jnp.float32))
            else:
                grad, hess = self._gh_fn(const_score)
            if K == 1:
                grad, hess = grad[None, :], hess[None, :]
            self._grad_const = grad
            self._hess_const = hess
        log.info("Using RF (random forest) mode")

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        """ref: rf.hpp TrainOneIter — running-average score maintenance."""
        if gradients is not None or hessians is not None:
            log.fatal("RF mode does not support custom objective functions")
        K = self.num_tree_per_iteration
        grad, hess = self._grad_const, self._hess_const

        sample = self.sample_strategy.sample(
            self.iter, np.asarray(grad), np.asarray(hess))
        if sample is not None:
            selected, weight = sample
            sel_dev = jnp.asarray(selected)
            w_dev = jnp.asarray(weight)
        else:
            selected, sel_dev, w_dev = None, None, None

        should_continue = False
        for k in range(K):
            if not self.class_need_train[k] or self._grow is None:
                out = self.init_scores[k]
                self.models.append(HostTree.constant(out))
                continue
            g, h = grad[k], hess[k]
            if sel_dev is not None:
                gh = jnp.stack([g * w_dev, h * w_dev, sel_dev], axis=1)
            else:
                gh = jnp.stack([g, h, jnp.ones_like(g)], axis=1)
            fmask = self._feature_mask()
            import jax
            rng_key = None
            if self._grow_rng is not None:
                rng_key = jax.random.fold_in(self._grow_rng,
                                             self.iter * K + k)
            tree_dev, leaf_id = self._grow(self._train_bins(), gh, fmask,
                                           self._cegb_penalty(), rng_key)
            host = HostTree(jax.tree.map(np.asarray, tree_dev),
                            self.train_set.used_feature_map)
            if host.num_leaves <= 1:
                self.models.append(HostTree.constant(
                    self.init_scores[k] if len(self.models) < K else 0.0))
                continue
            should_continue = True
            self._finalize_tree(host)
            leaf_np = np.asarray(leaf_id)
            self._cegb_after_tree(host, leaf_np, selected)

            if self.objective is not None and \
                    self.objective.is_renew_tree_output():
                init = self.init_scores[k]
                label = self.train_set.metadata.label

                def residual_fn():
                    return label.astype(np.float64) - init

                renew_leaf = leaf_np
                if selected is not None:
                    renew_leaf = np.where(selected > 0, leaf_np, -1)
                new_vals = self.objective.renew_tree_output(
                    None, residual_fn, renew_leaf, host.num_leaves)
                if new_vals is not None:
                    old = host.leaf_value[:host.num_leaves]
                    host.leaf_value[:host.num_leaves] = np.where(
                        np.isfinite(new_vals), new_vals, old)
            if abs(self.init_scores[k]) > K_EPSILON:
                host.add_bias(self.init_scores[k])

            # running average: score = (score*n + tree) / (n+1)
            n_prev = self.iter + self.num_init_iteration
            lv = np.zeros(self.config.num_leaves, np.float32)
            lv[:host.num_leaves] = host.leaf_value[:host.num_leaves]
            lv_dev = jnp.asarray(lv)
            self.score = self.score.at[k].set(
                (self.score[k] * n_prev + lv_dev[leaf_id]) / (n_prev + 1))
            for vd in self.valid_sets:
                vd.score = vd.score.at[k].set(
                    (vd.score[k] * n_prev +
                     self._tree_outputs(host, vd.bins_dev, vd.dataset.raw)) / (n_prev + 1))
            self.models.append(host)

        if not should_continue:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > K:
                del self.models[-K:]
            return True
        self.iter += 1
        return False
