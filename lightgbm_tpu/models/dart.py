"""DART boosting (Dropouts meet Multiple Additive Regression Trees).

TPU-native equivalent of src/boosting/dart.hpp: per-iteration tree dropout
with renormalization. Score add/subtract of dropped trees runs as batched
device traversals over the binned data (ref: dart.hpp:98 DroppingTrees,
:159 Normalize and the three-step shrinkage scheme documented there).
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..config import Config
from ..utils import log
from .gbdt import GBDT


class DART(GBDT):
    NAME = "dart"

    def __init__(self, config: Config, train_set, objective):
        super().__init__(config, train_set, objective)
        self.rng = np.random.default_rng(config.drop_seed)
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self.drop_index: List[int] = []
        log.info("Using DART")

    def _add_tree_score(self, tree_idx: int, k: int, factor: float) -> None:
        """score += factor * tree_output for train+valid (tree's current
        leaf values; factor folds the Shrinkage(-1) style steps)."""
        t = self.models[tree_idx]
        self.score = self.score.at[k].add(
            factor * self._tree_outputs(t, self.bins_dev, self.train_set.raw))

    def _add_tree_score_valid(self, tree_idx: int, k: int,
                              factor: float) -> None:
        t = self.models[tree_idx]
        for vd in self.valid_sets:
            vd.score = vd.score.at[k].add(
                factor * self._tree_outputs(t, vd.bins_dev, vd.dataset.raw))

    def _dropping_trees(self) -> None:
        """ref: dart.hpp:98 DroppingTrees."""
        cfg = self.config
        self.drop_index = []
        if self.rng.random() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            n_tree = self.iter
            if cfg.uniform_drop:
                if cfg.max_drop > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / max(n_tree, 1))
                for i in range(n_tree):
                    if self.rng.random() < drop_rate:
                        self.drop_index.append(self.num_init_iteration + i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
            else:
                inv_avg = len(self.tree_weight) / max(self.sum_weight, 1e-300)
                if cfg.max_drop > 0:
                    drop_rate = min(
                        drop_rate,
                        cfg.max_drop * inv_avg / max(self.sum_weight, 1e-300))
                for i in range(n_tree):
                    if self.rng.random() < \
                            drop_rate * self.tree_weight[i] * inv_avg:
                        self.drop_index.append(self.num_init_iteration + i)
                        if len(self.drop_index) >= cfg.max_drop > 0:
                            break
        K = self.num_tree_per_iteration
        # drop: negate tree, add to train score (ref: Shrinkage(-1)+AddScore)
        for i in self.drop_index:
            for k in range(K):
                ti = i * K + k
                self.models[ti].shrink(-1.0)
                self._add_tree_score(ti, k, 1.0)
        if self.drop_index:
            # shrink() edits leaf values in place — serving caches can't
            # see it through the models list
            self.invalidate_serving_cache()
        n_drop = len(self.drop_index)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + n_drop)
        else:
            if n_drop == 0:
                self.shrinkage_rate = cfg.learning_rate
            else:
                self.shrinkage_rate = cfg.learning_rate / (
                    cfg.learning_rate + n_drop)

    def _normalize(self) -> None:
        """ref: dart.hpp:159 Normalize (three-step shrinkage scheme)."""
        cfg = self.config
        k_drop = float(len(self.drop_index))
        K = self.num_tree_per_iteration
        for i in self.drop_index:
            for k in range(K):
                ti = i * K + k
                if not cfg.xgboost_dart_mode:
                    self.models[ti].shrink(1.0 / (k_drop + 1.0))
                    self._add_tree_score_valid(ti, k, 1.0)
                    self.models[ti].shrink(-k_drop)
                    self._add_tree_score(ti, k, 1.0)
                else:
                    self.models[ti].shrink(self.shrinkage_rate)
                    self._add_tree_score_valid(ti, k, 1.0)
                    self.models[ti].shrink(-k_drop / cfg.learning_rate)
                    self._add_tree_score(ti, k, 1.0)
            wi = i - self.num_init_iteration
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[wi] / (k_drop + 1.0)
                    self.tree_weight[wi] *= k_drop / (k_drop + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[wi] / (
                        k_drop + cfg.learning_rate)
                    self.tree_weight[wi] *= k_drop / (
                        k_drop + cfg.learning_rate)
        if self.drop_index:
            self.invalidate_serving_cache()

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._dropping_trees()
        finished = super().train_one_iter(gradients, hessians)
        if not finished:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
            self._normalize()
        else:
            # restore the trees we dropped (training ends here)
            self._restore_dropped()
        return finished

    def _restore_dropped(self) -> None:
        K = self.num_tree_per_iteration
        for i in self.drop_index:
            for k in range(K):
                ti = i * K + k
                self.models[ti].shrink(-1.0)
                self._add_tree_score(ti, k, 1.0)
        if self.drop_index:
            self.invalidate_serving_cache()
        self.drop_index = []
