"""Boosting model factory (ref: src/boosting/boosting.cpp CreateBoosting,
include/LightGBM/boosting.h:317)."""
from __future__ import annotations

from ..config import Config
from ..utils import log


def create_boosting(config: Config, train_set, objective):
    from .gbdt import GBDT
    from .dart import DART
    from .rf import RF
    name = str(config.boosting).lower()
    if name in ("gbdt", "gbrt", "gradient_boosting",
                "gradient_boosted_trees", "goss"):
        return GBDT(config, train_set, objective)
    if name == "dart":
        return DART(config, train_set, objective)
    if name in ("rf", "random_forest"):
        return RF(config, train_set, objective)
    log.fatal(f"Unknown boosting type {config.boosting}")
