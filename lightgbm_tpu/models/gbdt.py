"""GBDT boosting orchestrator.

TPU-native equivalent of the reference boosting layer
(ref: src/boosting/gbdt.{h,cpp} — Init :60, BoostFromAverage :328,
Boosting :229, TrainOneIter :353-461, UpdateScore :502, eval :534,
RollbackOneIter :463; src/boosting/score_updater.hpp ScoreUpdater).

State design (SURVEY.md §7): scores live on device as f32 [K, N] arrays;
gradients are computed on device by the objective (≡ boosting_on_gpu_,
gbdt.cpp:111); each tree is grown by the jitted leaf-wise grower; the train
score update reuses the grower's per-row leaf_id (no traversal needed);
valid scores update via batched device traversal over binned data.
Host keeps the canonical model list (HostTree) for IO/serving, exactly
mirroring models_ in the reference.

Async boosting (tpu_async_boosting): when the device sits behind a
high-latency transport (the tunneled TPU measures ~70 ms per host
round-trip), any per-iteration host<->device sync caps throughput at
~14 iters/s no matter how fast the chip is. The fast path therefore keeps
every per-iteration product on device: grown trees accumulate as
TreeArrays in ``_pending``; train/valid score updates read leaf values
straight from the device tree; HostTree materialization (threshold
resolution, shrinkage, model-list append) is deferred until a consumer
touches ``models``. The "no more splits" stop condition is checked in
batches (one scalar fetch per tpu_stop_check_interval iterations) and is
exact: on detection the affected iterations are rolled back (scores
subtracted, sampler RNG restored) and replayed through the synchronous
path. The final model matches the sync path BIT-FOR-BIT: both paths
accumulate the identical f32 leaf product through the same jitted
delta/traversal programs (see _leaf_delta — the product rounds in its
own dispatch so FMA fusion cannot smuggle in an extra half-ulp), and
HostTree.shrink stores exactly that product, so model replays
(init_model continued training, checkpoint resume) reproduce the live
score exactly as well.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import tuned
from ..config import Config
from ..robustness import faults, heartbeat, integrity
from ..core.grower import GrowerConfig, make_tree_grower
from ..core.metrics import Metric, metrics_for_config
from ..core.objective import ObjectiveFunction, CustomObjective, K_EPSILON
from ..core.tree import HostTree, TreeArrays, host_tree_to_arrays
from ..io.dataset_core import BinnedDataset
from ..ops.split import FeatureMeta, SplitHyperParams
from ..ops.forest import ServingEngine
from ..ops.predict import depth_steps, tree_leaf_bins
from ..utils import log
from ..utils.timer import global_timer
from .sample_strategy import SampleStrategy


class _PendingTree(NamedTuple):
    """A grown-but-not-yet-materialized tree (async boosting fast path)."""
    tree: TreeArrays          # device arrays from the grower
    k: int                    # class index within the iteration
    it: int                   # boosting iteration that grew it
    shrinkage: float          # rate to apply at materialization
    bias: float               # init score to fold into leaf values
    rng_state: Optional[dict]      # sampler RNG before this iteration
    col_rng_state: Optional[dict]  # column-sampler RNG before this tree


# canonical packer now lives next to the tree types (core/tree.py) so the
# serving engine (ops/forest.py) can share it without a models-layer import;
# it additionally records HostTree.max_depth for depth-bounded traversal
_host_tree_to_arrays = host_tree_to_arrays


class _ModelList(list):
    """Model container that notifies the owning engine on every structural
    mutation. Appends at the tail keep the serving forest incrementally
    packable; everything else (rollback's ``del``, shuffles, item
    replacement) is DESTRUCTIVE and bumps the model generation so serving
    caches can never replay a stale stacked forest — the ISSUE 5 bug was a
    rollback + retrain back to the SAME model count slipping past a cache
    keyed only on ``len(models)``."""

    __slots__ = ("_note",)

    def __init__(self, iterable=(), note=None):
        super().__init__(iterable)
        self._note = note if note is not None else lambda destructive: None

    def append(self, v):
        super().append(v)
        self._note(False)

    def extend(self, it):
        super().extend(it)
        self._note(False)

    def __iadd__(self, it):
        super().extend(it)
        self._note(False)
        return self

    def insert(self, i, v):
        super().insert(i, v)
        self._note(True)

    def pop(self, i=-1):
        v = super().pop(i)
        self._note(True)
        return v

    def remove(self, v):
        super().remove(v)
        self._note(True)

    def clear(self):
        super().clear()
        self._note(True)

    def reverse(self):
        super().reverse()
        self._note(True)

    def sort(self, **kw):
        super().sort(**kw)
        self._note(True)

    def __setitem__(self, i, v):
        super().__setitem__(i, v)
        self._note(True)

    def __delitem__(self, i):
        super().__delitem__(i)
        self._note(True)

    def __imul__(self, n):
        raise TypeError("model list repetition is not supported")


def _orig_to_used(used_feature_map) -> dict:
    """Original feature index -> used (inner) index (ref: Dataset::
    InnerFeatureIndex)."""
    return {int(o): u for u, o in enumerate(used_feature_map)}


def _parse_interaction_constraints(spec) -> list:
    """Parse "[0,1,2],[2,3]" (or a list of lists) into a list of int lists
    (ref: config.h interaction_constraints string format)."""
    if isinstance(spec, (list, tuple)):
        return [list(map(int, grp)) for grp in spec]
    import re
    return [[int(v) for v in grp.split(",") if v.strip() != ""]
            for grp in re.findall(r"\[([^\[\]]*)\]", str(spec))]


class _ValidData:
    """One validation set: device bins + score + metrics
    (ref: valid_score_updater_ / valid_metrics_ in gbdt.h)."""

    def __init__(self, dataset: BinnedDataset, metrics: List[Metric],
                 num_class: int, name: str = "valid"):
        self.dataset = dataset
        self.metrics = metrics
        self.name = name
        if dataset.bins is None and dataset.bins_mv is not None:
            # valid-set eval traverses feature-major dense bins; densify
            # the multi-value packing (valid folds are the smaller side)
            from ..ops.hist_multival import densify
            dflt = np.asarray([m.default_bin
                               for m in dataset.used_bin_mappers()],
                              np.int32)
            self.bins_dev = jnp.asarray(
                densify(dataset.bins_mv[0], dataset.bins_mv[1], dflt))
        else:
            self.bins_dev = jnp.asarray(dataset.ensure_logical_bins()
                                        if dataset.bins is None
                                        else dataset.bins)
        self.score = jnp.zeros((num_class, dataset.num_data), jnp.float32)
        if dataset.metadata.init_score is not None:
            init = dataset.metadata.init_score.reshape(
                -1, dataset.num_data).astype(np.float32)
            self.score = jnp.asarray(init)


def resolve_hist_kernel(requested: str, hist_dtype: str, use_quant: bool,
                        num_data, platform: str) -> str:
    """Resolve ``tpu_hist_kernel=auto`` to a concrete backend.

    CPU: scatter-add (einsum one-hot is pathologically slow there).
    TPU bf16/int8: the VMEM-resident Pallas kernel (measured on v5e at
    1M rows, docs/TPU_RUNBOOK.md: 6.0 / 5.6 ms vs einsum's 16.5 /
    16.3). TPU f32: einsum unless the on-device A/B recorded a Pallas
    win in the tuned cache — size-gated (tuned.applies), since the
    100k-measured flips regress small runs. Unknown cache values fall
    back: tuning must never be able to break training.
    """
    if requested not in ("auto", "pallas_level"):
        return requested
    if requested == "pallas_level":
        # "pallas_level" names the LEVEL-mode sorted-segment kernel
        # only; the compact/tail row-major path resolves as if auto (it
        # has no level formulation to run) — SAY so (r05 postmortem:
        # silent remaps make A/B numbers unattributable)
        log.info("tpu_hist_kernel=pallas_level applies to level-phase "
                 "histograms only; the compact/tail row-major path "
                 "resolves as auto")
    if platform == "cpu":
        return "scatter"
    if use_quant or hist_dtype in ("bfloat16", "bf16"):
        return "pallas"
    tk = (tuned.get("f32_hist_kernel", "einsum")
          if tuned.applies(num_data) else "einsum")
    return tk if tk in ("einsum", "pallas", "scatter") else "einsum"


def resolve_hist_reduce(requested: str, num_data, platform: str) -> str:
    """Resolve ``tpu_hist_reduce=auto`` to a concrete histogram
    collective for the row-sharded learners (ISSUE 12).

    Explicit values pass through (eligibility fallback happens at the
    learner, attributably). ``auto``: allreduce on CPU (virtual-device
    collectives are shared-memory copies — the reduce_scatter win is
    ICI bytes + the divided scan, both device properties); on TPU the
    tuned cache's ``hist_reduce`` (re-learned by the session
    ``ab_hist_reduce_*`` arms at the 1M depth-10 shape, 3% margin),
    size-gated like every tuned flip, allreduce incumbent. Unknown
    cache values fall back — tuning must never be able to break
    training.
    """
    if requested != "auto":
        return requested
    if platform == "cpu":
        return "allreduce"
    tk = (tuned.get("hist_reduce", "allreduce")
          if tuned.applies(num_data) else "allreduce")
    return tk if tk in ("allreduce", "reduce_scatter") else "allreduce"


def resolve_level_hist_kernel(requested: str, num_data,
                              platform: str) -> str:
    """Resolve ``tpu_hist_kernel`` for the LEVEL phase's per-node
    histograms (core/level_grower.py; the compact/tail path resolves
    separately through resolve_hist_kernel).

    Explicit values pass through (``pallas_level`` = the one-launch
    sorted-segment Pallas kernel, ops/hist_level_pallas.py; a bare
    ``pallas`` stays einsum-pinned under blocks mode per ADVICE r05 —
    level_grower._resolve_rm_backend). ``auto``: scatter on CPU;
    on TPU the tuned cache's ``level_hist_backend`` (re-learned by the
    microbench ``hist_level`` A/B at level shapes), size-gated like
    every tuned flip, einsum fallback. Unknown cache values fall back —
    tuning must never be able to break training.
    """
    if requested != "auto":
        return requested
    if platform == "cpu":
        return "scatter"
    tk = (tuned.get("level_hist_backend", "einsum")
          if tuned.applies(num_data) else "einsum")
    return tk if tk in ("einsum", "pallas", "scatter", "pallas_level") \
        else "einsum"


class GBDT:
    """Gradient Boosting Decision Tree engine (ref: gbdt.h:28)."""

    NAME = "gbdt"

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction]):
        self.config = config
        self.train_set = train_set
        self.objective = objective
        # async-boosting state must exist before the `models` setter runs
        self._pending: List[_PendingTree] = []
        self._stop_checked = 0        # pending entries already stop-checked
        self._async_mode: Optional[bool] = None   # resolved lazily
        self._async_disabled = False  # set on stop-rollback / fallbacks
        self._async_delta_fn = None
        self._async_trav_fn: Dict[int, object] = {}
        # phase-tagged liveness (ISSUE 4): beats + the process-global
        # stall watchdog; all no-ops unless a heartbeat file is
        # configured (tpu_heartbeat_file / LGBM_TPU_HEARTBEAT)
        self._hb_warm = False         # first iteration (compile) done
        self._hb_policy = None
        # serving state (ISSUE 5): the generation counter advances on every
        # DESTRUCTIVE model mutation (rollback, shuffle, item replacement,
        # in-place tree edits via invalidate_serving_cache); tail appends
        # leave it alone so the packed forest can grow incrementally
        self._model_gen = 0
        # resolved histogram collective attribution (ISSUE 12): "n/a"
        # for non-row-sharded learners, else the resolved mode with
        # fallback attribution (e.g. "allreduce(fallback:efb)") — the
        # ONE string bench records carry (same contract as PR6's
        # level_backend: numbers must be attributable to a comm config)
        self._hist_reduce = "n/a"
        self._serving: Optional[ServingEngine] = None
        self._serving_mappers = None  # stable identity for binner caching
        self.models: List[HostTree] = []
        self.iter = 0
        self.num_init_iteration = 0
        self.shrinkage_rate = float(config.learning_rate)
        self.valid_sets: List[_ValidData] = []
        self.train_metrics: List[Metric] = []
        self.best_score_by_metric: Dict[str, float] = {}
        # model-level metadata for IO
        self.max_feature_idx = 0
        self.label_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.average_output = False  # RF sets true

        if objective is not None:
            self.num_tree_per_iteration = objective.num_model_per_iteration
        else:
            self.num_tree_per_iteration = int(config.num_class)

        if train_set is not None:
            self._setup_train(train_set)

    # ---- async boosting: deferred host materialization ----------------
    @property
    def models(self) -> List[HostTree]:
        """Canonical host model list. Materializes any trees still living
        on device (async fast path) before returning, so every consumer —
        IO, eval on models, SHAP, refit, DART drops — sees the full
        ensemble. The returned list is the live internal list (callers
        append/del in place, mirroring models_ in the reference)."""
        self._flush_pending()
        return self._models

    @models.setter
    def models(self, value: List[HostTree]) -> None:
        self._flush_pending()   # never silently drop device-side trees
        self._note_models_mutation(True)
        self._models = _ModelList(value, note=self._note_models_mutation)

    def _note_models_mutation(self, destructive: bool) -> None:
        if destructive:
            self._model_gen += 1

    def invalidate_serving_cache(self) -> None:
        """Declare tree CONTENT mutated in place (set_leaf_output, refit
        decay, DART drop/normalize) — mutations the models-list generation
        counter cannot observe. Forces a full forest repack on the next
        device prediction."""
        self._model_gen += 1

    def _n_models_total(self) -> int:
        """Model count including not-yet-materialized device trees."""
        return len(self._models) + len(self._pending)

    def _async_on(self) -> bool:
        """Resolve (once) whether the sync-free fast path applies.

        Requirements: plain GBDT boosting with no per-iteration host
        feedback — no linear leaves (host lstsq), no CEGB bookkeeping,
        no quantized leaf renewal, no L1-style RenewTreeOutput, no
        position bias Newton step, and a sampler that either never
        reads gradients (bagging) or can sample on device (GOSS via
        sample_dev). Any tree learner qualifies: the distributed
        learners' collectives live inside the jitted grower program,
        and the device trees they return are replicated, so the
        deferred-materialization machinery is learner-agnostic."""
        if self._async_disabled:
            return False
        if self._async_mode is None:
            mode = str(self.config.tpu_async_boosting).lower()
            want = (jax.default_backend() != "cpu" if mode == "auto"
                    else mode in ("true", "1", "yes", "on"))
            self._async_mode = bool(
                want and self.NAME == "gbdt"
                and self._grow is not None
                and self._gh_fn is not None
                and not self._linear
                # stop-check rollback traverses the full training table
                # (bins_dev), which sharded ingestion never materializes
                and not getattr(self, "_sharded_ingest", False)
                and not self._cegb_enabled
                and not (self.grower_cfg.quantized and
                         self.config.quant_train_renew_leaf)
                and (self.objective is None or
                     not self.objective.is_renew_tree_output())
                and not self._pos_bias
                and (not self.sample_strategy.needs_grad or
                     hasattr(self.sample_strategy, "sample_dev"))
                and all(self.class_need_train))
            if want and not self._async_mode:
                log.info("tpu_async_boosting: falling back to the "
                         "synchronous path (a per-iteration host step is "
                         "required by the active features)")
        return self._async_mode

    def _flush_pending(self) -> None:
        """Materialize pending device trees into HostTrees (batched).

        One jnp.stack per tree field + one device_get of the stacked
        pytree keeps the transfer count independent of how many trees are
        pending (each transfer costs a full tunnel round-trip). The stop
        check runs first so degenerate iterations are rolled back before
        they could be materialized — a flush between periodic checks must
        not let the 'no more splits' condition slip through."""
        if not self._pending:
            return
        self._async_stop_check()
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        self._stop_checked = 0
        self._hb_sync_beat()
        with global_timer.section("Tree::ToHost"):
            stacked = jax.tree.map(lambda *xs: jnp.stack(xs),
                                   *[p.tree for p in pending])
            host_stacked = jax.device_get(stacked)
        for i, p in enumerate(pending):
            arrs = jax.tree.map(lambda x: x[i], host_stacked)
            host = HostTree(arrs, self.train_set.used_feature_map)
            if host.num_leaves <= 1:
                # a per-class degenerate tree in an iteration where other
                # classes still split (the all-degenerate case was rolled
                # back by the stop check above): the device update masked
                # its score contribution, so a constant tree keeps the
                # model list aligned (ref: gbdt.cpp TrainOneIter appends
                # a zero tree for classes with no valid split)
                self._models.append(self._constant_tree(p.bias))
                continue
            self._finalize_tree(host)
            host.shrink(p.shrinkage)
            if abs(p.bias) > K_EPSILON:
                host.add_bias(p.bias)
            guard = self._numeric_guard()
            if guard is not None:
                # async commit point (ISSUE 19): the deferred trees are
                # first observable HERE — non-finite leaf outputs must
                # not reach the model list on this path either
                guard.check_leaves(host.leaf_value[:host.num_leaves],
                                   self.iter)
            self._models.append(host)

    def _async_stop_check(self) -> bool:
        """Batched 'no more leaves to split' detection (exact).

        Fetches num_leaves over the pending window in one round-trip.
        An iteration stops training only when ALL K class trees are
        degenerate (≡ should_continue in the sync path); a single
        degenerate class among splitting ones just becomes a constant
        tree at flush. The engine's first iteration is the exception —
        its degenerate branch carries init-score side effects — so any
        degenerate tree there rolls back too. On detection: roll back
        every iteration from the stopping one (subtract score
        contributions, restore sampler RNG), disable the fast path, and
        let the caller's next train_one_iter replay those iterations
        synchronously — the sync path then reproduces the reference's
        stop behavior exactly."""
        if self._stop_checked >= len(self._pending):
            return False
        new = self._pending[self._stop_checked:]
        self._hb_sync_beat()
        with global_timer.section("GBDT::StopCheck"):
            nls = np.asarray(jax.device_get(
                jnp.stack([p.tree.num_leaves for p in new])))
        self._stop_checked = len(self._pending)
        K = self.num_tree_per_iteration
        degen_by_it: Dict[int, int] = {}
        for p, nl in zip(new, nls):
            if nl <= 1:
                degen_by_it[p.it] = degen_by_it.get(p.it, 0) + 1
        first_model_it = (self._pending[0].it
                          if len(self._models) == 0 else -1)
        stop_its = [it for it, cnt in degen_by_it.items()
                    if cnt >= K or it == first_model_it]
        if not stop_its:
            return False
        first_it = min(stop_its)
        rolled_back = self.iter - first_it
        log.debug(f"async boosting: degenerate iteration {first_it}; "
                  f"rolling back {rolled_back} iteration(s) and replaying "
                  "synchronously")
        self._async_rollback_from(first_it)
        self._async_disabled = True
        # Replay EVERY rolled-back iteration through the sync path NOW —
        # not on the caller's future train_one_iter calls: a terminal
        # flush from predict/save has no next iteration (which would drop
        # the sync path's degenerate side effects, e.g. the
        # first-iteration boost-from-average constant tree), and the
        # engine's fixed-round loop would otherwise end short by however
        # many iterations the window held. The sync path stops the replay
        # the moment the degeneracy is real for ALL classes, exactly like
        # an all-sync run. Recursion is safe: _async_disabled is set, and
        # the kept pending entries are already stop-checked, so the sync
        # path's entry flush materializes them without re-entering this
        # check.
        finished = False
        for _ in range(rolled_back):
            finished = bool(self.train_one_iter())
            if finished:
                break
        return finished

    def _async_traverse_add(self, score, tree_dev: TreeArrays, bins_dev,
                            rate: float, k: int, num_steps: int = None):
        """score[k] += rate * tree(bins) with degenerate trees masked —
        the one jitted traversal shared by valid-set updates (+rate) and
        rollback (-rate); jax.jit caches per bins/score shape. The
        traversal product rounds in its own dispatch, separate from the
        accumulate, for the FMA reason documented on _leaf_delta.
        ``num_steps`` (static, bucketed via depth_steps) bounds the
        lockstep walk when the caller knows the tree's depth; rollback of
        grower-resident device trees passes None (exhaustive bound — depth
        is only computed on the host copy, and a rollback must not sync)."""
        steps = (self.config.num_leaves - 1 if num_steps is None
                 else int(num_steps))
        fn = self._async_trav_fn.get(steps)
        if fn is None:
            meta = self.feature_meta

            @jax.jit
            def fn(tree, bins, rate):
                leaf = tree_leaf_bins(tree, bins, meta.num_bin,
                                      meta.missing_type, meta.default_bin,
                                      num_steps=steps)
                return jnp.where(tree.num_leaves > 1,
                                 tree.leaf_value[leaf] * rate,
                                 jnp.float32(0.0))

            self._async_trav_fn[steps] = fn
        delta = fn(tree_dev, bins_dev, jnp.float32(rate))
        return score.at[k].add(delta)

    def _async_rollback_from(self, it0: int) -> None:
        """Undo every pending iteration >= it0: subtract each tree's score
        contribution (device traversal — the grower's leaf assignment and
        tree_leaf_bins decide splits identically), undo any init score the
        iteration's _boost_from_average added (the sync replay re-adds
        it), and restore the sampler RNG states captured when the
        iteration started."""
        keep = [p for p in self._pending if p.it < it0]
        drop = [p for p in self._pending if p.it >= it0]
        for p in drop:
            self.score = self._async_traverse_add(
                self.score, p.tree, self.bins_dev, -p.shrinkage, p.k)
            if abs(p.bias) > K_EPSILON:
                self.score = self.score.at[p.k].add(-p.bias)
            for vd in self.valid_sets:
                vd.score = self._async_traverse_add(
                    vd.score, p.tree, vd.bins_dev, -p.shrinkage, p.k)
                if abs(p.bias) > K_EPSILON:
                    vd.score = vd.score.at[p.k].add(-p.bias)
        for p in drop:
            if p.it == it0:
                if p.rng_state is not None:
                    self.sample_strategy.rng.bit_generator.state = \
                        p.rng_state
                if p.col_rng_state is not None:
                    self._col_rng.bit_generator.state = p.col_rng_state
                break
        self._pending = keep
        self._stop_checked = min(self._stop_checked, len(keep))
        self.iter = it0

    def _train_one_iter_async(self) -> bool:
        """Sync-free TrainOneIter: every product stays on device; the only
        host work is RNG draws and dispatch (see module docstring)."""
        K = self.num_tree_per_iteration
        init_scores = [0.0] * K
        for k in range(K):
            init_scores[k] = self._boost_from_average(k)
        # RNG snapshots for exact rollback on deferred stop detection
        samp_state = (self.sample_strategy.rng.bit_generator.state
                      if getattr(self.sample_strategy, "rng", None)
                      is not None else None)
        # jaxlint: disable=JL005 — async fast path: sections deliberately
        # time DISPATCH only (a sync= barrier would serialize the very
        # pipeline this path exists to keep sync-free; device time shows
        # up in Tree::ToHost / GBDT::StopCheck at the batched fetches)
        with global_timer.section("GBDT::Boosting"):
            grad, hess = self._gh_fn(self.score)
            if K == 1:
                grad = grad[None, :]
                hess = hess[None, :]
        sel_dev = w_dev = None
        strat = self.sample_strategy
        if strat.needs_grad:
            # device-capable gradient sampler (GOSS): stateless jax key
            # chain, so there is no RNG state to snapshot. A stop-check
            # rollback replays through the SYNC path, which re-draws
            # from this same fold_in(key, iter) chain once the flag
            # below is set — bit-exact replay holds for GOSS exactly as
            # it does for the RNG-snapshot samplers (bagging)
            key = jax.random.fold_in(self._goss_key, self.iter)
            pair = strat.sample_dev(self.iter, grad, hess, key)
            if pair is not None:
                sel_dev, w_dev = pair
                self._goss_dev_used = True
            sample = pair
        else:
            sdev = getattr(strat, "sample_dev", None)
            sample = (sdev(self.iter, key=self._goss_key)
                      if sdev is not None else None)
            if sample is not None:      # opt-in device bagging
                sel_dev, w_dev = sample
            else:
                sample = strat.sample(self.iter)
                if sample is not None:
                    sel_dev = jnp.asarray(sample[0])
                    w_dev = jnp.asarray(sample[1])

        for k in range(K):
            col_state = self._col_rng.bit_generator.state
            g, h = grad[k], hess[k]
            if sample is not None:
                gh = jnp.stack([g * w_dev, h * w_dev, sel_dev], axis=1)
            else:
                gh = jnp.stack([g, h, jnp.ones_like(g)], axis=1)
            fmask = self._feature_mask()
            rng_key = None
            if self._grow_rng is not None:
                rng_key = jax.random.fold_in(
                    self._grow_rng, self.iter * K + k)
            # jaxlint: disable=JL005 — dispatch-only timing, see above
            with global_timer.section("TreeLearner::Train"):
                tree_dev, leaf_id = self._grow(
                    self._train_bins(), gh, fmask,
                    self._cegb_penalty(), rng_key)
            rate = jnp.float32(self.shrinkage_rate)
            # jaxlint: disable=JL005 — dispatch-only timing, see above
            with global_timer.section("GBDT::UpdateScore"):
                delta = self._leaf_delta(tree_dev.leaf_value,
                                         tree_dev.num_leaves, leaf_id,
                                         rate)
                self.score = self._score_add(self.score, delta, k)
            for vd in self.valid_sets:
                vd.score = self._async_traverse_add(
                    vd.score, tree_dev, vd.bins_dev,
                    self.shrinkage_rate, k)
            self._pending.append(_PendingTree(
                tree=tree_dev, k=k, it=self.iter,
                shrinkage=self.shrinkage_rate, bias=init_scores[k],
                rng_state=samp_state if k == 0 else None,
                col_rng_state=col_state))
        self.iter += 1
        interval = max(1, int(self.config.tpu_stop_check_interval))
        if self.iter % interval == 0:
            return self._async_stop_check()
        return False

    # ------------------------------------------------------------------
    def _setup_train(self, train: BinnedDataset) -> None:
        cfg = self.config
        cfg.warn_unimplemented()
        # persistent compile cache + liveness instrumentation (ISSUE 4)
        # — wired here (not only engine.train) so directly-constructed
        # Boosters get them too, BEFORE the grower compiles below; the
        # env knobs count like the param so a supervisor's exported
        # LGBM_TPU_COMPILE_CACHE reaches Booster(params, ds) users
        import os as _os

        from ..utils.jit_cache import (ENV_COMPILE_CACHE, ENV_JIT_CACHE,
                                       enable_persistent_cache)
        if cfg.tpu_compile_cache_dir or \
                _os.environ.get(ENV_COMPILE_CACHE) or \
                _os.environ.get(ENV_JIT_CACHE):
            enable_persistent_cache(
                str(cfg.tpu_compile_cache_dir) or None)
        # gang rank wiring (ISSUE 10): in a multi-process world every
        # rank writes its OWN heartbeat file (rank_path suffix — the
        # gang supervisor's read convention) so N ranks never clobber
        # one liveness file, and the rank_kill fault site knows which
        # rank it is
        try:
            self._process_rank = int(jax.process_index())
            _world = int(jax.process_count())
        except Exception:  # noqa: BLE001 — no backend/world yet
            self._process_rank, _world = 0, 1
        hb_path = str(cfg.tpu_heartbeat_file) or \
            (_os.environ.get(heartbeat.ENV_HEARTBEAT) or "").strip()
        if hb_path:
            if _world > 1:
                hb_path = heartbeat.rank_path(hb_path,
                                              self._process_rank)
            heartbeat.install(hb_path)
        if float(cfg.tpu_gang_collective_timeout_s or 0.0) > 0.0:
            from ..distributed import set_collective_timeout
            set_collective_timeout(
                float(cfg.tpu_gang_collective_timeout_s))
        policy = heartbeat.StallPolicy.from_env()
        if float(cfg.tpu_stall_sec or 0.0) > 0.0:
            s = float(cfg.tpu_stall_sec)
            policy = dataclasses.replace(
                policy, stall_sec={p: s for p in policy.stall_sec},
                default_stall=s)
        self._hb_policy = policy
        self.num_data = train.num_data
        self.max_feature_idx = train.num_total_features - 1
        self.feature_names = list(train.feature_names)
        self.feature_infos = train.feature_infos()
        md = train.metadata

        if self.objective is not None:
            self.objective.init(md, train.num_data)
        self.train_metrics = []

        mappers = train.used_bin_mappers()
        # monotone constraints are per ORIGINAL feature; gather to used
        # features (ref: feature_histogram.hpp:1440-1443)
        monotone = None
        if cfg.monotone_constraints:
            mc_in = np.asarray(cfg.monotone_constraints, np.int32)
            if len(mc_in) != train.num_total_features:
                log.fatal(
                    f"monotone_constraints has {len(mc_in)} entries but the "
                    f"dataset has {train.num_total_features} features")
            if np.any(mc_in != 0):
                monotone = mc_in[train.used_feature_map]
        mc_method = cfg.monotone_constraints_method
        if monotone is not None:
            if mc_method in ("intermediate", "advanced") and \
                    cfg.extra_trees:
                log.warning(f"monotone_constraints_method={mc_method} "
                            "does not compose with extra_trees; using "
                            "'basic'")
                mc_method = "basic"
        contri = None
        if cfg.feature_contri:
            fc_in = np.asarray(cfg.feature_contri, np.float64)
            if len(fc_in) != train.num_total_features:
                log.fatal(
                    f"feature_contri has {len(fc_in)} entries but the "
                    f"dataset has {train.num_total_features} features")
            if np.any(fc_in != 1.0):
                contri = fc_in[train.used_feature_map]
        self.feature_meta = FeatureMeta.from_mappers(
            mappers, monotone, penalty=contri) if mappers else None
        self.num_bin_max = int(max((m.num_bin for m in mappers), default=2))
        # the feature-major device copy is only needed by traversal paths
        # (rollback, DART drops, continued training, valid replay) — it is
        # materialized lazily so training doesn't hold a dead full-dataset
        # copy in HBM next to bins_rf / bins_sharded
        self._sharded_ingest = getattr(train, "shard", None) is not None
        # under sharded ingestion train.bins holds only the LOCAL row
        # shard — it must never masquerade as the full [F, N] table
        # (bins_dev guards; continued training replays shard-locally)
        self._bins_fr_host = None if self._sharded_ingest else train.bins
        self._bins_dev_cache = None

        K = self.num_tree_per_iteration
        self.score = jnp.zeros((K, self.num_data), jnp.float32)
        if md.init_score is not None:
            init = md.init_score.reshape(-1, self.num_data).astype(np.float32)
            self.score = jnp.asarray(init)
            self.has_init_score = True
        else:
            self.has_init_score = False

        self.class_need_train = [
            self.objective.class_need_train(k) if self.objective else True
            for k in range(K)]

        self.sample_strategy = SampleStrategy.create(
            cfg, self.num_data, K, metadata=md)
        # stateless key chain for device-side gradient sampling (GOSS
        # under async boosting); same seed the host sampler honors
        self._goss_key = jax.random.PRNGKey(int(cfg.bagging_seed))

        hp = SplitHyperParams(
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=cfg.min_gain_to_split,
            max_delta_step=cfg.max_delta_step,
            path_smooth=cfg.path_smooth,
            monotone_penalty=cfg.monotone_penalty,
            max_cat_threshold=int(cfg.max_cat_threshold),
            cat_l2=float(cfg.cat_l2), cat_smooth=float(cfg.cat_smooth),
            max_cat_to_onehot=int(cfg.max_cat_to_onehot),
            min_data_per_group=int(cfg.min_data_per_group))
        backend = "xla"
        if cfg.tpu_use_pallas and jax.default_backend() == "tpu":
            backend = "pallas"
        # interaction constraints: "[0,1,2],[2,3]" over ORIGINAL feature
        # indices -> tuple of tuples of USED indices (ref: col_sampler.hpp,
        # config.h interaction_constraints)
        groups = None
        if cfg.interaction_constraints:
            parsed = _parse_interaction_constraints(
                cfg.interaction_constraints)
            if not parsed:
                log.fatal(
                    f"could not parse interaction_constraints="
                    f"{cfg.interaction_constraints!r}; expected e.g. "
                    "\"[0,1,2],[2,3]\"")
            orig2used = _orig_to_used(train.used_feature_map)
            groups = tuple(
                tuple(orig2used[f] for f in grp if f in orig2used)
                for grp in parsed)
        self._bynode = cfg.feature_fraction_bynode < 1.0
        # compact row scheduling (O(rows_in_leaf) histogram passes) is the
        # serial default; "full" keeps the masked full-pass program.
        # tpu_hist_kernel=auto picks scatter-add on the CPU backend
        # (einsum one-hot is pathologically slow there) and the MXU
        # einsum kernel on TPU.
        row_sched = cfg.tpu_row_scheduling
        hist_dtype = cfg.tpu_hist_dtype
        rm_backend = resolve_hist_kernel(
            cfg.tpu_hist_kernel, hist_dtype, bool(cfg.use_quantized_grad),
            self.num_data, jax.default_backend())
        level_backend = resolve_level_hist_kernel(
            cfg.tpu_hist_kernel, self.num_data, jax.default_backend())
        part_mode = cfg.tpu_partition_mode
        if part_mode == "auto" and jax.default_backend() == "cpu":
            # CPU favors scatter at every size; on TPU "auto" passes
            # through to the grower, which picks sort for big buckets
            # (1.77 vs 5.17 ms at 1M rows, docs/TPU_RUNBOOK.md) and
            # scatter for small ones (lax.sort's fixed bitonic cost)
            part_mode = "scatter"
        self.grower_cfg = GrowerConfig(
            num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
            num_bin=self.num_bin_max, hparams=hp, hist_backend=backend,
            block_rows=cfg.tpu_rows_per_block,
            bynode_mask=self._bynode, interaction_groups=groups,
            row_sched=row_sched, hist_dtype=hist_dtype,
            hist_rm_backend=rm_backend,
            level_hist_backend=level_backend,
            partition_mode=part_mode,
            min_bucket=cfg.tpu_min_bucket,
            quantized=bool(cfg.use_quantized_grad),
            quant_bins=int(cfg.num_grad_quant_bins),
            stochastic_rounding=bool(cfg.stochastic_rounding),
            extra_trees=bool(cfg.extra_trees),
            mc_method=mc_method)
        # per-tree PRNG: stochastic rounding + extra_trees thresholds
        # (extra_seed falls back to seed, ref: config.h extra_seed)
        need_rng = bool(cfg.use_quantized_grad) or bool(cfg.extra_trees)
        rng_seed = (cfg.extra_seed if cfg.extra_trees and
                    cfg.extra_seed is not None
                    else (cfg.seed if cfg.seed is not None else 0))
        self._grow_rng = (jax.random.PRNGKey(int(rng_seed))
                          if need_rng else None)
        self._score_add_fn = None
        # ---- tree learner selection (ref: tree_learner.cpp:17 factory) ----
        # serial runs the single-program grower; data/voting shard rows and
        # feature shards columns over a jax Mesh, with the FULL TrainOneIter
        # (objectives, bagging, multiclass, ranking, eval) around them —
        # the parallel learners are drop-in under boosting exactly like
        # parallel_tree_learner.h:26-207
        self._tree_learner = "serial"
        self._mesh = None
        self._row_pad = 0
        self._feat_pad = 0
        # sharded-ingest row layout (set in _setup_distributed): padded
        # global slot -> concatenated-table row (-1 = pad), and its
        # inverse for un-permuting gathered leaf ids
        self._shard_row_map = None
        self._shard_inv_map = None
        avail = len(jax.devices())
        want = cfg.tpu_num_devices if cfg.tpu_num_devices > 0 else avail
        self._n_dev = min(want, avail)
        tl = cfg.tree_learner
        # linear trees: serial only; objective/missing conflicts fatal
        # (ref: config.cpp:426 CheckParamConflict linear_tree block)
        self._linear = bool(cfg.linear_tree)
        if self._linear:
            if train.raw is None:
                log.fatal("linear_tree requires the training Dataset to be "
                          "constructed with linear_tree=true in its params "
                          "(raw feature values are needed; datasets loaded "
                          "from binary files do not carry them)")
            if tl != "serial":
                log.warning("Linear tree learner must be serial")
                tl = "serial"
            if cfg.zero_as_missing:
                log.fatal("zero_as_missing must be false when fitting "
                          "linear trees")
            if self.objective is not None and \
                    getattr(self.objective, "NAME", "") == "regression_l1":
                log.fatal("Cannot use regression_l1 objective when fitting "
                          "linear trees")
        if tl in ("data", "voting", "feature"):
            if self._n_dev > 1:
                self._tree_learner = tl
                # quantized int8 gradients compose with all three learners
                # (global scales via pmax + exact int32 hist psum ≡ the
                # reference's int-histogram ReduceScatter variants,
                # data_parallel_tree_learner.cpp:285-299), as does
                # extra_trees (replicated per-tree key → identical random
                # thresholds on every device)
                # compact O(rows_in_leaf) scheduling composes with all
                # three learners; under feature-parallel the partition
                # column arrives via the once-per-split owner broadcast
                # (feature_parallel.py fetch_bin_column)
            else:
                cap = (f"tpu_num_devices={cfg.tpu_num_devices}"
                       if 0 < cfg.tpu_num_devices < avail
                       else f"only {avail} device(s) visible")
                log.warning(f"tree_learner={tl} requested but {cap}; "
                            "running serial")
        if (self._tree_learner not in ("data", "voting") and
                cfg.tpu_hist_reduce == "reduce_scatter"):
            # _hist_reduce stays "n/a": no histogram collective runs at
            # all outside the row-sharded learners (feature-parallel
            # ships one winner record + one column; serial — including
            # the injected-collectives per-worker program, whose
            # host-side hooks are allreduce by construction — has no
            # mesh), so there is nothing to scatter
            log.info(
                "tpu_hist_reduce=reduce_scatter applies to the "
                "row-sharded learners (tree_learner=data/voting); "
                f"tree_learner={self._tree_learner!r} keeps its "
                "existing collective contract")
        if self._sharded_ingest and self._tree_learner not in ("data",
                                                               "voting"):
            log.fatal(
                "sharded ingestion (pre_partition/tpu_ingest='sharded') "
                "requires the row-sharded learners: set "
                "tree_learner=data (or voting) with more than one "
                f"device — got tree_learner={self._tree_learner!r} over "
                f"{self._n_dev} device(s)")
        # ---- multi-value sparse storage (≡ SparseBin/MultiValSparseBin,
        # sparse_bin.hpp:858): scatter histogram over the stored
        # nonzeros; default-bin mass reconstructed at scan time.
        # Composes with the data-parallel learner (rows of the [R, K]
        # packing shard like dense rows; the default-bin fix runs on the
        # psum'd global histogram); voting/feature stay serial fallbacks
        self._multival = train.bins_mv is not None
        if self._multival:
            fallback = []
            if self._tree_learner not in ("serial", "data", "voting"):
                fallback.append(f"tree_learner={self._tree_learner}")
                self._tree_learner = "serial"
            if fallback:
                log.warning("multi-value sparse storage supports the "
                            "serial, data and voting learners "
                            "(consider tree_learner=data); overriding: "
                            + ", ".join(fallback))
            self.grower_cfg = dataclasses.replace(
                self.grower_cfg, hist_backend="multival")
        # "level" trains on the same row-major layout as "compact"
        self._compact = self.grower_cfg.row_sched in ("compact", "level")

        # ---- EFB bundling (ref: dataset.cpp:112 FindGroups) -----------
        self._bundle = None
        train_bins_host = train.bins
        forced = self._load_forced_splits(train)
        if forced is not None and cfg.enable_bundle:
            # forced splits need per-feature partition columns the bundled
            # layout doesn't expose; skip bundling BEFORE it inflates
            # num_bin_max / runs the O(F*R) conflict scan
            log.warning("forced splits with EFB bundling are untested; "
                        "disabling bundling")
        elif cfg.enable_bundle and self._sharded_ingest:
            # the conflict scan would see only the local row shard —
            # per-rank bundle disagreement desyncs the SPMD program, so
            # sharded ingestion trains unbundled (a replicated-sample
            # bundle agreement is future work)
            log.info("EFB bundling is disabled under sharded ingestion "
                     "(conflict scans need the global table)")
        elif (cfg.enable_bundle and
                self._tree_learner in ("serial", "data", "voting",
                                       "feature") and
                (train.bins is not None or
                 getattr(train, "bins_grouped", None) is not None) and
                train.num_used_features > 1):
            from ..io.bundling import find_bundles, pack_bins
            nb_used = np.asarray([m.num_bin for m in mappers], np.int64)
            if getattr(train, "bins_grouped", None) is not None:
                # sparse sources packed straight into [G, R] at dataset
                # construction (pack_sparse_direct) — reuse their
                # BundleInfo instead of re-deriving it from a logical
                # matrix that was never materialized
                info = train.efb_info
            else:
                info = find_bundles(train.bins, nb_used,
                                    max_conflict_rate=cfg.max_conflict_rate)
            if info is not None:
                B_all = int(max(self.num_bin_max,
                                info.group_num_bin.max()))
                info.build_gather_map(B_all)
                train_bins_host = (train.bins_grouped
                                   if train.bins_grouped is not None
                                   else pack_bins(train.bins, info))
                self.num_bin_max = B_all
                self.grower_cfg = dataclasses.replace(self.grower_cfg,
                                                      num_bin=B_all)
                self._bundle = dict(
                    gather_map=info.gather_map, group=info.group,
                    offset=info.offset, default_bin=info.default_bin,
                    num_bin=info.num_bin, num_groups=info.num_groups)
                log.info(
                    f"EFB bundled {train.num_used_features} features into "
                    f"{info.num_groups} groups")
                if (self._tree_learner == "feature" and
                        self.feature_meta is not None and
                        self.feature_meta.monotone is not None and
                        self.grower_cfg.mc_method in ("intermediate",
                                                      "advanced")):
                    # refined monotone geometry shards per logical
                    # feature; the EFB group layout permutes features
                    # across shards in a way the box psum cannot follow
                    log.warning(
                        "refined monotone constraints are not supported "
                        "with tree_learner=feature + EFB; using 'basic'")
                    self.grower_cfg = dataclasses.replace(
                        self.grower_cfg, mc_method="basic")

        if (train_bins_host is None and self._bundle is None and
                getattr(train, "bins_grouped", None) is not None):
            # direct-bundled dataset but the bundle could not engage
            # (enable_bundle off at train time, forced splits, learner
            # mix): reconstruct the logical matrix so every downstream
            # path keeps its contract
            train_bins_host = train.ensure_logical_bins()

        # resolve tpu_row_scheduling="level" ONCE, before the packing
        # block and the learner branches: every eligibility input
        # (learner, bundle, forced, meta, cegb params, hooks) is known
        # here, and a fallback must happen before packed-bins decide on
        # the final scheduler (review finding: a late fallback crashed
        # distributed learners on the row-major layout and silently
        # lost packing)
        if self.grower_cfg.row_sched == "level":
            reasons = self._level_ineligibility(forced)
            if reasons:
                log.warning(
                    "tpu_row_scheduling='level' does not support "
                    f"{'; '.join(reasons)} — falling back to 'compact'")
                self.grower_cfg = dataclasses.replace(
                    self.grower_cfg, row_sched="compact")

        self.bins_rf = None
        self._bins_packed_dev = None
        self._packed_cols = 0
        if (self._compact and self._tree_learner == "serial" and
                train_bins_host is not None):
            # row-major copy for the gather path; bins_dev keeps the
            # feature-major layout used by prediction/traversal (the
            # distributed learners shard their own row-major copy)
            pb = str(cfg.tpu_packed_bins).lower()
            # auto: off until the on-device gather A/B records a win in
            # the tuned cache (u32 packed words gather 4x fewer elements;
            # measured on CPU proxy only so far). Only a literal JSON
            # true counts — any other cache value falls back to off.
            want_pack = (pb in ("true", "1", "yes", "on") or
                         (pb == "auto" and
                          tuned.applies(self.num_data) and
                          tuned.get("packed_bins", False) is True))
            # the level grower reads plain u8 [R, F] directly
            want_pack &= self.grower_cfg.row_sched == "compact"
            if want_pack and self.num_bin_max <= 255:
                # bit-pack 4 uint8 bins per uint32 word: quarters the
                # element count of the compact scheduler's per-leaf row
                # gathers (grower unpacks with shifts post-gather)
                rm = np.ascontiguousarray(
                    train_bins_host.T).astype(np.uint8)
                Rn, Fn = rm.shape
                W = (Fn + 3) // 4
                full = np.zeros((Rn, W * 4), np.uint8)
                full[:, :Fn] = rm
                self.bins_rf = jnp.asarray(
                    np.ascontiguousarray(full).view(np.uint32)
                    .reshape(Rn, W))
                self._packed_cols = Fn
            else:
                if want_pack:
                    log.warning("tpu_packed_bins: bins exceed uint8 "
                                f"(num_bin_max={self.num_bin_max}); "
                                "storing unpacked")
                self.bins_rf = jnp.asarray(
                    np.ascontiguousarray(train_bins_host.T))
        elif self._bundle is not None and self._tree_learner == "serial":
            # distributed learners train from their own sharded copy;
            # a replicated upload here would just duplicate the matrix
            self._bins_packed_dev = jnp.asarray(train_bins_host)
        if self._packed_cols:
            self.grower_cfg = dataclasses.replace(
                self.grower_cfg, packed_cols=self._packed_cols)
        # histogram pool policy (ref: histogram_pool_size / LRU
        # HistogramPool, feature_histogram.hpp:1368): when the [L, F, B, 3]
        # pool would blow the budget (wide data), drop the pool and compute
        # both children histograms per split instead. Level scheduling is
        # exempt: the pure mode keeps no pool at all, and the hybrid tail
        # REQUIRES the full pool (seeded from the level hists) — configs
        # whose pool exceeds the budget already fell back to compact in
        # _level_ineligibility above.
        if self._compact and self.grower_cfg.row_sched != "level":
            slot_bytes, limit_bytes = self._hist_budget(
                n_feat_fallback=train.num_used_features)
            pool_bytes = cfg.num_leaves * slot_bytes
            if pool_bytes > limit_bytes:
                n_slots = int(limit_bytes // max(slot_bytes, 1))
                if forced is not None:
                    log.warning(
                        "histogram pool exceeds the budget but forced "
                        "splits need it; keeping the full pool")
                elif self.grower_cfg.mc_method in ("intermediate",
                                                   "advanced") and \
                        self.feature_meta is not None and \
                        self.feature_meta.monotone is not None:
                    log.warning(
                        "histogram pool exceeds the budget but "
                        "monotone_constraints_method=intermediate re-scans "
                        "from it; keeping the full pool")
                elif (n_slots >= 2 and
                        self._tree_learner == "serial" and
                        not self._multival):
                    # LRU middle ground (≡ the reference's
                    # histogram_pool_size-capped pool): cached parents
                    # keep the subtraction trick; evicted parents
                    # recompute both children
                    self.grower_cfg = dataclasses.replace(
                        self.grower_cfg, hist_pool="bounded",
                        pool_slots=n_slots)
                    log.info(
                        f"histogram pool ({pool_bytes >> 20} MB) exceeds "
                        f"the budget; bounded LRU pool with {n_slots} "
                        "slots (recompute on miss)")
                else:
                    self.grower_cfg = dataclasses.replace(
                        self.grower_cfg, hist_pool="none")
                    log.info(
                        f"histogram pool ({pool_bytes >> 20} MB) exceeds "
                        "the budget; computing per-split child histograms "
                        "without a pool")
        self._setup_cegb(train)
        self._bins_mv_dev = None
        if self.feature_meta is None:
            self._grow = None
        elif self._multival:
            from ..ops.hist_multival import SparseBins
            if forced is not None:
                log.warning("forced splits are not supported with "
                            "multi-value sparse storage; ignoring")
                forced = None
            if self._tree_learner in ("data", "voting"):
                self._setup_distributed(train, None, None)
            else:
                idx_h, binv_h = train.bins_mv
                self._bins_mv_dev = SparseBins(jnp.asarray(idx_h),
                                               jnp.asarray(binv_h),
                                               train.num_used_features)
                fetch, prepare = self._multival_hooks(train)
                self._grow = jax.jit(make_tree_grower(
                    self.grower_cfg, self.feature_meta,
                    fetch_bin_column=fetch, prepare_split_hist=prepare,
                    prepare_is_pure=True))
        elif self._tree_learner == "serial":
            # external collective injection (≡ LGBM_NetworkInitWithFunctions,
            # ref: c_api.h:1674): the serial program becomes the per-worker
            # data-parallel program with user-owned transport. The
            # injection is SNAPSHOTTED here so several workers can be
            # set up sequentially in one process (each Booster keeps
            # its own rank/world).
            from ..distributed import injected_collectives, \
                make_injected_hooks
            self._inj = injected_collectives()
            hooks = make_injected_hooks()
            if hooks is not None:
                self._grow = jax.jit(make_tree_grower(
                    self.grower_cfg, self.feature_meta, forced=forced,
                    bundle=self._bundle, **hooks))
            elif self.grower_cfg.row_sched == "level":
                # eligibility already resolved before the packing
                # block; depth routes pure vs hybrid (docs/TPU_RUNBOOK
                # round-6 §3: the hybrid serves the DEFAULT 255-leaf
                # unbounded-depth config)
                from ..core.level_grower import (MAX_LEVEL_DEPTH,
                                                 make_level_grower)
                if 1 <= self.grower_cfg.max_depth <= MAX_LEVEL_DEPTH:
                    self._grow = jax.jit(
                        make_level_grower(self.grower_cfg,
                                          self.feature_meta,
                                          bundle=self._bundle))
                else:
                    from ..core.hybrid_grower import make_hybrid_grower
                    d0 = int(cfg.tpu_level_handoff_depth)
                    if d0 > MAX_LEVEL_DEPTH:
                        log.warning(
                            f"tpu_level_handoff_depth={d0} exceeds "
                            f"MAX_LEVEL_DEPTH={MAX_LEVEL_DEPTH}; "
                            "clamping")
                    self._grow = jax.jit(make_hybrid_grower(
                        self.grower_cfg, self.feature_meta,
                        bundle=self._bundle, handoff_depth=d0))
            else:
                self._grow = jax.jit(
                    make_tree_grower(self.grower_cfg, self.feature_meta,
                                     forced=forced, bundle=self._bundle))
        else:
            self._setup_distributed(train, forced, train_bins_host)

        # jitted gradient fn (device-resident labels/weights in the closure)
        self._pos_bias = False
        if self.objective is not None and \
                not isinstance(self.objective, CustomObjective):
            obj = self.objective
            if getattr(obj, "uses_position_bias", False):
                # biases are a traced argument so the host-side Newton
                # update (ref: UpdatePositionBiasFactors) feeds back in
                self._pos_bias = True
                self._gh_fn = jax.jit(
                    lambda s, b: obj.get_gradients(s[0], b))
            elif K == 1:
                self._gh_fn = jax.jit(lambda s: obj.get_gradients(s[0]))
            else:
                self._gh_fn = jax.jit(lambda s: obj.get_gradients(s))
        else:
            self._gh_fn = None

        # feature sampling state (ref: col_sampler.hpp)
        self._col_rng = np.random.default_rng(cfg.feature_fraction_seed)
        self.num_used_features = train.num_used_features

    def _multival_hooks(self, train: BinnedDataset):
        """Multival grower hooks (shared by the serial and data-parallel
        builders so the default-bin semantics cannot drift): the
        column accessor for partitions and the FixHistogram-style
        default-bin reconstruction (ops/hist_multival.py)."""
        from ..ops.hist_multival import (make_default_bin_fix,
                                         make_fetch_bin_column)
        dflt = np.asarray(
            [m.default_bin for m in train.used_bin_mappers()], np.int32)
        return (make_fetch_bin_column(dflt),
                make_default_bin_fix(dflt, self.num_bin_max))

    def _train_bins(self):
        """Bins array the grower trains on (layout depends on the learner;
        the distributed wrapper holds its own sharded copy)."""
        if self._multival:
            return self._bins_mv_dev
        if self._tree_learner != "serial":
            return None
        if self._compact:
            return self.bins_rf
        if self._bins_packed_dev is not None:
            return self._bins_packed_dev
        return self.bins_dev

    @property
    def bins_dev(self):
        """Feature-major [F, R] device bins for traversal paths, lazily
        materialized (training reads bins_rf / bins_sharded instead).
        With multi-value sparse storage the dense matrix is reconstructed
        on demand — only rollback/DART/continued-training traversal needs
        it, and it costs the dense footprint (warned once)."""
        if getattr(self, "_sharded_ingest", False):
            log.fatal(
                "this operation needs the full [F, N] training table, "
                "which sharded ingestion never materializes on one host "
                "— rollback/DART/refit over a sharded train set are not "
                "supported (use tpu_ingest='replicated' for them)")
        mv_pair = None
        if (self._bins_dev_cache is None and self._bins_fr_host is None and
                self.train_set is not None and
                getattr(self.train_set, "bins_grouped", None) is not None):
            # direct-bundled storage: reconstruct logical bins once for
            # the traversal consumer (same cost note as multival below)
            log.warning("densifying EFB-bundled bins for a traversal "
                        "path (rollback/DART/continued training) — this "
                        "costs the logical bin footprint")
            self._bins_fr_host = self.train_set.ensure_logical_bins()
        if self._bins_dev_cache is None and self._bins_fr_host is None:
            if getattr(self, "_bins_mv_dev", None) is not None:
                mv_pair = (self._bins_mv_dev.idx, self._bins_mv_dev.binv)
            elif (self.train_set is not None and
                    self.train_set.bins_mv is not None):
                # distributed multival keeps only the sharded SparseBins;
                # densify from the host packing for traversal consumers
                mv_pair = self.train_set.bins_mv
        if mv_pair is not None:
            from ..ops.hist_multival import densify
            log.warning("densifying multi-value sparse bins for a "
                        "traversal path (rollback/DART/continued "
                        "training) — this costs the dense bin footprint")
            dflt = np.asarray(
                [m.default_bin for m in self.train_set.used_bin_mappers()],
                np.int32)
            self._bins_dev_cache = jnp.asarray(
                densify(mv_pair[0], mv_pair[1], dflt))
        elif (self._bins_dev_cache is None and
                self._bins_fr_host is not None):
            self._bins_dev_cache = jnp.asarray(self._bins_fr_host)
        return self._bins_dev_cache

    # ------------------------------------------------------------------
    def _resolve_hist_reduce_mode(self, tl: str, forced) -> str:
        """Resolve + eligibility-gate the histogram collective for the
        row-sharded learners (ISSUE 12), recording the attribution
        string bench reads (``self._hist_reduce``).

        The reduce-scatter contract scans feature WINDOWS with a packed
        small-record combine — numerical dense only for now. Everything
        else resolves to the existing allreduce path, logged once at
        INFO with the reason (the PR6 backend-fallback rule: silent
        remaps make A/B numbers unattributable)."""
        cfg = self.config
        mode = resolve_hist_reduce(cfg.tpu_hist_reduce, self.num_data,
                                   jax.default_backend())
        if tl not in ("data", "voting"):
            self._hist_reduce = "n/a"   # no histogram collective at all
            return "allreduce"
        if mode != "reduce_scatter":
            self._hist_reduce = "allreduce"
            return "allreduce"
        reasons = []
        if self._bundle is not None:
            reasons.append("efb")
        if self._multival:
            reasons.append("multival")
        if forced is not None and tl == "data":
            reasons.append("forced-splits")
        meta = self.feature_meta
        try:
            has_cat = bool(np.any(np.asarray(meta.is_categorical)))
        except Exception:
            has_cat = True
        if has_cat:
            reasons.append("categorical")
        if meta.monotone is not None:
            reasons.append("monotone")
        if reasons:
            why = "+".join(reasons)
            log.info(
                f"tpu_hist_reduce={cfg.tpu_hist_reduce} resolves to "
                f"allreduce: reduce_scatter is not yet eligible with "
                f"{why} (feature windows carry dense numerical scan "
                "state only)")
            self._hist_reduce = f"allreduce(fallback:{why})"
            return "allreduce"
        self._hist_reduce = "reduce_scatter"
        return "reduce_scatter"

    # ------------------------------------------------------------------
    def _setup_distributed(self, train: BinnedDataset, forced,
                           bins_host=None) -> None:
        """Build the mesh + sharded grower for tree_learner=data/voting/
        feature (ref: parallel_tree_learner.h — the learners are drop-in
        replacements under the unchanged boosting loop; SURVEY.md §3.3).

        Rows (data/voting) or features (feature) are padded to a multiple
        of the mesh size; padding rows carry gh = 0 and padded features are
        1-bin (never splittable), so they are invisible to training.
        """
        from ..parallel import (build_mesh, make_data_parallel_grower,
                                make_feature_parallel_grower,
                                make_voting_parallel_grower,
                                pad_feature_meta, padded_features)
        from ..parallel.mesh import (DATA_AXIS, FEATURE_AXIS, padded_rows,
                                     row_sharding)
        from jax.sharding import NamedSharding, PartitionSpec as P

        cfg = self.config
        tl = self._tree_learner
        n_dev = self._n_dev
        N = self.num_data
        F = train.num_used_features
        if forced is not None and tl != "data":
            log.warning(f"forcedsplits_filename is not supported with "
                        f"tree_learner={tl}; ignoring forced splits")
            forced = None
        # histogram collective (ISSUE 12): allreduce | reduce_scatter,
        # with the eligibility ladder + attribution recorded in
        # self._hist_reduce (returns "allreduce" wherever the
        # reduce-scatter window contract is not yet eligible)
        hist_reduce = self._resolve_hist_reduce_mode(tl, forced)
        if self.grower_cfg.interaction_groups and tl == "feature":
            log.fatal("interaction_constraints are not supported with "
                      "tree_learner=feature")

        if self._multival and tl in ("data", "voting"):
            # multi-value sparse storage under the row-sharded learners:
            # the [R, K] nonzero packing row-shards exactly like dense
            # rows (pad rows carry idx = -1, contributing nothing); the
            # column accessor and leaf gathers are shard-local. Data-
            # parallel reconstructs default bins on the psum'd GLOBAL
            # histograms in the split scan; voting fixes LOCAL hists
            # from the grower's local-sums channel BEFORE the vote (the
            # fix is linear, so the psum of fixed locals is exact).
            from ..ops.hist_multival import SparseBins
            mesh = build_mesh(n_dev, axis_names=(DATA_AXIS,))
            R_pad = padded_rows(N, n_dev)
            self._row_pad = R_pad - N
            idx_h, binv_h = train.bins_mv
            if self._row_pad:
                idx_h = np.pad(idx_h, ((0, self._row_pad), (0, 0)),
                               constant_values=-1)
                binv_h = np.pad(binv_h, ((0, self._row_pad), (0, 0)))
            sh = NamedSharding(mesh, P(DATA_AXIS, None))
            self.bins_sharded = SparseBins(
                jax.device_put(np.ascontiguousarray(idx_h), sh),
                jax.device_put(np.ascontiguousarray(binv_h), sh),
                train.num_used_features)
            fetch, prepare = self._multival_hooks(train)
            mv_spec = SparseBins(P(DATA_AXIS, None), P(DATA_AXIS, None),
                                 train.num_used_features)
            if tl == "data":
                grow = make_data_parallel_grower(
                    self.grower_cfg, self.feature_meta, mesh,
                    fetch_bin_column=fetch, prepare_split_hist=prepare,
                    prepare_is_pure=True, bins_spec=mv_spec)
            else:
                from ..ops.hist_multival import make_local_default_bin_fix
                dflt = np.asarray(
                    [m.default_bin for m in train.used_bin_mappers()],
                    np.int32)
                grow = make_voting_parallel_grower(
                    self.grower_cfg, self.feature_meta, mesh,
                    top_k=int(cfg.top_k), fetch_bin_column=fetch,
                    bins_spec=mv_spec,
                    pre_fix=make_local_default_bin_fix(
                        dflt, self.num_bin_max))
            self._grow_dist = jax.jit(grow)
        elif tl in ("data", "voting"):
            if bins_host is None:
                bins_host = train.bins
            mesh = build_mesh(n_dev, axis_names=(DATA_AXIS,))
            if self._sharded_ingest:
                # row-sharded ingestion (ISSUE 7): each process holds
                # only its shard's bin columns. The global device array
                # is assembled from the process-local blocks — no host
                # ever materializes [F, N]. Padded layout: one
                # ``region`` of rows per process (its shard + tail pad),
                # so every process's block covers exactly its own
                # devices' slots; pad slots carry gh = 0 and are
                # invisible to training (exact zeros under quantized
                # int32 histograms — the bit-identity contract).
                shard = train.shard
                world = shard.world
                if n_dev % world:
                    log.fatal(
                        f"sharded ingestion: {n_dev} devices do not "
                        f"divide evenly over {world} processes (set "
                        "tpu_num_devices=0 to use every device)")
                d_local = n_dev // world
                # the region layout below places process p's rows on
                # mesh slots [p*d_local, (p+1)*d_local) — a truncated
                # mesh (tpu_num_devices < all devices) can pass the
                # divisibility check yet exclude some process's devices
                # entirely, which would crash (or worse, misplace rows)
                # inside make_array_from_process_local_data
                mesh_devs = list(mesh.devices.flat)
                for p in range(world):
                    block = mesh_devs[p * d_local:(p + 1) * d_local]
                    if any(d.process_index != p for d in block):
                        log.fatal(
                            "sharded ingestion: the device mesh does "
                            f"not hold {d_local} devices per process "
                            "in process order (process "
                            f"{p} owns {[d.process_index for d in block]}"
                            ") — set tpu_num_devices=0 so every "
                            "process contributes all its devices")
                region = padded_rows(int(shard.row_counts.max()),
                                     d_local)
                R_pad = region * world
                self._row_pad = 0
                row_counts = np.asarray(shard.row_counts, np.int64)
                offsets = np.concatenate([[0], np.cumsum(row_counts)])
                row_map = np.full(R_pad, -1, np.int64)
                for p in range(world):
                    c = int(row_counts[p])
                    row_map[p * region:p * region + c] = \
                        offsets[p] + np.arange(c)
                inv_map = np.zeros(N, np.int64)
                inv_map[row_map[row_map >= 0]] = \
                    np.flatnonzero(row_map >= 0)
                self._shard_row_map = jnp.asarray(row_map, jnp.int32)
                self._shard_inv_map = inv_map
                local = bins_host              # [F_used, local_rows]
                pad_c = region - local.shape[1]
                if pad_c:
                    local = np.pad(local, ((0, 0), (0, pad_c)))
                if self._compact:
                    self.bins_sharded = \
                        jax.make_array_from_process_local_data(
                            NamedSharding(mesh, P(DATA_AXIS, None)),
                            np.ascontiguousarray(local.T),
                            (R_pad, local.shape[0]))
                else:
                    self.bins_sharded = \
                        jax.make_array_from_process_local_data(
                            NamedSharding(mesh, P(None, DATA_AXIS)),
                            np.ascontiguousarray(local),
                            (local.shape[0], R_pad))
            else:
                R_pad = padded_rows(N, n_dev)
                self._row_pad = R_pad - N
                bins = bins_host  # EFB-packed groups when bundling engaged
                if self._row_pad:
                    bins = np.pad(bins, ((0, 0), (0, self._row_pad)))
                if self._compact:
                    # row-major layout for the gathered O(rows_in_leaf)
                    # passes
                    self.bins_sharded = jax.device_put(
                        np.ascontiguousarray(bins.T),
                        NamedSharding(mesh, P(DATA_AXIS, None)))
                else:
                    self.bins_sharded = jax.device_put(
                        bins, NamedSharding(mesh, P(None, DATA_AXIS)))
            if tl == "data":
                grow = make_data_parallel_grower(
                    self.grower_cfg, self.feature_meta, mesh, forced=forced,
                    bundle=self._bundle, hist_reduce=hist_reduce)
            else:
                grow = make_voting_parallel_grower(
                    self.grower_cfg, self.feature_meta, mesh,
                    top_k=int(cfg.top_k), bundle=self._bundle,
                    hist_reduce=hist_reduce)
            if self._shard_row_map is not None:
                # scatter the replicated [N, 3] gh into the per-region
                # padded layout INSIDE the jitted program (pad slots get
                # exact zeros); the base grower's entry shapes are
                # untouched
                rm = self._shard_row_map
                base_grow = grow

                def grow(bins_arr, gh, fmask, cegb, rng_key,
                         _base=base_grow, _rm=rm):
                    gh_p = jnp.where((_rm >= 0)[:, None],
                                     gh[jnp.clip(_rm, 0), :],
                                     jnp.zeros((), gh.dtype))
                    return _base(bins_arr, gh_p, fmask, cegb, rng_key)
            self._grow_dist = jax.jit(grow)
        else:  # feature-parallel
            if bins_host is None:
                bins_host = train.bins
            mesh = build_mesh(n_dev, axis_names=(FEATURE_AXIS,))
            if self._bundle is not None:
                # EFB: the sharded storage axis is PHYSICAL GROUPS —
                # pad the packed bins to a group count divisible by the
                # mesh (masks/cegb stay global-logical; the grower
                # permutes them into the shard layout)
                self._feat_pad = 0
                from ..parallel.feature_parallel import padded_groups
                G = int(self._bundle["num_groups"])
                bins = np.pad(bins_host,
                              ((0, padded_groups(G, n_dev) - G),
                               (0, 0)))
            else:
                Fp = padded_features(F, n_dev)
                self._feat_pad = Fp - F
                bins = bins_host
                if self._feat_pad:
                    bins = np.pad(bins, ((0, self._feat_pad), (0, 0)))
            if self._compact:
                self.bins_sharded = jax.device_put(
                    np.ascontiguousarray(bins.T),
                    NamedSharding(mesh, P(None, FEATURE_AXIS)))
            else:
                self.bins_sharded = jax.device_put(
                    bins, NamedSharding(mesh, P(FEATURE_AXIS, None)))
            if self._bundle is not None:
                grow = make_feature_parallel_grower(
                    self.grower_cfg, self.feature_meta, mesh,
                    bundle=self._bundle)
            else:
                meta_p = pad_feature_meta(self.feature_meta, Fp)
                grow = make_feature_parallel_grower(self.grower_cfg,
                                                    meta_p, mesh)
            self._grow_dist = jax.jit(grow)
        self._mesh = mesh

        def grow_wrapper(bins_unused, gh, fmask, cegb, rng_key=None):
            if self._row_pad:
                gh = jnp.pad(gh, ((0, self._row_pad), (0, 0)))
            if self._feat_pad and fmask is not None:
                pad_w = [(0, self._feat_pad)]
                if fmask.ndim == 2:
                    pad_w = [(0, 0)] + pad_w
                fmask = jnp.pad(fmask, pad_w)
            if self._feat_pad and cegb is not None:
                cegb = (jnp.pad(cegb[0], (0, self._feat_pad)),
                        jnp.pad(cegb[1], (0, self._feat_pad)))
            tree, leaf_id = self._grow_dist(self.bins_sharded, gh, fmask,
                                            cegb, rng_key)
            if self._shard_inv_map is not None:
                # sharded ingestion: gather the [R_pad] padded layout and
                # un-permute to the concatenated-table row order (pads
                # interleave per process region, so this is an index map,
                # not a suffix slice)
                from jax.experimental import multihost_utils
                leaf_all = np.asarray(multihost_utils.process_allgather(
                    leaf_id, tiled=True)).reshape(-1)
                return tree, jnp.asarray(leaf_all[self._shard_inv_map])
            if self._row_pad:
                leaf_id = leaf_id[:N]
            if jax.process_count() > 1:
                # multi-host: leaf_id is row-sharded across processes and
                # a direct host fetch (np.asarray in train_one_iter) can
                # only see addressable shards — gather it once per tree.
                # Score updates and leaf bookkeeping then run on the
                # replicated copy, matching the reference where every
                # machine holds its full local partition
                # (data_parallel_tree_learner GlobalSync semantics).
                from jax.experimental import multihost_utils
                leaf_id = jnp.asarray(
                    multihost_utils.process_allgather(leaf_id, tiled=True))
            return tree, leaf_id

        self._grow = grow_wrapper

    # ------------------------------------------------------------------
    def add_valid_data(self, valid: BinnedDataset,
                       metrics: Optional[List[Metric]] = None,
                       name: Optional[str] = None) -> None:
        if getattr(valid, "shard", None) is not None:
            log.fatal(
                "validation sets must be replicated: construct them "
                "with reference=<train Dataset> (sharded ingestion "
                "applies to the training table only)")
        if metrics is None:
            metrics = metrics_for_config(
                self.config,
                self.objective.NAME if self.objective else "custom")
        for m in metrics:
            m.init(valid.metadata, valid.num_data)
        if getattr(self, "_linear", False) and valid.raw is None:
            log.fatal("linear_tree validation data was constructed without "
                      "raw features; pass the same params (incl. "
                      "linear_tree) to the valid Dataset")
        vd = _ValidData(valid, metrics, self.num_tree_per_iteration,
                        name or f"valid_{len(self.valid_sets) + 1}")
        # replay existing model onto the new valid set (continued training)
        for it in range(len(self.models) // self.num_tree_per_iteration):
            for k in range(self.num_tree_per_iteration):
                t = self.models[it * self.num_tree_per_iteration + k]
                vd.score = vd.score.at[k].add(self._tree_outputs(
                    t, vd.bins_dev, vd.dataset.raw))
        self.valid_sets.append(vd)

    def add_train_metrics(self, metrics: List[Metric]) -> None:
        for m in metrics:
            m.init(self.train_set.metadata, self.num_data)
        self.train_metrics = metrics

    # ------------------------------------------------------------------
    def _load_forced_splits(self, train: BinnedDataset):
        """Parse forcedsplits_filename JSON into the grower's static forced
        arrays (ref: gbdt.cpp:91-97 forced_splits_json_, serial_tree_learner
        ForceSplits). Leaf slots are simulated exactly like the grower
        assigns them: splitting slot s at step i keeps the left child in s
        and puts the right child in slot i+1."""
        cfg = self.config
        if not cfg.forcedsplits_filename:
            return None
        import json
        with open(cfg.forcedsplits_filename) as f:
            root = json.load(f)
        if not root or "feature" not in root:
            return None
        orig2used = _orig_to_used(train.used_feature_map)
        L = cfg.num_leaves
        active = np.zeros(L - 1, bool)
        slot = np.zeros(L - 1, np.int32)
        feat = np.zeros(L - 1, np.int32)
        thr = np.zeros(L - 1, np.int32)
        from collections import deque
        q = deque([(root, 0)])
        step = 0
        while q and step < L - 1:
            node, s = q.popleft()
            f_orig = int(node["feature"])
            if f_orig not in orig2used:
                log.warning(f"forced split on unused feature {f_orig}; "
                            "stopping forced prefix here")
                break
            mapper = train.bin_mappers[f_orig]
            if mapper.bin_type == "categorical":
                log.warning(f"forced split on categorical feature {f_orig} "
                            "is not supported; stopping forced prefix here")
                break
            # real threshold -> bin: the left side is value <= threshold,
            # i.e. bin(threshold) (ref: Dataset::BinThreshold)
            tb = int(mapper.value_to_bin(
                np.asarray([float(node["threshold"])]))[0])
            active[step] = True
            slot[step] = s
            feat[step] = orig2used[f_orig]
            thr[step] = tb
            left_slot, right_slot = s, step + 1
            for key, child_slot in (("left", left_slot),
                                    ("right", right_slot)):
                child = node.get(key)
                if isinstance(child, dict) and "feature" in child and \
                        "threshold" in child:
                    q.append((child, child_slot))
            step += 1
        if not active.any():
            return None
        return (active, slot, feat, thr)

    # ------------------------------------------------------------------
    def _setup_cegb(self, train: BinnedDataset) -> None:
        """Cost-efficient gradient boosting state (ref: cost_effective_
        gradient_boosting.hpp). Penalties are applied per feature as
        penalty[f] = const[f] + per_count[f] * num_data_in_leaf:

        - cegb_penalty_split enters per_count exactly;
        - cegb_penalty_feature_coupled enters const for features not yet
          used anywhere in the forest (used-set updated between trees —
          the reference's within-tree re-ranking of cached candidates,
          UpdateLeafBestSplits, is approximated at tree granularity);
        - cegb_penalty_feature_lazy enters per_count scaled by the fraction
          of rows not yet charged for the feature (the reference charges
          per uncharged row in the leaf; here the global uncharged fraction
          stands in for the per-leaf one, again tree-granular).
        """
        cfg = self.config
        F = train.num_used_features
        coupled = cfg.cegb_penalty_feature_coupled
        lazy = cfg.cegb_penalty_feature_lazy
        self._cegb_enabled = bool(
            cfg.cegb_penalty_split > 0.0 or coupled or lazy)
        if not self._cegb_enabled:
            return
        for name, pen in (("coupled", coupled), ("lazy", lazy)):
            if pen and len(pen) != train.num_total_features:
                log.fatal(f"cegb_penalty_feature_{name} should be the same "
                          "size as feature number")
        ufm = train.used_feature_map
        self._cegb_coupled = (np.asarray(coupled, np.float64)[ufm]
                              if coupled else np.zeros(F))
        self._cegb_lazy = (np.asarray(lazy, np.float64)[ufm]
                           if lazy else np.zeros(F))
        self._cegb_feature_used = np.zeros(F, bool)
        self._cegb_row_charged = (np.zeros((F, self.num_data), bool)
                                  if lazy else None)

    def _hist_budget(self, n_feat_fallback: int = 0):
        """(bytes per [Fp, B, 3] histogram row, budget limit in bytes)
        — the ONE place the histogram memory rule lives, shared by the
        compact pool policy and the hybrid eligibility gate so the two
        can never budget with different constants."""
        cfg = self.config
        if self._bundle is not None:
            n_phys = self._bundle["num_groups"]
        elif self.feature_meta is not None:
            n_phys = int(self.feature_meta.num_bin.shape[0])
        else:
            n_phys = n_feat_fallback
        row_bytes = n_phys * self.num_bin_max * 3 * 4
        limit_bytes = (cfg.histogram_pool_size * (1 << 20)
                       if cfg.histogram_pool_size >= 0 else 4 << 30)
        return row_bytes, limit_bytes

    def _level_ineligibility(self, forced) -> list:
        """Reasons level scheduling cannot serve this config (pure
        level grower for max_depth in [1, MAX_LEVEL_DEPTH], the hybrid
        level+tail grower otherwise — core/level_grower.py and
        core/hybrid_grower.py docstrings); empty list = eligible.

        Round-7 admissions: any max_depth (incl. the default -1, via
        the hybrid), categorical features, EFB bundles and quantized
        gradients are now served — they were histogram-layout
        questions, not ordering questions. The remaining reasons are
        order-dependent features (the sequential loop's step-by-step
        state feeds back into later split decisions in ways a batched
        level scan cannot reproduce) or other-learner layouts."""
        from ..core.level_grower import MAX_LEVEL_DEPTH
        from ..distributed import make_injected_hooks
        cfg = self.config
        reasons = []
        if self._tree_learner != "serial":
            reasons.append(f"tree_learner={self._tree_learner!r}")
        if self._multival:
            reasons.append("multi-value sparse storage")
        if make_injected_hooks() is not None:
            reasons.append("injected collectives")
        if self.grower_cfg.hparams.monotone_penalty > 0 or \
                self.feature_meta.monotone is not None:
            reasons.append("monotone constraints")
        if self.grower_cfg.interaction_groups is not None:
            reasons.append("interaction constraints")
        if (cfg.cegb_penalty_split > 0.0 or
                cfg.cegb_penalty_feature_coupled or
                cfg.cegb_penalty_feature_lazy):
            # from config (the check runs before _setup_cegb)
            reasons.append("CEGB penalties")
        if forced is not None:
            reasons.append("forced splits")
        if self.grower_cfg.extra_trees:
            reasons.append("extra_trees")
        if self.grower_cfg.bynode_mask:
            reasons.append("feature_fraction_bynode")
        if cfg.linear_tree:
            reasons.append("linear trees")
        if not (1 <= self.grower_cfg.max_depth <= MAX_LEVEL_DEPTH):
            # hybrid path: the sequential tail runs with the FULL
            # [L, Fp, B, 3] histogram pool (its rows are seeded from
            # the level hists), AND the level phase keeps ALL level
            # hists [T, Fp, B, 3] with T = 2^(D0+1)-1 (~4L at the auto
            # depth) alive through the ranking for that seeding.
            # Budget BOTH against the histogram_pool_size limit —
            # configs that exceed it would previously train compact
            # with a bounded/none pool, which the handoff cannot seed
            # (review r7: gating on the pool alone admitted wide
            # configs whose phase hists alone exceed device HBM)
            from ..core.hybrid_grower import resolve_handoff_depth
            d0 = resolve_handoff_depth(cfg.num_leaves,
                                       cfg.tpu_level_handoff_depth)
            t_nodes = 2 ** (d0 + 1) - 1
            row_bytes, limit_bytes = self._hist_budget()
            need_bytes = (cfg.num_leaves + t_nodes) * row_bytes
            if need_bytes > limit_bytes:
                reasons.append(
                    f"histogram memory over budget ({need_bytes >> 20}"
                    " MB for the hybrid's full pool + level-phase "
                    "hists)")
        return reasons

    def _cegb_penalty(self):
        """(const [F], per_count [F]) for the current tree, or None."""
        if not getattr(self, "_cegb_enabled", False):
            return None
        cfg = self.config
        tradeoff = cfg.cegb_tradeoff
        const = tradeoff * self._cegb_coupled * (~self._cegb_feature_used)
        per_count = np.full(self.num_used_features,
                            tradeoff * cfg.cegb_penalty_split)
        if self._cegb_row_charged is not None:
            frac_uncharged = 1.0 - self._cegb_row_charged.mean(axis=1)
            per_count = per_count + tradeoff * self._cegb_lazy * frac_uncharged
        return (jnp.asarray(const, jnp.float32),
                jnp.asarray(per_count, jnp.float32))

    def _cegb_after_tree(self, host: "HostTree", leaf_np: np.ndarray,
                         selected: Optional[np.ndarray] = None) -> None:
        """Update the forest-level used-feature set and per-row charges.
        ``selected`` is the bagging mask — only in-bag rows actually had
        their features fetched, so only they get charged (ref: cost_
        effective_gradient_boosting.hpp UpdateLeafBestSplits uses
        data_partition indices, which contain bagged rows only)."""
        if not getattr(self, "_cegb_enabled", False):
            return
        n_int = host.num_leaves - 1
        for i in range(n_int):
            self._cegb_feature_used[int(host.split_feature_inner[i])] = True
        if self._cegb_row_charged is not None and n_int > 0:
            # rows in each leaf are charged for the features on its path
            path_feats = {}  # leaf -> set of inner features

            def walk(node, feats):
                if node < 0:
                    path_feats[~node] = feats
                    return
                f = int(host.split_feature_inner[node])
                walk(int(host.left_child[node]), feats | {f})
                walk(int(host.right_child[node]), feats | {f})
            walk(0, frozenset())
            in_bag = selected > 0 if selected is not None else None
            for leaf, feats in path_feats.items():
                if not feats:
                    continue
                rows = leaf_np == leaf
                if in_bag is not None:
                    rows = rows & in_bag
                for f in feats:
                    self._cegb_row_charged[f, rows] = True

    # ------------------------------------------------------------------
    def _feature_mask(self) -> Optional[jnp.ndarray]:
        """Column sampling (ref: col_sampler.hpp): feature_fraction samples
        once per tree; feature_fraction_bynode additionally samples per node
        (one mask row per grower step)."""
        frac = self.config.feature_fraction
        F = self.num_used_features
        tree_mask = np.ones(F, bool)
        if frac < 1.0 and F > 1:
            n_take = max(1, min(F, int(round(F * frac))))
            tree_mask = np.zeros(F, bool)
            tree_mask[self._col_rng.choice(F, size=n_take,
                                           replace=False)] = True
        if not self._bynode:
            if frac >= 1.0 or F <= 1:
                return None
            return jnp.asarray(tree_mask)
        # per-node masks: sample within the tree-level subset per node.
        # Row layout matches the grower: root=0, step i children 2i+1/2i+2.
        L = self.config.num_leaves
        frac_node = self.config.feature_fraction_bynode
        base_idx = np.flatnonzero(tree_mask)
        n_node = max(1, int(round(len(base_idx) * frac_node)))
        masks = np.zeros((2 * L, F), bool)
        for i in range(2 * L):
            take = self._col_rng.choice(base_idx, size=n_node, replace=False)
            masks[i, take] = True
        return jnp.asarray(masks)

    def _obtain_init_score(self, k: int) -> float:
        """ref: gbdt.cpp:317 ObtainAutomaticInitialScore + network mean."""
        init = self.objective.boost_from_score(k) if self.objective else 0.0
        inj = getattr(self, "_inj", None)
        if inj is not None and inj["num_machines"] > 1:
            # ≡ Network::GlobalSyncUpByMean over machines (gbdt.cpp:322)
            import numpy as _np

            from ..distributed import retried_collective
            tot = retried_collective(
                inj["reduce_sum"], _np.asarray([init], _np.float64),
                what="init-score sync")
            init = float(tot[0]) / inj["num_machines"]
        return float(init)

    def _leaf_delta(self, lv, nl, leaf, rate):
        """Per-row score delta ``f32(lv[leaf]) * f32(rate)`` (masked for
        degenerate trees), rounded in its OWN dispatch.

        The product must NOT live in the same program as the score
        accumulate: XLA fuses ``lv[leaf] * rate + score`` into an FMA
        (observed on this image's CPU backend), making the live score
        differ by one ulp from what a model replay (init_model /
        checkpoint resume, which adds the STORED f32 product back)
        produces — and one ulp eventually flips near-tie splits. Two
        dispatches pin the accumulated value to exactly the product
        HostTree.shrink stores in the model, so async runs, sync runs
        and replays stay bit-identical."""
        if self._async_delta_fn is None:
            self._async_delta_fn = jax.jit(
                lambda lv, nl, leaf, rate: jnp.where(
                    nl > 1, lv[leaf] * rate, jnp.float32(0.0)))
        return self._async_delta_fn(lv, nl, leaf, rate)

    def _score_add(self, score, delta, k: int):
        """score[k] += delta, donating the old score buffer when
        tpu_donate_state is on (the [K, N] score array is the largest
        training-state buffer; donation lets XLA update it in place
        instead of holding both generations in HBM)."""
        if self._score_add_fn is None:
            if self.config.tpu_donate_state:
                self._score_add_fn = jax.jit(
                    lambda s, d, kk: s.at[kk].add(d),
                    donate_argnums=(0,))
            else:
                self._score_add_fn = jax.jit(lambda s, d, kk: s.at[kk].add(d))
        return self._score_add_fn(score, delta, k)

    def _boost_from_average(self, k: int) -> float:
        """ref: gbdt.cpp:328 BoostFromAverage."""
        if (self._n_models_total() == 0 and not self.has_init_score and
                self.objective is not None and
                (self.config.boost_from_average or
                 self.num_used_features == 0)):
            init_score = self._obtain_init_score(k)
            if abs(init_score) > K_EPSILON:
                self.score = self.score.at[k].add(init_score)
                for vd in self.valid_sets:
                    vd.score = vd.score.at[k].add(init_score)
                log.info(f"Start training from score {init_score:.6f}")
                return init_score
        return 0.0

    def _tree_outputs(self, t: HostTree, bins_dev,
                      raw: Optional[np.ndarray] = None) -> jnp.ndarray:
        """Per-row output of a host tree over binned data. Linear trees
        route over bins but add raw-feature linear terms (ref: tree.cpp
        PredictionFunLinear operates on binned decisions + raw pointers)."""
        arrs = _host_tree_to_arrays(t, self.config.num_leaves)
        leaf = tree_leaf_bins(arrs, bins_dev, self.feature_meta.num_bin,
                              self.feature_meta.missing_type,
                              self.feature_meta.default_bin)
        if t.is_linear and raw is not None:
            return jnp.asarray(
                t.linear_output(raw, np.asarray(leaf)).astype(np.float32))
        return arrs.leaf_value[leaf]

    # ------------------------------------------------------------------
    def predict_device(self, X: np.ndarray, start_iteration: int,
                       end_iteration: int) -> np.ndarray:
        """Batched TPU prediction through the packed-forest serving engine
        (ops/forest.py; ≡ the CUDA predictor's batched
        AddPredictionToScore, cuda_tree.cu — the reference CPU predictor
        walks rows under OMP).

        With in-session training mappers the request is binned ON DEVICE
        (vmapped searchsorted over the uploaded BinMapper bounds) and
        traversal runs on integer bin thresholds — split decisions are
        exact by construction: threshold_real is the left bin's upper
        bound, so `x <= threshold_real` and `bin(x) <= threshold_bin`
        decide identically. Without mappers (model loaded from file) the
        raw-threshold route serves instead (per-node missing handling
        from decision_type); categorical raw bitsets stay on the host
        path. Only the leaf-value accumulation differs from the host walk
        (f32 on device vs f64). The packed forest grows incrementally
        with training and is keyed on the model generation; batch sizes
        are bucketed into a small family of compiled shapes
        (tpu_predict_buckets).
        """
        K = self.num_tree_per_iteration
        models = self.models          # property: flushes pending trees
        lo, hi = start_iteration * K, end_iteration * K
        window = models[lo:hi]
        if not window:
            raise ValueError("device prediction needs a non-empty tree "
                             "range")
        if any(t.is_linear for t in window):
            raise ValueError("device prediction does not cover linear "
                             "trees")
        bucket = bool(self.config.tpu_predict_buckets)
        srv = self._serving
        if srv is None or srv.bucket != bucket:
            srv = self._serving = ServingEngine(
                self.config.num_leaves, K, bucket=bucket)
        if self.train_set is not None and self.train_set.bin_mappers:
            if self._serving_mappers is None:
                # fresh list per used_bin_mappers() call — pin one so the
                # binner/pack identity caches hold across requests
                self._serving_mappers = self.train_set.used_bin_mappers()
            out = srv.predict_binned(
                models, self._model_gen, X, lo, hi,
                self._serving_mappers, self.train_set.used_feature_map)
        else:
            out = srv.predict_raw(models, self._model_gen, X, lo, hi)
        return out.T  # [R, K]

    def explain_device(self, X: np.ndarray, start_iteration: int,
                       end_iteration: int) -> np.ndarray:
        """[R, (F+1)*K] f64 SHAP contributions through the packed path
        tensors (ops/shap_pack.py, ISSUE 20) — the device counterpart
        of ``core.shap.predict_contrib`` with the same output layout
        (per-class blocks of F+1, bias last). Route selection mirrors
        ``predict_device`` (binned with in-session mappers, raw
        thresholds for loaded models); linear trees and categorical
        splits raise ValueError for the Booster's loud-once host
        fallback. The SHAP pack rides the SAME ServingEngine as
        predictions, so it grows incrementally with training and
        generations stay shared."""
        K = self.num_tree_per_iteration
        models = self.models          # property: flushes pending trees
        lo, hi = start_iteration * K, end_iteration * K
        if not models[lo:hi]:
            raise ValueError("device explanation needs a non-empty "
                             "tree range")
        n_features = self.max_feature_idx + 1
        bucket = bool(self.config.tpu_predict_buckets)
        srv = self._serving
        if srv is None or srv.bucket != bucket:
            srv = self._serving = ServingEngine(
                self.config.num_leaves, K, bucket=bucket)
        if self.train_set is not None and self.train_set.bin_mappers:
            if self._serving_mappers is None:
                self._serving_mappers = self.train_set.used_bin_mappers()
            return srv.explain_binned(
                models, self._model_gen, X, lo, hi,
                self._serving_mappers, self.train_set.used_feature_map,
                n_features)
        return srv.explain_raw(models, self._model_gen, X, lo, hi,
                               n_features)

    def serving_state(self):
        """Frozen ``(models, generation, mappers, used_feature_map)``
        for an external model server (serving/server.py ISSUE 8). The
        list COPY decouples the server's snapshot from trees the
        training loop appends afterwards (the next ``publish`` picks
        them up incrementally); the pinned mapper list keeps the
        server's binner/pack identity caches valid across publishes."""
        models = list(self.models)        # property: flushes pending
        if self.train_set is not None and self.train_set.bin_mappers:
            if self._serving_mappers is None:
                self._serving_mappers = self.train_set.used_bin_mappers()
            return (models, self._model_gen, self._serving_mappers,
                    self.train_set.used_feature_map)
        return models, self._model_gen, None, None

    # ------------------------------------------------------------------
    def _hb_iter_begin(self):
        """Beat the process heartbeat and arm the stall watchdog for one
        iteration (ISSUE 4). Phase is ``compiling`` until the first
        iteration completed (the grower's multi-minute XLA compile
        happens inside it), ``iter`` + iteration counter afterwards —
        the supervisor's generous compile budget applies exactly where
        compiles can occur, and advancing iterations are never parked.
        Returns the armed watchdog (None when unsupervised)."""
        hb = heartbeat.current()
        if hb is None:
            return None
        wd = heartbeat.training_watchdog(self._hb_policy)
        wd.check()                  # a stall armed while we were away
        wd.begin()
        hb.beat(heartbeat.PHASE_ITER if self._hb_warm
                else heartbeat.PHASE_COMPILING, self.iter)
        return wd

    def _hb_sync_beat(self) -> None:
        """Refresh liveness right before a blocking device fetch — the
        exact points a wedged tunnel freezes the loop, so beat age
        measured by watchdog/supervisor starts at the sync, not at the
        iteration that dispatched it."""
        hb = heartbeat.current()
        if hb is not None:
            hb.beat(heartbeat.PHASE_ITER if self._hb_warm
                    else heartbeat.PHASE_COMPILING, self.iter)

    def _numeric_guard(self) -> Optional[integrity.NumericHealthGuard]:
        """The per-iteration numeric-health watchdog (ISSUE 19), built
        lazily when ``tpu_integrity_numeric_guard`` is armed (off by
        default; the resident trainer arms it). Catches NaN/Inf
        grad/hess sums, non-finite committed leaf outputs and
        loss-proxy spikes BEFORE a poisoned tree reaches the model —
        raising the DATA_CORRUPTION-classified NumericHealthError the
        continual trainer answers with a checkpoint rollback."""
        if not bool(getattr(self.config, "tpu_integrity_numeric_guard",
                            False)):
            return None
        g = getattr(self, "_nguard", None)
        if g is None:
            g = integrity.NumericHealthGuard(
                spike_factor=float(getattr(
                    self.config, "tpu_integrity_loss_spike_factor",
                    100.0)),
                what="training")
            self._nguard = g
        return g

    def _guard_sums(self, grad, hess) -> Tuple[float, float, float]:
        """(sum g, sum h, mean |g|) in ONE fused jitted reduction —
        the guard's whole per-iteration device cost. mean |g| is the
        loss PROXY the spike check watches: it tracks the training
        loss's gradient magnitude without a per-iteration [K, N]
        device->host score pull."""
        fn = getattr(self, "_guard_sums_fn", None)
        if fn is None:
            fn = jax.jit(lambda g, h: (jnp.sum(g), jnp.sum(h),
                                       jnp.mean(jnp.abs(g))))
            self._guard_sums_fn = fn
        gs, hs, ga = fn(grad, hess)
        return float(gs), float(hs), float(ga)

    def _gang_digest_check(self) -> None:
        """Gang agreement check (ISSUE 19): every
        ``tpu_integrity_digest_every`` iterations, all ranks allreduce
        a cheap CRC digest of the freshly committed iteration's trees
        and verify agreement through the sum-based reduction identity
        (``integrity.check_digest_reduction`` — injected transports
        only guarantee ``reduce_sum``). Divergence raises the
        classified ``GangDivergence``: the worker exits nonzero and the
        gang supervisor (robustness/gang.py) relaunches the whole gang
        from the newest manifest. No-op unless this booster trains
        under injected collectives with world > 1."""
        every = int(getattr(self.config, "tpu_integrity_digest_every",
                            0) or 0)
        inj = getattr(self, "_inj", None)
        if every <= 0 or inj is None or int(inj["num_machines"]) <= 1:
            return
        if self.iter % every != 0:
            return
        from ..distributed import retried_collective
        K = self.num_tree_per_iteration
        models = self.models          # flushes pending device trees
        digest = integrity.iteration_digest(models[-K:])
        if faults.check("bitflip", where="digest"):
            # gang-divergence drill: THIS rank's digest lies — the
            # agreement check must refuse the iteration on every rank
            log.warning("fault injection: bit-flipped this rank's tree "
                        "digest before the gang agreement sync")
            digest ^= 0x1
        total = np.asarray(retried_collective(
            inj["reduce_sum"], integrity.digest_reduction(digest),
            what="integrity tree-digest sync"))
        integrity.check_digest_reduction(
            total, int(inj["num_machines"]), digest, self.iter,
            rank=int(inj["rank"]), what="gang")

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (ref: gbdt.cpp:353 TrainOneIter).
        Returns True when training should stop (no more valid splits).

        Liveness shell around the sync/async bodies: beats + the stall
        watchdog (armed only while the iteration is in flight) convert
        a forever-hang at a device sync into DeviceStallError."""
        # injected rank death (ISSUE 10 chaos site): an armed rank_kill
        # hard-exits THIS rank at the iteration boundary — the gang
        # supervisor must SIGTERM the survivors and relaunch from the
        # newest manifest (no-op without an active plan)
        faults.maybe_kill_rank(getattr(self, "_process_rank", 0))
        wd = self._hb_iter_begin()
        try:
            if gradients is None and hessians is None and \
                    self._async_on():
                done = self._train_one_iter_async()
            else:
                done = self._train_one_iter_sync(gradients, hessians)
            self._hb_warm = True
            if not done:
                self._gang_digest_check()
            return done
        except KeyboardInterrupt:
            # the watchdog unblocks a wedged iteration via
            # interrupt_main — surface that as the classified
            # DeviceStallError the contract promises, not as a fake
            # Ctrl-C. With no stall armed this is a real Ctrl-C and
            # re-raises untouched; with one armed, check() raises the
            # DeviceStallError carrying the armed detail.
            if wd is not None:
                wd.check()
            raise
        finally:
            if wd is not None:
                wd.end()

    def _train_one_iter_sync(self,
                             gradients: Optional[np.ndarray] = None,
                             hessians: Optional[np.ndarray] = None
                             ) -> bool:
        """Synchronous TrainOneIter body (see train_one_iter)."""
        self._flush_pending()
        K = self.num_tree_per_iteration
        init_scores = [0.0] * K

        if gradients is None or hessians is None:
            for k in range(K):
                init_scores[k] = self._boost_from_average(k)
            with global_timer.section("GBDT::Boosting",
                                      sync=lambda: grad):
                if self._pos_bias:
                    grad, hess = self._gh_fn(
                        self.score,
                        jnp.asarray(self.objective.pos_biases, jnp.float32))
                    self.objective.update_position_bias(
                        np.asarray(grad, np.float64),
                        np.asarray(hess, np.float64))
                else:
                    grad, hess = self._gh_fn(self.score)
            if K == 1:
                grad = grad[None, :]
                hess = hess[None, :]
        else:
            grad = jnp.asarray(
                np.asarray(gradients, np.float32).reshape(K, self.num_data))
            hess = jnp.asarray(
                np.asarray(hessians, np.float32).reshape(K, self.num_data))

        # -- integrity defense (ISSUE 19) -------------------------------
        # the nan_grad fault site poisons the gradient stream (silent
        # data corruption: with no guard armed, the NaN walks into a
        # committed tree's leaf outputs); the numeric-health guard —
        # armed via tpu_integrity_numeric_guard — catches it HERE,
        # before a tree is grown from the poisoned stream
        if faults.check("nan_grad"):
            log.warning("fault injection: poisoning this iteration's "
                        "gradient stream with NaN (silent data "
                        "corruption)")
            grad = jnp.asarray(grad).at[0, 0].set(jnp.nan)
        guard = self._numeric_guard()
        if guard is not None:
            gsum, hsum, gabs = self._guard_sums(grad, hess)
            guard.check_gradients(gsum, hsum, self.iter)
            guard.observe_loss(gabs, self.iter, what="loss proxy")

        # -- bagging / GOSS (host decision, device apply) ---------------
        # only GOSS reads gradients; skip the [K, N] device->host pull
        # for RNG-only strategies (it costs a full tunnel round-trip).
        # Opt-in device bagging is consulted HERE too so a stop-check
        # rollback replay re-derives the exact same stateless-key mask
        # the async path used (sample_strategy.sample_dev docstring)
        if self.sample_strategy.needs_grad:
            pair = None
            if getattr(self, "_goss_dev_used", False):
                # this run's GOSS samples come from the async path's
                # stateless key chain — a stop-check rollback replay
                # re-derives the EXACT draw the async path used, so
                # stopped-and-replayed runs stay bit-identical to
                # uninterrupted async runs
                key = jax.random.fold_in(self._goss_key, self.iter)
                pair = self.sample_strategy.sample_dev(
                    self.iter, grad, hess, key)
            if pair is not None:
                sample = (np.asarray(pair[0]), np.asarray(pair[1]))
            else:
                sample = self.sample_strategy.sample(
                    self.iter, np.asarray(grad), np.asarray(hess))
        else:
            sdev = getattr(self.sample_strategy, "sample_dev", None)
            sample = (sdev(self.iter, key=self._goss_key)
                      if sdev is not None else None)
            if sample is not None:
                sample = (np.asarray(sample[0]), np.asarray(sample[1]))
            else:
                sample = self.sample_strategy.sample(self.iter)
        if sample is not None:
            selected, weight = sample
            sel_dev = jnp.asarray(selected)
            w_dev = jnp.asarray(weight)
        else:
            selected = None
            sel_dev = None
            w_dev = None

        should_continue = False
        for k in range(K):
            if not self.class_need_train[k] or self._grow is None:
                self.models.append(self._constant_tree(init_scores[k]))
                continue
            g, h = grad[k], hess[k]
            if sel_dev is not None:
                gh = jnp.stack([g * w_dev, h * w_dev, sel_dev], axis=1)
            else:
                ones = jnp.ones_like(g)
                gh = jnp.stack([g, h, ones], axis=1)
            fmask = self._feature_mask()
            train_bins = self._train_bins()
            rng_key = None
            if self._grow_rng is not None:
                # fresh per-tree noise: stochastic rounding (ref:
                # gradient_discretizer.cpp random_values_use_start) and/or
                # extra_trees random thresholds
                rng_key = jax.random.fold_in(
                    self._grow_rng, self.iter * K + k)
            with global_timer.section("TreeLearner::Train",
                                      sync=lambda: tree_dev.leaf_value):
                tree_dev, leaf_id = self._grow(train_bins, gh, fmask,
                                               self._cegb_penalty(),
                                               rng_key)
            self._hb_sync_beat()
            with global_timer.section("Tree::ToHost"):
                host = HostTree(jax.tree.map(np.asarray, tree_dev),
                                self.train_set.used_feature_map)

            if host.num_leaves <= 1:
                # no valid split for this class this iteration
                if len(self.models) < K:
                    if (self.objective is not None and
                            not self.config.boost_from_average and
                            not self.has_init_score):
                        init_scores[k] = self._obtain_init_score(k)
                        self.score = self.score.at[k].add(init_scores[k])
                        for vd in self.valid_sets:
                            vd.score = vd.score.at[k].add(init_scores[k])
                    self.models.append(self._constant_tree(init_scores[k]))
                else:
                    self.models.append(self._constant_tree(0.0))
                continue

            should_continue = True
            self._finalize_tree(host)
            leaf_np = np.asarray(leaf_id)
            self._cegb_after_tree(host, leaf_np, selected)

            # -- linear leaves (ref: LinearTreeLearner::CalculateLinear) --
            if self._linear:
                w_np = (np.asarray(weight) * selected
                        if sample is not None else None)
                self._fit_linear_leaves(
                    host, leaf_np, np.asarray(grad[k]), np.asarray(hess[k]),
                    w_np,
                    is_first_tree=(len(self.models) < K and
                                   self.num_init_iteration == 0))

            # -- quantized-gradient leaf renewal ------------------------
            # (ref: GradientDiscretizer::RenewIntGradTreeOutput — refit
            # leaf outputs from the TRUE fp32 grad/hess sums, no smoothing)
            if (self.grower_cfg.quantized and
                    self.config.quant_train_renew_leaf):
                # use the full bagging/GOSS weights (incl. amplification),
                # matching the gh the tree was grown with
                w_np = (np.asarray(weight) * selected
                        if sample is not None else None)
                self._renew_quant_leaves(host, leaf_np,
                                         np.asarray(grad[k]),
                                         np.asarray(hess[k]), w_np)

            # -- RenewTreeOutput (L1-family percentile re-fit) ----------
            # (ref: gbdt.cpp:418 via tree_learner_->RenewTreeOutput)
            if (self.objective is not None and
                    self.objective.is_renew_tree_output()):
                score_k = np.asarray(self.score[k], np.float64)
                label = self.train_set.metadata.label

                def residual_fn():
                    return label.astype(np.float64) - score_k

                renew_leaf = leaf_np
                if selected is not None:
                    # restrict percentile to bagged rows (ref: bag indices)
                    renew_leaf = np.where(selected > 0, leaf_np, -1)
                new_vals = self.objective.renew_tree_output(
                    score_k, residual_fn, renew_leaf, host.num_leaves)
                if new_vals is not None:
                    old = host.leaf_value[:host.num_leaves]
                    host.leaf_value[:host.num_leaves] = np.where(
                        np.isfinite(new_vals), new_vals, old)

            # -- shrinkage + score updates ------------------------------
            # non-linear trees shrink AFTER the updates: the update
            # routes through the same jitted delta/traversal programs
            # the async path uses (unshrunk f32 leaf values x f32 rate),
            # so sync, async and replayed models accumulate bit-identical
            # scores (see _leaf_delta)
            with global_timer.section("GBDT::UpdateScore",
                                      sync=lambda: self.score):
                if host.is_linear:
                    host.shrink(self.shrinkage_rate)
                    delta = jnp.asarray(
                        host.linear_output(self.train_set.raw,
                                           leaf_np).astype(np.float32))
                    self.score = self._score_add(self.score, delta, k)
                else:
                    lv = np.zeros(self.config.num_leaves, np.float32)
                    lv[:host.num_leaves] = host.leaf_value[:host.num_leaves]
                    delta = self._leaf_delta(
                        jnp.asarray(lv), jnp.int32(host.num_leaves),
                        leaf_id, jnp.float32(self.shrinkage_rate))
                    self.score = self._score_add(self.score, delta, k)
            with global_timer.section(
                    "GBDT::UpdateValidScore",
                    sync=lambda: [vd.score for vd in self.valid_sets]):
                for vd in self.valid_sets:
                    if host.is_linear:
                        vd.score = vd.score.at[k].add(
                            self._tree_outputs(host, vd.bins_dev,
                                               vd.dataset.raw))
                    else:
                        vd.score = self._async_traverse_add(
                            vd.score,
                            _host_tree_to_arrays(
                                host, self.config.num_leaves),
                            vd.bins_dev, self.shrinkage_rate, k,
                            num_steps=depth_steps(
                                host.max_depth, self.config.num_leaves))
            if not host.is_linear:
                host.shrink(self.shrinkage_rate)
            if abs(init_scores[k]) > K_EPSILON:
                host.add_bias(init_scores[k])
            if guard is not None:
                guard.check_leaves(host.leaf_value[:host.num_leaves],
                                   self.iter)
            self.models.append(host)

        if not should_continue:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > K:
                del self.models[-K:]
            return True
        self.iter += 1
        return False

    def _fit_linear_leaves(self, host: HostTree, leaf_np: np.ndarray,
                           grad: np.ndarray, hess: np.ndarray,
                           weight: Optional[np.ndarray],
                           is_first_tree: bool) -> None:
        """Fit a ridge-regularized linear model in every leaf over the
        NUMERICAL features on the leaf's path (ref: linear_tree_learner.cpp
        CalculateLinear — coeffs = -(X'HX + lambda*I)^-1 X'g per Eq 3 of
        arXiv:1802.05640; NaN rows excluded; leaves with too few usable
        rows stay constant; |coef| <= 1e-35 dropped)."""
        host.is_linear = True
        host._init_linear_fields()
        n = host.num_leaves
        host.leaf_const[:] = host.leaf_value[:n]
        if is_first_tree:
            return
        raw = self.train_set.raw
        lam = float(self.config.linear_lambda)
        mappers = self.train_set.bin_mappers

        # numerical features on each leaf's path (sorted unique ORIGINAL
        # indices, like branch_features + InnerFeatureIndex filtering);
        # explicit stack — leaf-wise trees can be num_leaves deep
        path_feats = {}
        stack = [(0, [])]
        while stack:
            node, feats = stack.pop()
            if node < 0:
                path_feats[~node] = sorted(set(feats))
                continue
            f = int(host.split_feature[node])
            nxt = feats + [f] if mappers[f].bin_type == "numerical" else feats
            stack.append((int(host.left_child[node]), nxt))
            stack.append((int(host.right_child[node]), nxt))

        # group rows by leaf in one argsort pass (not O(N*L) scans)
        order = np.argsort(leaf_np, kind="stable")
        counts = np.bincount(leaf_np, minlength=n)
        starts = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=starts[1:])

        g = grad.astype(np.float64)
        h = hess.astype(np.float64)
        if weight is not None:
            g = g * weight
            h = h * weight
        for leaf, feats in path_feats.items():
            if not feats:
                continue
            rows = order[starts[leaf]:starts[leaf + 1]]
            if weight is not None:
                rows = rows[weight[rows] > 0]
            Xl = raw[np.ix_(rows, feats)].astype(np.float64)
            ok = ~np.isnan(Xl).any(axis=1)
            rows, Xl = rows[ok], Xl[ok]
            if len(rows) < len(feats) + 1:
                continue  # leaf stays constant
            X1 = np.concatenate([Xl, np.ones((len(rows), 1))], axis=1)
            hw = h[rows]
            XTHX = (X1 * hw[:, None]).T @ X1
            XTHX[np.arange(len(feats)), np.arange(len(feats))] += lam
            XTg = X1.T @ g[rows]
            try:
                coeffs = -np.linalg.solve(XTHX, XTg)
            except np.linalg.LinAlgError:
                continue
            if not np.isfinite(coeffs).all():
                continue
            keep = np.abs(coeffs[:-1]) > 1e-35
            host.leaf_features[leaf] = [feats[j]
                                        for j in np.flatnonzero(keep)]
            host.leaf_coeff[leaf] = coeffs[:-1][keep]
            host.leaf_const[leaf] = coeffs[-1]

    def _renew_quant_leaves(self, host: HostTree, leaf_np: np.ndarray,
                            grad: np.ndarray, hess: np.ndarray,
                            weight: Optional[np.ndarray]) -> None:
        """Refit leaf outputs from true fp32 gradient sums after quantized
        growth (ref: gradient_discretizer.cpp RenewIntGradTreeOutput —
        CalculateSplittedLeafOutput without path smoothing). ``weight`` is
        the full bagging/GOSS row weight (amplification included)."""
        cfg = self.config
        n = host.num_leaves
        w = weight.astype(np.float64) if weight is not None \
            else np.ones_like(grad, np.float64)
        sg = np.bincount(leaf_np, weights=grad * w, minlength=n)[:n]
        sh = np.bincount(leaf_np, weights=hess * w, minlength=n)[:n]
        l1, l2 = cfg.lambda_l1, cfg.lambda_l2
        tg = np.sign(sg) * np.maximum(np.abs(sg) - l1, 0.0) if l1 > 0 else sg
        out = -tg / (sh + l2 + K_EPSILON)
        if cfg.max_delta_step > 0:
            out = np.clip(out, -cfg.max_delta_step, cfg.max_delta_step)
        host.leaf_value[:n] = np.where(np.isfinite(out), out,
                                       host.leaf_value[:n])

    def _constant_tree(self, value: float) -> HostTree:
        """ref: Tree::AsConstantTree."""
        t = HostTree.constant(value)
        return t

    def _finalize_tree(self, host: HostTree) -> None:
        """Resolve bin thresholds to real values and pack decision_type bits
        (ref: tree.h kCategoricalMask=1, kDefaultLeftMask=2, missing type in
        bits 2-3; Tree::Split stores RealThreshold = bin upper bound)."""
        mappers = self.train_set.bin_mappers
        n_int = host.num_leaves - 1
        thr_real = np.zeros(n_int, np.float64)
        dtype_bits = np.zeros(n_int, np.int32)
        miss_enum = {"none": 0, "zero": 1, "nan": 2}
        cat_boundaries = [0]
        cat_words: List[np.ndarray] = []
        for i in range(n_int):
            m = mappers[host.split_feature[i]]
            tb = int(host.threshold_bin[i])
            if m.bin_type == "categorical":
                # categorical optimal split: translate the chosen BIN set
                # into a bitset over RAW category values (ref: Tree::
                # SplitCategorical cat_threshold_/cat_boundaries_,
                # Common::ConstructBitset); threshold_real holds cat_idx
                k = int(host.cat_count_inner[i])
                bins_set = host.cat_bins_inner[i][:k]
                cats = [m.bin_2_categorical[b] for b in bins_set
                        if 0 < b < len(m.bin_2_categorical) and
                        m.bin_2_categorical[b] >= 0]
                n_words = (max(cats) // 32 + 1) if cats else 1
                words = np.zeros(n_words, np.uint32)
                for v in cats:
                    words[v // 32] |= np.uint32(1) << np.uint32(v % 32)
                thr_real[i] = float(len(cat_boundaries) - 1)  # cat_idx
                cat_boundaries.append(cat_boundaries[-1] + n_words)
                cat_words.append(words)
                dtype_bits[i] |= 1
            else:
                thr_real[i] = m.bin_upper_bound[min(
                    tb, len(m.bin_upper_bound) - 1)]
            if host.default_left[i]:
                dtype_bits[i] |= 2
            dtype_bits[i] |= miss_enum[m.missing_type] << 2
        host.threshold_real = thr_real
        host.decision_type = dtype_bits
        host.num_cat = len(cat_words)
        host.cat_boundaries = np.asarray(cat_boundaries, np.int64)
        host.cat_threshold = (np.concatenate(cat_words) if cat_words
                              else np.zeros(0, np.uint32))

    def rollback_one_iter(self) -> None:
        """ref: gbdt.cpp:463 RollbackOneIter."""
        if self.iter <= 0:
            return
        K = self.num_tree_per_iteration
        for k in range(K):
            t = self.models[len(self.models) - K + k]
            # subtract contribution from train & valid scores
            self.score = self.score.at[k].add(
                -self._tree_outputs(t, self.bins_dev, self.train_set.raw))
            for vd in self.valid_sets:
                vd.score = vd.score.at[k].add(
                    -self._tree_outputs(t, vd.bins_dev, vd.dataset.raw))
        del self.models[-K:]
        self.iter -= 1

    # ------------------------------------------------------------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval(self.train_metrics, self.score, "training")

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vd in self.valid_sets:
            out.extend(self._eval(vd.metrics, vd.score, vd.name))
        return out

    def rng_snapshot(self) -> Dict:
        """JSON-serializable snapshot of every host RNG that advances
        per iteration/tree — the bagging sampler and the column sampler.
        Restoring it (restore_rng) before the next iteration makes a
        checkpoint-resumed run draw the exact masks an uninterrupted
        run would have drawn (the GOSS/device-bagging samplers are
        stateless fold_in(key, iter) chains and need no snapshot)."""
        samp = getattr(self.sample_strategy, "rng", None)
        col = getattr(self, "_col_rng", None)
        return {
            "sampler": samp.bit_generator.state if samp is not None
            else None,
            "col": col.bit_generator.state if col is not None else None,
        }

    def restore_rng(self, snapshot: Dict) -> None:
        """Inverse of rng_snapshot (missing entries are left alone)."""
        if not snapshot:
            return
        samp = getattr(self.sample_strategy, "rng", None)
        if samp is not None and snapshot.get("sampler"):
            samp.bit_generator.state = snapshot["sampler"]
        if snapshot.get("col") and getattr(self, "_col_rng", None) \
                is not None:
            self._col_rng.bit_generator.state = snapshot["col"]

    def init_from_model(self, other: "GBDT") -> None:
        """Continued training from an existing model (ref: CLI input_model,
        boosting.h:305 Boosting::CreateBoosting(filename) then continue)."""
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            log.fatal("Cannot continue training: num_tree_per_iteration "
                      "differs between the init model and this config")
        K = self.num_tree_per_iteration
        self.models = [t.copy() for t in other.models]
        self.num_init_iteration = len(self.models) // max(K, 1)
        # trees loaded from model text carry ORIGINAL feature indices and
        # real thresholds; rebind them to this dataset's inner indices/bins
        inner_of = {int(orig): i for i, orig in
                    enumerate(self.train_set.used_feature_map)}
        mappers = self.train_set.bin_mappers
        for t in self.models:
            if not getattr(t, "from_text", False):
                continue
            cat_sets = {}
            for i in range(t.num_leaves - 1):
                f = int(t.split_feature[i])
                if f not in inner_of:
                    log.fatal(f"init model splits on feature {f} which is "
                              "trivial/absent in the new training data")
                t.split_feature_inner[i] = inner_of[f]
                m = mappers[f]
                if m.bin_type == "numerical":
                    t.threshold_bin[i] = int(
                        m.value_to_bin(np.asarray([t.threshold_real[i]]))[0])
                elif (t.decision_type[i] & 1) and t.num_cat > 0:
                    # decode the raw-category bitset back to this dataset's
                    # BIN set so binned traversal replays correctly
                    vals = t.cat_values(int(t.threshold_real[i]))
                    cat_sets[i] = [m.categorical_2_bin[v] for v in vals
                                   if v in m.categorical_2_bin]
            if cat_sets:
                width = max(len(s) for s in cat_sets.values())
                ni = t.num_leaves - 1
                t.cat_bins_inner = np.full((ni, width), -1, np.int32)
                t.cat_count_inner = np.zeros(ni, np.int32)
                for i, s in cat_sets.items():
                    t.cat_bins_inner[i, :len(s)] = s
                    t.cat_count_inner[i] = len(s)
            t.from_text = False
        bins_replay = None
        if getattr(self, "_sharded_ingest", False):
            # sharded ingestion: replay each tree over the LOCAL shard's
            # feature-major bins and allgather the per-row outputs into
            # the global rank-order layout — elementwise per row, so the
            # restored score is bit-identical to a replicated replay
            # (the checkpoint-resume path for multi-host runs). One
            # allgather PER TREE is deliberate: batching trees into a
            # local accumulator before gathering would reassociate the
            # f32 score sum and break the bit-exact-resume contract
            # (each tree must land on the score in the same order and
            # rounding as the replicated `.at[k].add` chain)
            bins_replay = jnp.asarray(self.train_set.bins)
        for i, t in enumerate(self.models):
            k = i % K
            if bins_replay is not None:
                from ..distributed import allgather_bytes
                local = np.asarray(
                    self._tree_outputs(t, bins_replay, None), np.float32)
                parts = allgather_bytes(
                    local.tobytes(),
                    what="sharded ingest: continued-training replay")
                self.score = self.score.at[k].add(jnp.asarray(
                    np.concatenate([np.frombuffer(p, np.float32)
                                    for p in parts])))
            else:
                self.score = self.score.at[k].add(
                    self._tree_outputs(t, self.bins_dev,
                                       self.train_set.raw))
            for vd in self.valid_sets:
                vd.score = vd.score.at[k].add(
                    self._tree_outputs(t, vd.bins_dev, vd.dataset.raw))

    def _eval(self, metrics, score, data_name):
        """Evaluate metrics over a device score array.

        On non-CPU backends, metrics with a device path (Metric.
        eval_device) compute on device and ALL their scalars come back
        in one stacked fetch — pulling the full [K, N] score through a
        high-latency tunnel every eval would otherwise dominate training
        when valid sets are attached. Metrics without a device path fall
        back to the host implementation (one score pull, shared)."""
        out = []
        K = self.num_tree_per_iteration
        # tpu_device_eval gates the f32 device path (its clips are wider
        # than the host f64 path's — saturated predictions can report
        # different logloss and flip early-stopping decisions)
        mode = str(getattr(self.config, "tpu_device_eval", "auto")).lower()
        if mode == "auto":
            use_dev = jax.default_backend() != "cpu"
        else:
            use_dev = mode in ("true", "1", "yes")
        view_dev = score[0] if K == 1 else score
        entries = []          # ("dev", name, hib, idx) | ("host", metric)
        dev_scalars = []
        for m in metrics:
            dev = m.eval_device(view_dev, self.objective) if use_dev \
                else None
            if dev is None:
                entries.append(("host", m))
            else:
                for name, scalar, hib in dev:
                    entries.append(("dev", name, hib, len(dev_scalars)))
                    dev_scalars.append(scalar)
        fetched = (np.asarray(jnp.stack(dev_scalars), np.float64)
                   if dev_scalars else None)
        view_np = None
        for e in entries:
            if e[0] == "host":
                if view_np is None:
                    score_np = np.asarray(score, np.float64)
                    view_np = score_np[0] if K == 1 else score_np
                for name, value, hib in e[1].eval(view_np, self.objective):
                    out.append((data_name, name, value, hib))
            else:
                out.append((data_name, e[1], float(fetched[e[3]]), e[2]))
        return out

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter

    def current_iteration(self) -> int:
        return len(self.models) // max(self.num_tree_per_iteration, 1)
