"""GBDT boosting orchestrator.

TPU-native equivalent of the reference boosting layer
(ref: src/boosting/gbdt.{h,cpp} — Init :60, BoostFromAverage :328,
Boosting :229, TrainOneIter :353-461, UpdateScore :502, eval :534,
RollbackOneIter :463; src/boosting/score_updater.hpp ScoreUpdater).

State design (SURVEY.md §7): scores live on device as f32 [K, N] arrays;
gradients are computed on device by the objective (≡ boosting_on_gpu_,
gbdt.cpp:111); each tree is grown by the jitted leaf-wise grower; the train
score update reuses the grower's per-row leaf_id (no traversal needed);
valid scores update via batched device traversal over binned data.
Host keeps the canonical model list (HostTree) for IO/serving, exactly
mirroring models_ in the reference.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..core.grower import GrowerConfig, make_tree_grower
from ..core.metrics import Metric, metrics_for_config
from ..core.objective import ObjectiveFunction, CustomObjective, K_EPSILON
from ..core.tree import HostTree, TreeArrays
from ..io.dataset_core import BinnedDataset
from ..ops.split import FeatureMeta, SplitHyperParams
from ..ops.predict import tree_leaf_bins
from ..utils import log
from ..utils.timer import global_timer
from .sample_strategy import SampleStrategy


def _host_tree_to_arrays(t: HostTree, max_leaves: int) -> TreeArrays:
    """Rebuild device TreeArrays from a host tree (for DART drop/restore &
    valid-set traversal of reloaded models)."""
    li = max_leaves - 1
    L = max_leaves

    def pad_i(a, n):
        out = np.zeros(n, np.int32)
        out[:len(a)] = a
        return jnp.asarray(out)

    def pad_f(a, n):
        out = np.zeros(n, np.float32)
        out[:len(a)] = a
        return jnp.asarray(out)

    def pad_b(a, n):
        out = np.zeros(n, bool)
        out[:len(a)] = a
        return jnp.asarray(out)

    return TreeArrays(
        split_feature=pad_i(t.split_feature_inner, li),
        threshold_bin=pad_i(t.threshold_bin, li),
        default_left=pad_b(t.default_left, li),
        left_child=pad_i(t.left_child, li),
        right_child=pad_i(t.right_child, li),
        split_gain=pad_f(t.split_gain, li),
        internal_value=pad_f(t.internal_value, li),
        internal_weight=pad_f(t.internal_weight, li),
        internal_count=pad_f(t.internal_count, li),
        leaf_value=pad_f(t.leaf_value, L),
        leaf_weight=pad_f(t.leaf_weight, L),
        leaf_count=pad_f(t.leaf_count, L),
        leaf_parent=pad_i(t.leaf_parent, L),
        num_leaves=jnp.asarray(t.num_leaves, jnp.int32),
        shrinkage=jnp.asarray(t.shrinkage, jnp.float32),
    )


def _orig_to_used(used_feature_map) -> dict:
    """Original feature index -> used (inner) index (ref: Dataset::
    InnerFeatureIndex)."""
    return {int(o): u for u, o in enumerate(used_feature_map)}


def _parse_interaction_constraints(spec) -> list:
    """Parse "[0,1,2],[2,3]" (or a list of lists) into a list of int lists
    (ref: config.h interaction_constraints string format)."""
    if isinstance(spec, (list, tuple)):
        return [list(map(int, grp)) for grp in spec]
    import re
    return [[int(v) for v in grp.split(",") if v.strip() != ""]
            for grp in re.findall(r"\[([^\[\]]*)\]", str(spec))]


class _ValidData:
    """One validation set: device bins + score + metrics
    (ref: valid_score_updater_ / valid_metrics_ in gbdt.h)."""

    def __init__(self, dataset: BinnedDataset, metrics: List[Metric],
                 num_class: int, name: str = "valid"):
        self.dataset = dataset
        self.metrics = metrics
        self.name = name
        self.bins_dev = jnp.asarray(dataset.bins)
        self.score = jnp.zeros((num_class, dataset.num_data), jnp.float32)
        if dataset.metadata.init_score is not None:
            init = dataset.metadata.init_score.reshape(
                -1, dataset.num_data).astype(np.float32)
            self.score = jnp.asarray(init)


class GBDT:
    """Gradient Boosting Decision Tree engine (ref: gbdt.h:28)."""

    NAME = "gbdt"

    def __init__(self, config: Config, train_set: Optional[BinnedDataset],
                 objective: Optional[ObjectiveFunction]):
        self.config = config
        self.train_set = train_set
        self.objective = objective
        self.models: List[HostTree] = []
        self.iter = 0
        self.num_init_iteration = 0
        self.shrinkage_rate = float(config.learning_rate)
        self.valid_sets: List[_ValidData] = []
        self.train_metrics: List[Metric] = []
        self.best_score_by_metric: Dict[str, float] = {}
        # model-level metadata for IO
        self.max_feature_idx = 0
        self.label_idx = 0
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.average_output = False  # RF sets true

        if objective is not None:
            self.num_tree_per_iteration = objective.num_model_per_iteration
        else:
            self.num_tree_per_iteration = int(config.num_class)

        if train_set is not None:
            self._setup_train(train_set)

    # ------------------------------------------------------------------
    def _setup_train(self, train: BinnedDataset) -> None:
        cfg = self.config
        cfg.warn_unimplemented()
        self.num_data = train.num_data
        self.max_feature_idx = train.num_total_features - 1
        self.feature_names = list(train.feature_names)
        self.feature_infos = train.feature_infos()
        md = train.metadata

        if self.objective is not None:
            self.objective.init(md, train.num_data)
        self.train_metrics = []

        mappers = train.used_bin_mappers()
        # monotone constraints are per ORIGINAL feature; gather to used
        # features (ref: feature_histogram.hpp:1440-1443)
        monotone = None
        if cfg.monotone_constraints:
            mc_in = np.asarray(cfg.monotone_constraints, np.int32)
            if len(mc_in) != train.num_total_features:
                log.fatal(
                    f"monotone_constraints has {len(mc_in)} entries but the "
                    f"dataset has {train.num_total_features} features")
            if np.any(mc_in != 0):
                monotone = mc_in[train.used_feature_map]
                if cfg.monotone_constraints_method not in ("basic",):
                    log.warning(
                        f"monotone_constraints_method="
                        f"{cfg.monotone_constraints_method} not implemented; "
                        "using 'basic'")
        self.feature_meta = FeatureMeta.from_mappers(mappers, monotone) \
            if mappers else None
        self.num_bin_max = int(max((m.num_bin for m in mappers), default=2))
        self.bins_dev = jnp.asarray(train.bins) if train.bins is not None \
            else None

        K = self.num_tree_per_iteration
        self.score = jnp.zeros((K, self.num_data), jnp.float32)
        if md.init_score is not None:
            init = md.init_score.reshape(-1, self.num_data).astype(np.float32)
            self.score = jnp.asarray(init)
            self.has_init_score = True
        else:
            self.has_init_score = False

        self.class_need_train = [
            self.objective.class_need_train(k) if self.objective else True
            for k in range(K)]

        self.sample_strategy = SampleStrategy.create(
            cfg, self.num_data, K, metadata=md)

        hp = SplitHyperParams(
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=cfg.min_gain_to_split,
            max_delta_step=cfg.max_delta_step,
            path_smooth=cfg.path_smooth,
            monotone_penalty=cfg.monotone_penalty)
        backend = "xla"
        if cfg.tpu_use_pallas and jax.default_backend() == "tpu":
            backend = "pallas"
        # interaction constraints: "[0,1,2],[2,3]" over ORIGINAL feature
        # indices -> tuple of tuples of USED indices (ref: col_sampler.hpp,
        # config.h interaction_constraints)
        groups = None
        if cfg.interaction_constraints:
            parsed = _parse_interaction_constraints(
                cfg.interaction_constraints)
            if not parsed:
                log.fatal(
                    f"could not parse interaction_constraints="
                    f"{cfg.interaction_constraints!r}; expected e.g. "
                    "\"[0,1,2],[2,3]\"")
            orig2used = _orig_to_used(train.used_feature_map)
            groups = tuple(
                tuple(orig2used[f] for f in grp if f in orig2used)
                for grp in parsed)
        self._bynode = cfg.feature_fraction_bynode < 1.0
        self.grower_cfg = GrowerConfig(
            num_leaves=cfg.num_leaves, max_depth=cfg.max_depth,
            num_bin=self.num_bin_max, hparams=hp, hist_backend=backend,
            block_rows=cfg.tpu_rows_per_block,
            bynode_mask=self._bynode, interaction_groups=groups)
        forced = self._load_forced_splits(train)
        self._setup_cegb(train)
        if self.feature_meta is not None:
            self._grow = jax.jit(
                make_tree_grower(self.grower_cfg, self.feature_meta,
                                 forced=forced))
        else:
            self._grow = None

        # jitted gradient fn (device-resident labels/weights in the closure)
        if self.objective is not None and \
                not isinstance(self.objective, CustomObjective):
            obj = self.objective
            if K == 1:
                self._gh_fn = jax.jit(lambda s: obj.get_gradients(s[0]))
            else:
                self._gh_fn = jax.jit(lambda s: obj.get_gradients(s))
        else:
            self._gh_fn = None

        # feature sampling state (ref: col_sampler.hpp)
        self._col_rng = np.random.default_rng(cfg.feature_fraction_seed)
        self.num_used_features = train.num_used_features

    # ------------------------------------------------------------------
    def add_valid_data(self, valid: BinnedDataset,
                       metrics: Optional[List[Metric]] = None,
                       name: Optional[str] = None) -> None:
        if metrics is None:
            metrics = metrics_for_config(
                self.config,
                self.objective.NAME if self.objective else "custom")
        for m in metrics:
            m.init(valid.metadata, valid.num_data)
        vd = _ValidData(valid, metrics, self.num_tree_per_iteration,
                        name or f"valid_{len(self.valid_sets) + 1}")
        # replay existing model onto the new valid set (continued training)
        for it in range(len(self.models) // self.num_tree_per_iteration):
            for k in range(self.num_tree_per_iteration):
                t = self.models[it * self.num_tree_per_iteration + k]
                vd.score = vd.score.at[k].add(self._tree_outputs(
                    t, vd.bins_dev))
        self.valid_sets.append(vd)

    def add_train_metrics(self, metrics: List[Metric]) -> None:
        for m in metrics:
            m.init(self.train_set.metadata, self.num_data)
        self.train_metrics = metrics

    # ------------------------------------------------------------------
    def _load_forced_splits(self, train: BinnedDataset):
        """Parse forcedsplits_filename JSON into the grower's static forced
        arrays (ref: gbdt.cpp:91-97 forced_splits_json_, serial_tree_learner
        ForceSplits). Leaf slots are simulated exactly like the grower
        assigns them: splitting slot s at step i keeps the left child in s
        and puts the right child in slot i+1."""
        cfg = self.config
        if not cfg.forcedsplits_filename:
            return None
        import json
        with open(cfg.forcedsplits_filename) as f:
            root = json.load(f)
        if not root or "feature" not in root:
            return None
        orig2used = _orig_to_used(train.used_feature_map)
        L = cfg.num_leaves
        active = np.zeros(L - 1, bool)
        slot = np.zeros(L - 1, np.int32)
        feat = np.zeros(L - 1, np.int32)
        thr = np.zeros(L - 1, np.int32)
        from collections import deque
        q = deque([(root, 0)])
        step = 0
        while q and step < L - 1:
            node, s = q.popleft()
            f_orig = int(node["feature"])
            if f_orig not in orig2used:
                log.warning(f"forced split on unused feature {f_orig}; "
                            "stopping forced prefix here")
                break
            mapper = train.bin_mappers[f_orig]
            # real threshold -> bin: the left side is value <= threshold,
            # i.e. bin(threshold) (ref: Dataset::BinThreshold)
            tb = int(mapper.value_to_bin(
                np.asarray([float(node["threshold"])]))[0])
            active[step] = True
            slot[step] = s
            feat[step] = orig2used[f_orig]
            thr[step] = tb
            left_slot, right_slot = s, step + 1
            for key, child_slot in (("left", left_slot),
                                    ("right", right_slot)):
                child = node.get(key)
                if isinstance(child, dict) and "feature" in child and \
                        "threshold" in child:
                    q.append((child, child_slot))
            step += 1
        if not active.any():
            return None
        return (active, slot, feat, thr)

    # ------------------------------------------------------------------
    def _setup_cegb(self, train: BinnedDataset) -> None:
        """Cost-efficient gradient boosting state (ref: cost_effective_
        gradient_boosting.hpp). Penalties are applied per feature as
        penalty[f] = const[f] + per_count[f] * num_data_in_leaf:

        - cegb_penalty_split enters per_count exactly;
        - cegb_penalty_feature_coupled enters const for features not yet
          used anywhere in the forest (used-set updated between trees —
          the reference's within-tree re-ranking of cached candidates,
          UpdateLeafBestSplits, is approximated at tree granularity);
        - cegb_penalty_feature_lazy enters per_count scaled by the fraction
          of rows not yet charged for the feature (the reference charges
          per uncharged row in the leaf; here the global uncharged fraction
          stands in for the per-leaf one, again tree-granular).
        """
        cfg = self.config
        F = train.num_used_features
        coupled = cfg.cegb_penalty_feature_coupled
        lazy = cfg.cegb_penalty_feature_lazy
        self._cegb_enabled = bool(
            cfg.cegb_penalty_split > 0.0 or coupled or lazy)
        if not self._cegb_enabled:
            return
        for name, pen in (("coupled", coupled), ("lazy", lazy)):
            if pen and len(pen) != train.num_total_features:
                log.fatal(f"cegb_penalty_feature_{name} should be the same "
                          "size as feature number")
        ufm = train.used_feature_map
        self._cegb_coupled = (np.asarray(coupled, np.float64)[ufm]
                              if coupled else np.zeros(F))
        self._cegb_lazy = (np.asarray(lazy, np.float64)[ufm]
                           if lazy else np.zeros(F))
        self._cegb_feature_used = np.zeros(F, bool)
        self._cegb_row_charged = (np.zeros((F, self.num_data), bool)
                                  if lazy else None)

    def _cegb_penalty(self):
        """(const [F], per_count [F]) for the current tree, or None."""
        if not getattr(self, "_cegb_enabled", False):
            return None
        cfg = self.config
        tradeoff = cfg.cegb_tradeoff
        const = tradeoff * self._cegb_coupled * (~self._cegb_feature_used)
        per_count = np.full(self.num_used_features,
                            tradeoff * cfg.cegb_penalty_split)
        if self._cegb_row_charged is not None:
            frac_uncharged = 1.0 - self._cegb_row_charged.mean(axis=1)
            per_count = per_count + tradeoff * self._cegb_lazy * frac_uncharged
        return (jnp.asarray(const, jnp.float32),
                jnp.asarray(per_count, jnp.float32))

    def _cegb_after_tree(self, host: "HostTree", leaf_np: np.ndarray,
                         selected: Optional[np.ndarray] = None) -> None:
        """Update the forest-level used-feature set and per-row charges.
        ``selected`` is the bagging mask — only in-bag rows actually had
        their features fetched, so only they get charged (ref: cost_
        effective_gradient_boosting.hpp UpdateLeafBestSplits uses
        data_partition indices, which contain bagged rows only)."""
        if not getattr(self, "_cegb_enabled", False):
            return
        n_int = host.num_leaves - 1
        for i in range(n_int):
            self._cegb_feature_used[int(host.split_feature_inner[i])] = True
        if self._cegb_row_charged is not None and n_int > 0:
            # rows in each leaf are charged for the features on its path
            path_feats = {}  # leaf -> set of inner features

            def walk(node, feats):
                if node < 0:
                    path_feats[~node] = feats
                    return
                f = int(host.split_feature_inner[node])
                walk(int(host.left_child[node]), feats | {f})
                walk(int(host.right_child[node]), feats | {f})
            walk(0, frozenset())
            in_bag = selected > 0 if selected is not None else None
            for leaf, feats in path_feats.items():
                if not feats:
                    continue
                rows = leaf_np == leaf
                if in_bag is not None:
                    rows = rows & in_bag
                for f in feats:
                    self._cegb_row_charged[f, rows] = True

    # ------------------------------------------------------------------
    def _feature_mask(self) -> Optional[jnp.ndarray]:
        """Column sampling (ref: col_sampler.hpp): feature_fraction samples
        once per tree; feature_fraction_bynode additionally samples per node
        (one mask row per grower step)."""
        frac = self.config.feature_fraction
        F = self.num_used_features
        tree_mask = np.ones(F, bool)
        if frac < 1.0 and F > 1:
            n_take = max(1, min(F, int(round(F * frac))))
            tree_mask = np.zeros(F, bool)
            tree_mask[self._col_rng.choice(F, size=n_take,
                                           replace=False)] = True
        if not self._bynode:
            if frac >= 1.0 or F <= 1:
                return None
            return jnp.asarray(tree_mask)
        # per-node masks: sample within the tree-level subset per node.
        # Row layout matches the grower: root=0, step i children 2i+1/2i+2.
        L = self.config.num_leaves
        frac_node = self.config.feature_fraction_bynode
        base_idx = np.flatnonzero(tree_mask)
        n_node = max(1, int(round(len(base_idx) * frac_node)))
        masks = np.zeros((2 * L, F), bool)
        for i in range(2 * L):
            take = self._col_rng.choice(base_idx, size=n_node, replace=False)
            masks[i, take] = True
        return jnp.asarray(masks)

    def _obtain_init_score(self, k: int) -> float:
        """ref: gbdt.cpp:317 ObtainAutomaticInitialScore + network mean."""
        init = self.objective.boost_from_score(k) if self.objective else 0.0
        return float(init)

    def _boost_from_average(self, k: int) -> float:
        """ref: gbdt.cpp:328 BoostFromAverage."""
        if (not self.models and not self.has_init_score and
                self.objective is not None and
                (self.config.boost_from_average or
                 self.num_used_features == 0)):
            init_score = self._obtain_init_score(k)
            if abs(init_score) > K_EPSILON:
                self.score = self.score.at[k].add(init_score)
                for vd in self.valid_sets:
                    vd.score = vd.score.at[k].add(init_score)
                log.info(f"Start training from score {init_score:.6f}")
                return init_score
        return 0.0

    def _tree_outputs(self, t: HostTree, bins_dev) -> jnp.ndarray:
        """Per-row output of a host tree over binned data."""
        arrs = _host_tree_to_arrays(t, self.config.num_leaves)
        leaf = tree_leaf_bins(arrs, bins_dev, self.feature_meta.num_bin,
                              self.feature_meta.missing_type,
                              self.feature_meta.default_bin)
        return arrs.leaf_value[leaf]

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (ref: gbdt.cpp:353 TrainOneIter).
        Returns True when training should stop (no more valid splits)."""
        K = self.num_tree_per_iteration
        init_scores = [0.0] * K

        if gradients is None or hessians is None:
            for k in range(K):
                init_scores[k] = self._boost_from_average(k)
            with global_timer.section("GBDT::Boosting",
                                      sync=lambda: grad):
                grad, hess = self._gh_fn(self.score)
            if K == 1:
                grad = grad[None, :]
                hess = hess[None, :]
        else:
            grad = jnp.asarray(
                np.asarray(gradients, np.float32).reshape(K, self.num_data))
            hess = jnp.asarray(
                np.asarray(hessians, np.float32).reshape(K, self.num_data))

        # -- bagging / GOSS (host decision, device apply) ---------------
        sample = self.sample_strategy.sample(
            self.iter, np.asarray(grad), np.asarray(hess))
        if sample is not None:
            selected, weight = sample
            sel_dev = jnp.asarray(selected)
            w_dev = jnp.asarray(weight)
        else:
            selected = None
            sel_dev = None
            w_dev = None

        should_continue = False
        for k in range(K):
            if not self.class_need_train[k] or self._grow is None:
                self.models.append(self._constant_tree(init_scores[k]))
                continue
            g, h = grad[k], hess[k]
            if sel_dev is not None:
                gh = jnp.stack([g * w_dev, h * w_dev, sel_dev], axis=1)
            else:
                ones = jnp.ones_like(g)
                gh = jnp.stack([g, h, ones], axis=1)
            fmask = self._feature_mask()
            with global_timer.section("TreeLearner::Train",
                                      sync=lambda: tree_dev.leaf_value):
                tree_dev, leaf_id = self._grow(self.bins_dev, gh, fmask,
                                               self._cegb_penalty())
            with global_timer.section("Tree::ToHost"):
                host = HostTree(jax.tree.map(np.asarray, tree_dev),
                                self.train_set.used_feature_map)

            if host.num_leaves <= 1:
                # no valid split for this class this iteration
                if len(self.models) < K:
                    if (self.objective is not None and
                            not self.config.boost_from_average and
                            not self.has_init_score):
                        init_scores[k] = self._obtain_init_score(k)
                        self.score = self.score.at[k].add(init_scores[k])
                        for vd in self.valid_sets:
                            vd.score = vd.score.at[k].add(init_scores[k])
                    self.models.append(self._constant_tree(init_scores[k]))
                else:
                    self.models.append(self._constant_tree(0.0))
                continue

            should_continue = True
            self._finalize_tree(host)
            leaf_np = np.asarray(leaf_id)
            self._cegb_after_tree(host, leaf_np, selected)

            # -- RenewTreeOutput (L1-family percentile re-fit) ----------
            # (ref: gbdt.cpp:418 via tree_learner_->RenewTreeOutput)
            if (self.objective is not None and
                    self.objective.is_renew_tree_output()):
                score_k = np.asarray(self.score[k], np.float64)
                label = self.train_set.metadata.label

                def residual_fn():
                    return label.astype(np.float64) - score_k

                renew_leaf = leaf_np
                if selected is not None:
                    # restrict percentile to bagged rows (ref: bag indices)
                    renew_leaf = np.where(selected > 0, leaf_np, -1)
                new_vals = self.objective.renew_tree_output(
                    score_k, residual_fn, renew_leaf, host.num_leaves)
                if new_vals is not None:
                    old = host.leaf_value[:host.num_leaves]
                    host.leaf_value[:host.num_leaves] = np.where(
                        np.isfinite(new_vals), new_vals, old)

            # -- shrinkage + score updates ------------------------------
            host.shrink(self.shrinkage_rate)
            with global_timer.section("GBDT::UpdateScore",
                                      sync=lambda: self.score):
                lv = np.zeros(self.config.num_leaves, np.float32)
                lv[:host.num_leaves] = host.leaf_value[:host.num_leaves]
                lv_dev = jnp.asarray(lv)
                self.score = self.score.at[k].add(lv_dev[leaf_id])
            with global_timer.section(
                    "GBDT::UpdateValidScore",
                    sync=lambda: [vd.score for vd in self.valid_sets]):
                for vd in self.valid_sets:
                    vd.score = vd.score.at[k].add(
                        self._tree_outputs(host, vd.bins_dev))
            if abs(init_scores[k]) > K_EPSILON:
                host.add_bias(init_scores[k])
            self.models.append(host)

        if not should_continue:
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            if len(self.models) > K:
                del self.models[-K:]
            return True
        self.iter += 1
        return False

    def _constant_tree(self, value: float) -> HostTree:
        """ref: Tree::AsConstantTree."""
        t = HostTree.constant(value)
        return t

    def _finalize_tree(self, host: HostTree) -> None:
        """Resolve bin thresholds to real values and pack decision_type bits
        (ref: tree.h kCategoricalMask=1, kDefaultLeftMask=2, missing type in
        bits 2-3; Tree::Split stores RealThreshold = bin upper bound)."""
        from ..io.binning import MISSING_NONE, MISSING_ZERO
        mappers = self.train_set.bin_mappers
        n_int = host.num_leaves - 1
        thr_real = np.zeros(n_int, np.float64)
        dtype_bits = np.zeros(n_int, np.int32)
        miss_enum = {"none": 0, "zero": 1, "nan": 2}
        cat_maps = {}
        for i in range(n_int):
            m = mappers[host.split_feature[i]]
            tb = int(host.threshold_bin[i])
            if m.bin_type == "categorical":
                # interim ordered-bin categorical split: serve by mapping the
                # raw category to its bin (train/serve consistent); the
                # LightGBM bitset subset split lands with the categorical
                # optimal-split work (ref: feature_histogram.hpp sorted-subset)
                thr_real[i] = float(tb)
                dtype_bits[i] |= 1
                f_orig = int(host.split_feature[i])
                if f_orig not in cat_maps:
                    cat_maps[f_orig] = dict(m.categorical_2_bin)
            else:
                thr_real[i] = m.bin_upper_bound[min(
                    tb, len(m.bin_upper_bound) - 1)]
            if host.default_left[i]:
                dtype_bits[i] |= 2
            dtype_bits[i] |= miss_enum[m.missing_type] << 2
        host.threshold_real = thr_real
        host.decision_type = dtype_bits
        host.cat_value_to_bin = cat_maps

    def rollback_one_iter(self) -> None:
        """ref: gbdt.cpp:463 RollbackOneIter."""
        if self.iter <= 0:
            return
        K = self.num_tree_per_iteration
        for k in range(K):
            t = self.models[len(self.models) - K + k]
            # subtract contribution from train & valid scores
            self.score = self.score.at[k].add(
                -self._tree_outputs(t, self.bins_dev))
            for vd in self.valid_sets:
                vd.score = vd.score.at[k].add(
                    -self._tree_outputs(t, vd.bins_dev))
        del self.models[-K:]
        self.iter -= 1

    # ------------------------------------------------------------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        return self._eval(self.train_metrics, self.score, "training")

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vd in self.valid_sets:
            out.extend(self._eval(vd.metrics, vd.score, vd.name))
        return out

    def init_from_model(self, other: "GBDT") -> None:
        """Continued training from an existing model (ref: CLI input_model,
        boosting.h:305 Boosting::CreateBoosting(filename) then continue)."""
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            log.fatal("Cannot continue training: num_tree_per_iteration "
                      "differs between the init model and this config")
        K = self.num_tree_per_iteration
        self.models = [t.copy() for t in other.models]
        self.num_init_iteration = len(self.models) // max(K, 1)
        # trees loaded from model text carry ORIGINAL feature indices and
        # real thresholds; rebind them to this dataset's inner indices/bins
        inner_of = {int(orig): i for i, orig in
                    enumerate(self.train_set.used_feature_map)}
        mappers = self.train_set.bin_mappers
        for t in self.models:
            if not getattr(t, "from_text", False):
                continue
            for i in range(t.num_leaves - 1):
                f = int(t.split_feature[i])
                if f not in inner_of:
                    log.fatal(f"init model splits on feature {f} which is "
                              "trivial/absent in the new training data")
                t.split_feature_inner[i] = inner_of[f]
                m = mappers[f]
                if m.bin_type == "numerical":
                    t.threshold_bin[i] = int(
                        m.value_to_bin(np.asarray([t.threshold_real[i]]))[0])
                else:
                    t.threshold_bin[i] = int(t.threshold_real[i])
            t.from_text = False
        for i, t in enumerate(self.models):
            k = i % K
            self.score = self.score.at[k].add(
                self._tree_outputs(t, self.bins_dev))
            for vd in self.valid_sets:
                vd.score = vd.score.at[k].add(
                    self._tree_outputs(t, vd.bins_dev))

    def _eval(self, metrics, score, data_name):
        out = []
        score_np = np.asarray(score, np.float64)
        view = score_np[0] if self.num_tree_per_iteration == 1 else score_np
        for m in metrics:
            for name, value, hib in m.eval(view, self.objective):
                out.append((data_name, name, value, hib))
        return out

    # ------------------------------------------------------------------
    @property
    def num_iterations_trained(self) -> int:
        return self.iter

    def current_iteration(self) -> int:
        return len(self.models) // max(self.num_tree_per_iteration, 1)
