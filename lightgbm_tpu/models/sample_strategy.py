"""Row sampling strategies: bagging and GOSS.

TPU-native equivalent of the reference SampleStrategy layer
(ref: include/LightGBM/sample_strategy.h:24 factory,
src/boosting/bagging.hpp:15 BaggingSampleStrategy,
src/boosting/goss.hpp:19 GOSSStrategy).

Where the reference produces a permuted index array (`bag_data_indices_`) fed
to DataPartition, the TPU formulation produces per-row mask/weight vectors
multiplied into (grad, hess, count) before the histogram pass — same math,
no dynamic shapes. ``weight`` carries GOSS's small-gradient amplification
(1-a)/b; ``selected`` is the 0/1 membership used for histogram counts so
min_data_in_leaf keeps its bagged-count meaning.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..config import Config
from ..utils import log


class SampleStrategy:
    """Base: no sampling."""

    # whether sample() reads grad/hess. Bagging decides from RNG alone, so
    # the caller can skip the device->host gradient pull entirely (each
    # pull is a full [K, N] transfer through the device tunnel per iter)
    needs_grad = False

    def __init__(self, config: Config, num_data: int,
                 num_tree_per_iteration: int = 1):
        self.config = config
        self.num_data = num_data
        self.num_tree_per_iteration = num_tree_per_iteration

    def reset_config(self, config: Config) -> None:
        self.config = config

    def sample(self, it: int, grad: Optional[np.ndarray] = None,
               hess: Optional[np.ndarray] = None
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Return (selected[N] 0/1 f32, weight[N] f32) or None for no-op."""
        return None

    def is_hessian_change(self) -> bool:
        return False

    @staticmethod
    def create(config: Config, num_data: int, num_tree_per_iteration: int,
               metadata=None) -> "SampleStrategy":
        """ref: sample_strategy.cpp SampleStrategy::CreateSampleStrategy."""
        if str(config.data_sample_strategy).lower() == "goss":
            return GOSSStrategy(config, num_data, num_tree_per_iteration)
        return BaggingStrategy(config, num_data, num_tree_per_iteration,
                               metadata)


class BaggingStrategy(SampleStrategy):
    """ref: bagging.hpp:15. Re-samples every ``bagging_freq`` iterations;
    supports balanced bagging (pos/neg fractions) and query-level bagging."""

    def __init__(self, config: Config, num_data: int,
                 num_tree_per_iteration: int = 1, metadata=None):
        super().__init__(config, num_data, num_tree_per_iteration)
        self.rng = np.random.default_rng(config.bagging_seed)
        self.metadata = metadata
        self._cached: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self.balanced = (
            config.pos_bagging_fraction < 1.0 or
            config.neg_bagging_fraction < 1.0)
        self.need_bagging = (
            (config.bagging_freq > 0 and config.bagging_fraction < 1.0)
            or self.balanced)
        if self.need_bagging:
            log.info("Using bagging, bagging_fraction="
                     f"{config.bagging_fraction}")

    def sample_dev(self, it, grad=None, hess=None, key=None):
        """Opt-in device bagging (tpu_device_bagging): per-row keep with
        probability bagging_fraction from the stateless key chain. The
        key is derived from the RESAMPLE iteration (it - it % freq), so
        the mask is identical across a bagging_freq window and BOTH the
        async and sync paths re-derive it (train_one_iter consults
        sample_dev in either mode when the opt-in is on) — a stop-check
        rollback replay therefore reproduces the exact mask. At least
        one row is always kept (the host path's max(1, cnt) analogue).
        Returns None (host fallback) for the balanced / by-query
        variants and when the opt-in is off; approximate fraction vs
        the host path's exact-count subset (documented in config.py)."""
        cfg = self.config
        if (not getattr(cfg, "tpu_device_bagging", False) or
                not self.need_bagging or self.balanced or
                cfg.bagging_by_query):
            return None
        import jax
        import jax.numpy as jnp
        freq = max(cfg.bagging_freq, 1)
        kit = it - it % freq
        cached = getattr(self, "_dev_cached", None)
        if cached is not None and cached[0] == kit:
            return cached[1]
        k = jax.random.fold_in(key, kit)
        u = jax.random.uniform(k, (self.num_data,))
        sel = u < cfg.bagging_fraction
        # an unlucky draw must not produce an empty bag: the row with
        # the smallest uniform is the most-likely-kept row — forcing it
        # distorts the distribution minimally
        sel = sel.at[jnp.argmin(u)].set(True)
        sel = sel.astype(jnp.float32)
        self._dev_cached = (kit, (sel, sel))
        return sel, sel

    def sample(self, it, grad=None, hess=None):
        cfg = self.config
        if not self.need_bagging:
            return None
        freq = max(cfg.bagging_freq, 1)
        if it % freq != 0 and self._cached is not None:
            return self._cached
        n = self.num_data
        if self.balanced and self.metadata is not None and \
                self.metadata.label is not None:
            pos = self.metadata.label > 0
            sel = np.zeros(n, np.float32)
            sel[pos] = (self.rng.random(int(pos.sum())) <
                        cfg.pos_bagging_fraction)
            sel[~pos] = (self.rng.random(int((~pos).sum())) <
                         cfg.neg_bagging_fraction)
        elif cfg.bagging_by_query and self.metadata is not None and \
                self.metadata.query_boundaries is not None:
            qb = self.metadata.query_boundaries
            nq = len(qb) - 1
            take = self.rng.random(nq) < cfg.bagging_fraction
            sel = np.zeros(n, np.float32)
            for q in np.flatnonzero(take):
                sel[qb[q]:qb[q + 1]] = 1.0
        else:
            cnt = max(1, int(n * cfg.bagging_fraction))
            idx = self.rng.choice(n, size=cnt, replace=False)
            sel = np.zeros(n, np.float32)
            sel[idx] = 1.0
        self._cached = (sel, sel)
        return self._cached


class GOSSStrategy(SampleStrategy):
    """Gradient-based one-side sampling (ref: goss.hpp:19): keep the top
    ``top_rate`` rows by sum_k |g_k * h_k|, randomly keep ``other_rate`` of
    the rest with g/h amplified by (n - top_k)/other_k. Starts after
    1/learning_rate iterations (ref: goss.hpp:33)."""

    needs_grad = True

    def __init__(self, config: Config, num_data: int,
                 num_tree_per_iteration: int = 1):
        super().__init__(config, num_data, num_tree_per_iteration)
        if not (config.top_rate > 0 and config.other_rate > 0):
            log.fatal("GOSS requires top_rate > 0 and other_rate > 0")
        if config.top_rate + config.other_rate > 1.0:
            log.fatal("top_rate + other_rate must be <= 1.0 for GOSS")
        if config.bagging_freq > 0 and config.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS")
        self.rng = np.random.default_rng(config.bagging_seed)

    def is_hessian_change(self):
        return True

    def _policy(self, it):
        """Shared scalar GOSS policy (ref: goss.hpp:19-45): returns
        (top_k, other_k, multiply) or None during the 1/learning_rate
        warmup. The single source for BOTH the host and device samplers
        so the policy cannot drift between them."""
        cfg = self.config
        if it < int(1.0 / cfg.learning_rate):
            return None
        n = self.num_data
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        return top_k, other_k, (n - top_k) / other_k

    def sample_dev(self, it, grad, hess, key):
        """Device-side GOSS for the async fast path: the _policy
        computed entirely on device (lax top-k threshold + jax RNG keep
        mask), so gradient-based sampling never pulls [K, N] gradients
        through the host. The keep mask uses the stateless jax key
        chain instead of the host Generator — an equally valid GOSS
        draw, but not bit-identical to the sync path's numpy sampling
        (both honor bagging_seed). One jitted dispatch per call.
        Returns (selected, weight) device arrays or None in warmup."""
        pol = self._policy(it)
        if pol is None:
            return None
        top_k, other_k, multiply = pol
        if not hasattr(self, "_dev_jit"):
            import jax
            import jax.numpy as jnp

            def draw(grad, hess, key, top_k, other_k, multiply):
                n = grad.shape[-1]
                g = jnp.sum(jnp.abs(grad * hess), axis=0)    # [N]
                threshold = jax.lax.top_k(g, top_k)[0][-1]
                is_top = g >= threshold
                rest = ~is_top
                n_rest = jnp.maximum(
                    jnp.sum(rest.astype(jnp.int32)), 1)
                keep_prob = jnp.minimum(
                    1.0, other_k / n_rest.astype(jnp.float32))
                sampled = rest & (jax.random.uniform(key, (n,)) <
                                  keep_prob)
                sel = (is_top | sampled).astype(jnp.float32)
                weight = jnp.where(sampled, jnp.float32(multiply),
                                   1.0) * sel
                return sel, weight

            self._dev_jit = jax.jit(draw,
                                    static_argnames=("top_k", "other_k",
                                                     "multiply"))
        return self._dev_jit(grad, hess, key, top_k=top_k,
                             other_k=other_k, multiply=multiply)

    def sample(self, it, grad=None, hess=None):
        pol = self._policy(it)
        if pol is None:
            return None
        top_k, other_k, multiply = pol
        n = self.num_data
        # grad/hess may be [K, N]; rank by sum over classes of |g*h|
        g = np.abs(np.asarray(grad, np.float64) * np.asarray(hess, np.float64))
        if g.ndim == 2:
            g = g.sum(axis=0)
        threshold = np.partition(g, n - top_k)[n - top_k]
        is_top = g >= threshold
        rest = ~is_top
        n_rest = int(rest.sum())
        keep_prob = min(1.0, other_k / max(n_rest, 1))
        sampled = rest & (self.rng.random(n) < keep_prob)
        sel = (is_top | sampled).astype(np.float32)
        weight = np.where(sampled, multiply, 1.0).astype(np.float32) * sel
        return sel, weight
