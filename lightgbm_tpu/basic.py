"""User-facing Dataset and Booster.

TPU-native equivalent of python-package/lightgbm/basic.py (5251 LoC,
ref: Dataset basic.py:1692, Booster :3495, update :4005, predict :4625,
_InnerPredictor :907). There is no C ABI to cross — the "C API layer"
(ref: src/c_api.cpp Booster wrapper) collapses into direct Python calls into
the jitted engine, which is the idiomatic JAX shape of the same design.
"""
from __future__ import annotations

import copy
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .config import Config, _ConfigAliases
from .core.metrics import Metric, metrics_for_config
from .core.objective import CustomObjective, create_objective
from .io.dataset_core import BinnedDataset
from .models import create_boosting
from .utils import log

__all__ = ["Dataset", "Booster", "LightGBMError"]


class LightGBMError(Exception):
    """Error raised by the framework (ref: basic.py LightGBMError)."""


def _to_2d_numpy(data) -> Tuple[np.ndarray, Optional[List[str]]]:
    """Accept numpy / pandas / list-of-lists; return (float64 2-D, names)."""
    names = None
    if hasattr(data, "values") and hasattr(data, "columns"):  # pandas
        names = [str(c) for c in data.columns]
        data = data.values
    arr = np.asarray(data)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    if arr.dtype.kind not in "fiub":
        arr = arr.astype(np.float64)
    return np.ascontiguousarray(arr, dtype=np.float64), names


def _to_1d_numpy(data, dtype=np.float32) -> np.ndarray:
    if _has_arrow_c_stream(data):     # e.g. polars Series
        data = _arrow_chunked_from_c(data)
    if _is_arrow_array(data):
        data = data.to_numpy(zero_copy_only=False)
    elif hasattr(data, "values"):
        data = data.values
    return np.ascontiguousarray(np.asarray(data).reshape(-1), dtype=dtype)


def _hstack_any(a, b):
    """Column-concatenate two raw-data containers of possibly different
    types (dense/list/pandas/scipy); None when no sensible merge exists."""
    if _is_scipy_sparse(a) or _is_scipy_sparse(b):
        import scipy.sparse as sp
        if _is_scipy_sparse(a) and _is_scipy_sparse(b):
            return sp.hstack([a, b], format="csr")
        return None
    if hasattr(a, "columns") and hasattr(b, "columns"):  # both pandas
        import pandas as pd
        return pd.concat([a.reset_index(drop=True),
                          b.reset_index(drop=True)], axis=1)
    try:
        aa, bb = np.asarray(a), np.asarray(b)
        if aa.ndim == 2 and bb.ndim == 2 and aa.shape[0] == bb.shape[0]:
            return np.hstack([aa, bb])
    except Exception:
        pass
    return None


def _is_sequence_input(data) -> bool:
    from .io.sequence import Sequence
    if isinstance(data, Sequence):
        return True
    return (isinstance(data, (list, tuple)) and len(data) > 0 and
            all(isinstance(s, Sequence) for s in data))


def _is_scipy_sparse(data) -> bool:
    try:
        import scipy.sparse as sp
    except ImportError:
        return False
    return sp.issparse(data)


def _is_arrow_table(data) -> bool:
    try:
        import pyarrow as pa
    except ImportError:
        return False
    return isinstance(data, (pa.Table, pa.RecordBatch))


def _has_arrow_c_stream(data) -> bool:
    """Arrow PyCapsule protocol producer that is not already handled.

    Covers polars DataFrames/Series (ref: the reference's polars
    ingestion rides the same Arrow C interface,
    tests/python_package_test/test_polars.py) and any other producer of
    ``__arrow_c_stream__``. pandas also implements the capsule protocol
    on recent versions but keeps its dedicated path (detected first via
    .values/.columns); pyarrow objects keep theirs.
    """
    return (hasattr(data, "__arrow_c_stream__") and
            not (hasattr(data, "values") and hasattr(data, "columns")) and
            not isinstance(data, np.ndarray) and
            not _is_arrow_table(data) and not _is_arrow_array(data))


def _arrow_table_from_c(data):
    """Materialize a capsule-protocol producer as a pyarrow Table."""
    try:
        import pyarrow as pa
    except ImportError as e:
        raise LightGBMError(
            "this input implements the Arrow C-stream protocol (e.g. a "
            "polars DataFrame); ingesting it requires pyarrow") from e
    return pa.table(data)


def _arrow_chunked_from_c(data):
    """Materialize a 1-D capsule-protocol producer (e.g. polars Series)."""
    try:
        import pyarrow as pa
    except ImportError as e:
        raise LightGBMError(
            "this input implements the Arrow C-stream protocol (e.g. a "
            "polars Series); ingesting it requires pyarrow") from e
    return pa.chunked_array(data)


def _is_arrow_array(data) -> bool:
    try:
        import pyarrow as pa
    except ImportError:
        return False
    return isinstance(data, (pa.Array, pa.ChunkedArray))


class Dataset:
    """Training data wrapper with lazy construction
    (ref: basic.py:1692 Dataset, _lazy_init :2037)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, List[str]] = "auto",
                 categorical_feature: Union[str, List] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True, position=None):
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.position = position
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = copy.deepcopy(params) if params else {}
        self.free_raw_data = free_raw_data
        self._binned: Optional[BinnedDataset] = None
        self.used_indices: Optional[np.ndarray] = None
        self.version = 0

    # -- construction ---------------------------------------------------
    def _update_params(self, params) -> "Dataset":
        """Fill dataset params from booster params before construction
        (ref: Dataset._update_params, python-package basic.py — dataset
        keys keep precedence; no-op once constructed)."""
        if self._binned is None and params:
            for k, v in params.items():
                self.params.setdefault(k, v)
        return self

    def _finish_prebinned(self) -> "Dataset":
        """Apply explicit metadata overrides to an already-binned dataset
        (binary-file and two_round loading exits)."""
        md = self._binned.metadata
        if self.label is not None:
            md.set_label(_to_1d_numpy(self.label))
        if self.weight is not None:
            md.set_weight(_to_1d_numpy(self.weight))
        if self.group is not None:
            md.set_query(_to_1d_numpy(self.group, np.int64))
        if self.init_score is not None:
            md.set_init_score(_to_1d_numpy(self.init_score, np.float64))
        if self.position is not None:
            md.set_position(_to_1d_numpy(self.position, np.int32))
        if self.free_raw_data:
            self.data = None
        return self

    def construct(self) -> "Dataset":
        if self._binned is not None:
            return self
        if self.reference is not None:
            ref_binned = self.reference.construct()._binned
        else:
            ref_binned = None

        if self.used_indices is not None and self.reference is not None:
            # subset path (ref: Dataset.subset basic.py)
            base = self.reference.construct()._binned
            self._binned = base.subset(self.used_indices)
            if self.label is not None:
                self._binned.metadata.set_label(_to_1d_numpy(self.label))
            return self

        if isinstance(self.data, (str, Path)):
            from .io.binary_io import is_binary_dataset_file, load_binary
            if is_binary_dataset_file(str(self.data)):
                from .io.dataset_core import _resolve_shard_world
                if self.reference is None and \
                        _resolve_shard_world(Config(self.params)) is not None:
                    log.fatal(
                        "binary dataset files cannot be shard-ingested "
                        "(pre_partition=true / tpu_ingest='sharded'): a "
                        ".bin file is already binned with its own global "
                        "mappers, so distributed bin finding cannot run "
                        "and per-host .bin files at the same path would "
                        "desync the SPMD program — load the raw data "
                        "with per-rank files ('...{rank}...'), or set "
                        "tpu_ingest='replicated'")
                self._binned = load_binary(str(self.data))
                return self._finish_prebinned()
            cfg = Config(self.params)
            if cfg.two_round:
                from .io.dataset_core import _resolve_shard_world
                if self.reference is None and \
                        _resolve_shard_world(cfg) is not None:
                    log.fatal(
                        "two_round=true is incompatible with sharded "
                        "ingestion (pre_partition=true / "
                        "tpu_ingest='sharded'): the two-pass streaming "
                        "loader reads the GLOBAL file on every rank, so "
                        "the O(rows/world) host-memory contract would "
                        "not hold — use per-rank files "
                        "('...{rank}...') without two_round, or set "
                        "tpu_ingest='replicated'")
                # streaming two-pass load: bounded memory, binned in place
                # (ref: dataset_loader.cpp:266 two_round branch)
                from .io.stream_loader import load_binned_two_round
                self._binned = load_binned_two_round(
                    str(self.data), cfg,
                    categorical_feature=self.categorical_feature,
                    reference=ref_binned)
                return self._finish_prebinned()
            from .io.dataset_core import _resolve_shard_world
            from .io.file_loader import load_position_file, load_svm_or_csv
            # shard-load ONLY the training table: datasets built with
            # reference= (validation sets) take the replicated
            # construction path, so slicing their file here would
            # silently hand each rank a different partial valid set
            sw = (_resolve_shard_world(cfg)
                  if self.reference is None else None)
            X, y, w, grp = load_svm_or_csv(
                str(self.data), cfg,
                rank=sw[0] if sw else None,
                world=sw[1] if sw else None)
            if self.label is None:
                self.label = y
            if self.weight is None:
                self.weight = w
            if self.group is None:
                self.group = grp
            if self.position is None:
                from .io.file_loader import resolve_rank_path
                ppath, per_rank = resolve_rank_path(
                    str(self.data), sw[0] if sw else None)
                self.position = load_position_file(ppath)
                if (self.position is not None and sw is not None
                        and not per_rank
                        and len(self.position) != len(X)):
                    # shared-file row-slice mode: a full-length
                    # .position sidecar gets this shard's rows, the
                    # same treatment the .weight sidecar receives in
                    # load_svm_or_csv. Cut with the shared shard
                    # convention and re-check the length: a sidecar
                    # whose row count disagrees with the data file
                    # yields a wrong-length slice on at least one rank
                    # (the slice lengths sum to the sidecar's count,
                    # the shards to the data file's), so at least one
                    # rank dies loudly here instead of training on
                    # shifted positions; its peers then fail their
                    # first ingest collective within the retry-policy
                    # deadline (launch_local's watchdog reaps the gang
                    # immediately)
                    from .distributed import row_slice
                    rank, world = sw
                    lo, hi = row_slice(len(self.position), rank, world)
                    if hi - lo != len(X):
                        log.fatal(
                            f"{ppath}: position sidecar has "
                            f"{len(self.position)} entries but the data "
                            f"file's rank {rank}/{world} row slice holds "
                            f"{len(X)} rows — the sidecar must have "
                            "exactly one entry per data-file row")
                    self.position = self.position[lo:hi]
            data, inferred_names = X, None
        elif _is_sequence_input(self.data):
            from .io.sequence import build_from_sequences
            from .io.stream_loader import _resolve_categoricals
            seqs = (list(self.data) if isinstance(self.data, (list, tuple))
                    else [self.data])
            cfg = Config(self.params)
            names = ([str(f) for f in self.feature_name]
                     if isinstance(self.feature_name, list) else None)
            cats = _resolve_categoricals(self.categorical_feature, cfg,
                                         names)
            self._binned = build_from_sequences(
                seqs, cfg, categorical_features=cats, reference=ref_binned,
                feature_names=names)
            return self._finish_prebinned()
        elif _is_scipy_sparse(self.data):
            from .io.dataset_core import SparseColumns
            data, inferred_names = SparseColumns(self.data), None
        elif _is_arrow_table(self.data) or _has_arrow_c_stream(self.data):
            from .io.dataset_core import ArrowColumns
            if _has_arrow_c_stream(self.data):   # e.g. polars DataFrame
                self.data = _arrow_table_from_c(self.data)
            data = ArrowColumns(self.data)
            inferred_names = data.column_names()
        else:
            data, inferred_names = _to_2d_numpy(self.data)

        cfg = Config(self.params)
        feature_names = None
        if isinstance(self.feature_name, list):
            feature_names = [str(f) for f in self.feature_name]
        elif inferred_names is not None:
            feature_names = inferred_names

        cats: List[int] = []
        if isinstance(self.categorical_feature, (list, tuple)):
            for c in self.categorical_feature:
                if isinstance(c, int):
                    cats.append(c)
                elif feature_names and c in feature_names:
                    cats.append(feature_names.index(c))
        elif cfg.categorical_feature:
            cats = [int(c) for c in str(cfg.categorical_feature).split(",")
                    if c.strip() != ""]

        label = _to_1d_numpy(self.label) if self.label is not None else None
        weight = _to_1d_numpy(self.weight) if self.weight is not None else None
        group = (_to_1d_numpy(self.group, np.int64)
                 if self.group is not None else None)
        init_score = (_to_1d_numpy(self.init_score, np.float64)
                      if self.init_score is not None else None)
        position = (_to_1d_numpy(self.position, np.int32)
                    if self.position is not None else None)

        from .io.dataset_core import ColumnSource
        builder = (BinnedDataset.from_columns
                   if isinstance(data, ColumnSource)
                   else BinnedDataset.from_matrix)
        self._binned = builder(
            data, cfg, label=label, weight=weight, group=group,
            init_score=init_score, position=position,
            feature_names=feature_names, categorical_features=cats,
            reference=ref_binned)
        if self.free_raw_data:
            self.data = None
        return self

    # -- setters (ref: set_field paths) ---------------------------------
    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """Change the categorical features (ref: basic.py
        Dataset.set_categorical_feature): a no-op when unchanged;
        otherwise the dataset re-bins on next construct (requires the
        raw data to still be around)."""
        if self.categorical_feature == categorical_feature:
            return self
        if self._binned is not None:
            if self.data is None:
                raise LightGBMError(
                    "Cannot set categorical feature after freeing raw "
                    "data; set free_raw_data=False when constructing "
                    "the Dataset")
            from .utils import log
            log.warning("categorical_feature changed after construction; "
                        "the dataset will be re-binned")
            self._binned = None
        self.categorical_feature = categorical_feature
        return self

    def set_label(self, label) -> "Dataset":
        self.label = label
        if self._binned is not None:
            if label is None:
                self._binned.metadata.label = None   # unset, like set_field
            else:
                self._binned.metadata.set_label(_to_1d_numpy(label))
        return self

    def set_weight(self, weight) -> "Dataset":
        self.weight = weight
        if self._binned is not None:
            self._binned.metadata.set_weight(
                _to_1d_numpy(weight) if weight is not None else None)
        return self

    def set_group(self, group) -> "Dataset":
        self.group = group
        if self._binned is not None:
            self._binned.metadata.set_query(
                _to_1d_numpy(group, np.int64) if group is not None else None)
        return self

    def set_init_score(self, init_score) -> "Dataset":
        self.init_score = init_score
        if self._binned is not None:
            self._binned.metadata.set_init_score(
                _to_1d_numpy(init_score, np.float64)
                if init_score is not None else None)
        return self

    def get_label(self):
        if self._binned is not None:
            return self._binned.metadata.label
        return self.label

    def get_weight(self):
        if self._binned is not None:
            return self._binned.metadata.weight
        return self.weight

    def get_group(self):
        if self._binned is not None and \
                self._binned.metadata.query_boundaries is not None:
            return np.diff(self._binned.metadata.query_boundaries)
        return self.group

    def get_init_score(self):
        if self._binned is not None:
            return self._binned.metadata.init_score
        return self.init_score

    def num_data(self) -> int:
        if self._binned is not None:
            return self._binned.num_data
        if self.data is not None and hasattr(self.data, "shape"):
            return int(self.data.shape[0])
        raise LightGBMError("Dataset not constructed")

    def num_feature(self) -> int:
        if self._binned is not None:
            return self._binned.num_total_features
        if self.data is not None and hasattr(self.data, "shape"):
            return int(self.data.shape[1])
        raise LightGBMError("Dataset not constructed")

    def set_position(self, position) -> "Dataset":
        self.position = position
        if self._binned is not None:
            self._binned.metadata.set_position(
                _to_1d_numpy(position, np.int32)
                if position is not None else None)
        return self

    def get_position(self):
        if self._binned is not None:
            return self._binned.metadata.position
        return self.position

    # generic field access (ref: basic.py Dataset.set_field/get_field)
    _FIELDS = {"label": ("set_label", "get_label"),
               "weight": ("set_weight", "get_weight"),
               "group": ("set_group", "get_group"),
               "init_score": ("set_init_score", "get_init_score"),
               "position": ("set_position", "get_position")}

    def set_field(self, field_name: str, data) -> "Dataset":
        if field_name not in self._FIELDS:
            raise LightGBMError(f"Unknown field name: {field_name}")
        getattr(self, self._FIELDS[field_name][0])(data)
        return self

    def get_field(self, field_name: str):
        if field_name not in self._FIELDS:
            raise LightGBMError(f"Unknown field name: {field_name}")
        if self._binned is None:
            # ref: basic.py get_field raises before construction
            raise LightGBMError("Cannot get fields before construct Dataset")
        if field_name == "group":
            # the FIELD is the cumulative boundaries array (ref: basic.py
            # get_field('group') -> [0, n1, n1+n2, ...]); get_group()
            # returns the per-query sizes
            return self._binned.metadata.query_boundaries
        return getattr(self, self._FIELDS[field_name][1])()

    def get_data(self):
        """The raw data this Dataset was built from (None once freed by
        free_raw_data=True construction, like the reference)."""
        return self.data

    def get_feature_name(self) -> List[str]:
        return list(self.construct()._binned.feature_names)

    def set_feature_name(self, feature_name) -> "Dataset":
        if feature_name is not None and feature_name != "auto":
            names = [str(f) for f in feature_name]
            self.feature_name = names
            if self._binned is not None:
                if len(names) != self._binned.num_total_features:
                    raise LightGBMError(
                        f"Length of feature names ({len(names)}) does not "
                        "equal the number of features "
                        f"({self._binned.num_total_features})")
                self._binned.feature_names = names
        return self

    def feature_num_bin(self, feature: Union[int, str]) -> int:
        """Number of bins of one feature (ref: basic.py feature_num_bin /
        LGBM_DatasetGetFeatureNumBin)."""
        binned = self.construct()._binned
        if isinstance(feature, str):
            if feature not in binned.feature_names:
                raise LightGBMError(f"Unknown feature name: {feature!r}")
            feature = binned.feature_names.index(feature)
        return int(binned.bin_mappers[int(feature)].num_bin)

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """Bin this dataset in ``reference``'s bin space
        (ref: basic.py set_reference — merges the reference's dataset
        params first, no-ops on the same reference, and refuses to change
        it after construction)."""
        self._update_params(reference.params)
        if self.reference is reference:
            return self
        if self._binned is not None:
            raise LightGBMError(
                "Cannot set reference after the dataset was constructed")
        self.reference = reference
        return self

    def get_ref_chain(self, ref_limit: int = 100) -> set:
        """The chain of reference datasets (ref: basic.py get_ref_chain)."""
        head = self
        ref_chain: set = set()
        while len(ref_chain) < ref_limit:
            if isinstance(head, Dataset):
                ref_chain.add(head)
                if head.reference is not None and \
                        head.reference not in ref_chain:
                    head = head.reference
                else:
                    break
            else:
                break
        return ref_chain

    def get_params(self) -> Dict[str, Any]:
        """The dataset-relevant parameters this Dataset carries
        (ref: basic.py get_params returns the _PARAMETER_ALIASES subset)."""
        return copy.deepcopy(self.params)

    def add_features_from(self, other: "Dataset") -> "Dataset":
        """Append ``other``'s features to this dataset in place
        (ref: basic.py add_features_from / Dataset::AddFeaturesFrom —
        both datasets must be constructed with the same row count; this
        dataset keeps its metadata)."""
        a = self.construct()._binned
        b = other.construct()._binned
        if a.num_data != b.num_data:
            raise LightGBMError(
                f"Cannot add features from a dataset with {b.num_data} "
                f"rows to one with {a.num_data} rows")
        off = a.num_total_features
        a.ensure_logical_bins()
        b.ensure_logical_bins()
        a.bin_mappers = list(a.bin_mappers) + list(b.bin_mappers)
        a.used_feature_map = np.concatenate(
            [a.used_feature_map, b.used_feature_map + off]).astype(np.int32)
        if a.bins is not None and b.bins is not None:
            dtype = (np.uint16 if (a.bins.dtype == np.uint16 or
                                   b.bins.dtype == np.uint16) else np.uint8)
            a.bins = np.concatenate([a.bins.astype(dtype),
                                     b.bins.astype(dtype)], axis=0)
        a.num_total_features += b.num_total_features
        # de-duplicate colliding default names like the reference warns
        merged = list(a.feature_names) + list(b.feature_names)
        if len(set(merged)) != len(merged):
            merged = (list(a.feature_names) +
                      [f"D{off + i}_{n}"
                       for i, n in enumerate(b.feature_names)])
        a.feature_names = merged
        if a.raw is not None and b.raw is not None:
            a.raw = np.concatenate([a.raw, b.raw], axis=1)
        else:
            a.raw = None
        a.max_bin = max(a.max_bin, b.max_bin)
        # keep Dataset-level state consistent with the merged binned view
        # (ref: add_features_from concatenates self.data or drops it)
        if self.data is not None and other.data is not None:
            self.data = _hstack_any(self.data, other.data)
            if self.data is None:
                log.warning("Cannot merge raw data of these input types "
                            "after add_features_from; raw data dropped")
            elif (hasattr(self.data, "columns") and
                  len(a.feature_names) == self.data.shape[1]):
                # keep columns aligned with the (possibly deduped) names
                self.data.columns = list(a.feature_names)
        elif self.data is not None:
            log.warning("Cannot keep raw data after add_features_from "
                        "(the other dataset was constructed with "
                        "free_raw_data=True)")
            self.data = None
        self.feature_name = list(a.feature_names)
        return self

    def subset(self, used_indices: Sequence[int],
               params: Optional[Dict] = None) -> "Dataset":
        """Row subset sharing this dataset's bin mappers
        (ref: basic.py Dataset.subset / Dataset::CopySubrow)."""
        ret = Dataset(None, reference=self,
                      params=params or self.params,
                      free_raw_data=self.free_raw_data)
        ret.used_indices = np.asarray(sorted(used_indices), dtype=np.int64)
        return ret

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None, position=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       params=params or self.params, position=position)

    def save_binary(self, filename) -> "Dataset":
        """Serialize the constructed (binned) dataset to a binary file
        (ref: Dataset::SaveBinaryFile, dataset.h:710)."""
        from .io.binary_io import save_binary
        save_binary(self.construct()._binned, str(filename))
        return self

    @property
    def binned(self) -> BinnedDataset:
        self.construct()
        return self._binned


class _InnerPredictor:
    """Prediction init-score provider for continued training
    (ref: basic.py:907 _InnerPredictor)."""

    def __init__(self, booster: "Booster"):
        self.booster = booster

    def predict_init_score(self, dataset: Dataset) -> np.ndarray:
        binned = dataset.binned
        # raw prediction over the ORIGINAL raw matrix is unavailable after
        # binning; use the binned prediction path instead
        raw = self.booster._predict_binned_raw(binned)
        return raw.astype(np.float64).reshape(-1)


class Booster:
    """The trained model handle (ref: basic.py:3495 Booster,
    src/c_api.cpp:170 Booster wrapper)."""

    def __init__(self, params: Optional[Dict[str, Any]] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = copy.deepcopy(params) if params else {}
        self.train_set: Optional[Dataset] = None
        self.valid_sets: List[Dataset] = []
        self.name_valid_sets: List[str] = []
        self._engine = None
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self.train_data_name = "training"
        self._network_initialized = False

        if train_set is not None:
            self._init_from_train_set(train_set)
        elif model_file is not None:
            from .io.model_io import load_model_file
            self._engine, self.config = load_model_file(str(model_file))
        elif model_str is not None:
            from .io.model_io import load_model_string
            self._engine, self.config = load_model_string(model_str)
        else:
            raise LightGBMError(
                "need at least one of train_set, model_file, model_str")

    def _init_from_train_set(self, train_set: Dataset) -> None:
        if not isinstance(train_set, Dataset):
            raise LightGBMError("train_set must be a Dataset")
        self.train_set = train_set
        merged = dict(train_set.params)
        merged.update(self.params)
        self.config = Config(merged)
        train_set._update_params(self.params)
        binned = train_set.construct().binned
        obj_name = self.config.objective
        objective = create_objective(obj_name, self.config)
        self._engine = create_boosting(self.config, binned, objective)
        self._train_metrics = metrics_for_config(self.config, objective.NAME)
        self._engine.add_train_metrics(self._train_metrics)

    # -- training -------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if self._engine is None or self.train_set is None:
            raise LightGBMError("Booster has no training data")
        data._update_params(self.params)
        data.construct()
        self.valid_sets.append(data)
        self.name_valid_sets.append(name)
        self._engine.add_valid_data(data.binned, name=name)
        return self

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting round (ref: basic.py:4005 update). Returns True if
        no further splits were possible (training finished)."""
        if train_set is not None and train_set is not self.train_set:
            raise LightGBMError("Replacing train_set is not supported yet")
        if fobj is None:
            return self._engine.train_one_iter()
        grad, hess = fobj(self._raw_train_score(), self.train_set)
        grad = np.asarray(grad, np.float32)
        hess = np.asarray(hess, np.float32)
        return self._engine.train_one_iter(grad, hess)

    def _raw_train_score(self) -> np.ndarray:
        s = np.asarray(self._engine.score, np.float64)
        return s[0] if s.shape[0] == 1 else s

    def refit(self, data, label, decay_rate: float = 0.9, weight=None,
              group=None, init_score=None, **kwargs) -> "Booster":
        """Refit the existing tree structures on new data: tree shapes and
        thresholds are kept, leaf values are re-estimated from the new
        data's gradient statistics and blended with the old values by
        ``decay_rate`` (ref: basic.py Booster.refit -> LGBM_BoosterRefit;
        gbdt.cpp GBDT::RefitTree with refit_decay_rate)."""
        from .core.objective import create_objective
        from .io.dataset_core import Metadata as _Metadata
        from .io.model_io import load_model_string
        from .ops.split import SplitHyperParams, calculate_splitted_leaf_output

        X, _ = _to_2d_numpy(data)
        y = _to_1d_numpy(label)
        n = X.shape[0]

        # fresh engine carrying only the model (no training state)
        new_engine, new_config = load_model_string(self.model_to_string())
        new_config.update({k: v for k, v in kwargs.items()})
        cfg = new_config

        md = _Metadata(n)
        md.set_label(y)
        if weight is not None:
            md.set_weight(_to_1d_numpy(weight))
        if group is not None:
            md.set_query(_to_1d_numpy(group, np.int64))
        objective = create_objective(cfg.objective, cfg)
        objective.init(md, n)

        hp = SplitHyperParams(
            lambda_l1=cfg.lambda_l1, lambda_l2=cfg.lambda_l2,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            min_gain_to_split=cfg.min_gain_to_split,
            max_delta_step=cfg.max_delta_step)

        K = new_engine.num_tree_per_iteration
        n_iter = len(new_engine.models) // max(K, 1)
        score = np.zeros((K, n), np.float64)
        if init_score is not None:
            score += np.asarray(init_score, np.float64).reshape(-1, n)

        import jax.numpy as jnp
        for it in range(n_iter):
            s_dev = jnp.asarray(score, jnp.float32)
            g, h = objective.get_gradients(s_dev[0] if K == 1 else s_dev)
            g = np.asarray(g, np.float64).reshape(K, n)
            h = np.asarray(h, np.float64).reshape(K, n)
            for k in range(K):
                t = new_engine.models[it * K + k]
                if t.num_leaves <= 1:
                    score[k] += t.leaf_value[0] if len(t.leaf_value) else 0.0
                    continue
                leaf = t.predict_leaf(X)
                sum_g = np.bincount(leaf, weights=g[k],
                                    minlength=t.num_leaves)
                sum_h = np.bincount(leaf, weights=h[k],
                                    minlength=t.num_leaves)
                new_val = np.asarray(calculate_splitted_leaf_output(
                    jnp.asarray(sum_g), jnp.asarray(sum_h), hp), np.float64)
                new_val *= t.shrinkage
                # leaves with no rows in the new data keep their old value
                # (ref: gbdt.cpp RefitTree only updates populated leaves)
                has_data = sum_h > 0
                t.leaf_value = np.where(
                    has_data,
                    decay_rate * t.leaf_value + (1.0 - decay_rate) * new_val,
                    t.leaf_value)
                score[k] += t.leaf_value[leaf]

        out = Booster.__new__(Booster)
        out.params = copy.deepcopy(self.params)
        out.train_set = None
        out.valid_sets = []
        out.name_valid_sets = []
        out.best_iteration = -1
        out.best_score = {}
        out.train_data_name = "training"
        out._network_initialized = False
        out._engine = new_engine
        out.config = cfg
        return out

    def rollback_one_iter(self) -> "Booster":
        self._engine.rollback_one_iter()
        return self

    def serve(self, fleet=None, tenant=None, **kwargs) -> "ModelServer":
        """Start a concurrent model server over this booster (ISSUE 8/9,
        serving/server.py): a dynamic micro-batcher coalesces concurrent
        ``submit()`` requests into the packed-forest engine's compiled
        row buckets, the pack is replicated over the serving mesh with
        request batches sharded across it, and ``ModelServer.publish()``
        hot-swaps newly trained trees into the live server with zero
        downtime. The failure path is built in: per-request deadlines
        (expired requests dropped before coalescing), fail-fast
        admission control (``OVERLOADED`` on a full queue),
        retry-then-degrade dispatch that falls back to the host walk and
        probes the device in the background, and publish rollback (a
        failed publish keeps serving the old generation). Knobs default
        from the ``tpu_serving_*`` params; kwargs (``max_batch``,
        ``linger_ms``, ``num_devices``, ``queue_depth``, ``raw_score``,
        ``bucket``, ``deadline_ms``, ``max_queue_rows``,
        ``retry_policy``, ``probe_interval_s``) override.

        Multi-tenant fleet serving (ISSUE 13): ``serve(fleet=server)``
        registers this booster as one TENANT of an existing
        :class:`FleetServer` (``tenant=`` names it; default
        ``tenant<N>``) and returns a :class:`TenantHandle` — one shared
        dispatcher, device arena and trace budget for the whole fleet
        instead of a server per model. Per-tenant kwargs there:
        ``deadline_ms``, ``quota_rows``, ``raw_score``.

        A booster has at most ONE live solo server: calling ``serve()``
        again while one is open returns the live server (kwarg-less
        call) or refuses loudly (a kwarg'd call cannot be honored
        without a second dispatcher over the same pack — the bug class
        this guard exists to kill). A closed server is replaced."""
        if fleet is not None:
            if tenant is None:
                # probe for a free default name: len() alone collides
                # once any tenant was removed
                i = len(fleet.tenants)
                while f"tenant{i}" in fleet.tenants:
                    i += 1
                tenant = f"tenant{i}"
            return fleet.add_tenant(tenant, self, **kwargs)
        live = getattr(self, "_live_server", None)
        if live is not None and not live.closed:
            if kwargs:
                raise LightGBMError(
                    "this Booster already has a live ModelServer; a "
                    "second serve() with different knobs would spawn a "
                    "second dispatcher thread over the same pack. Use "
                    "the existing server (serve() with no kwargs "
                    "returns it) or close() it first.")
            log.warning("serve(): returning this Booster's live "
                        "ModelServer (one dispatcher per booster)")
            return live
        from .serving import ModelServer
        srv = ModelServer(self, **kwargs)
        self._live_server = srv
        return srv

    @property
    def current_iteration(self):
        return self._engine.current_iteration

    def num_trees(self) -> int:
        return len(self._engine.models)

    def num_model_per_iteration(self) -> int:
        return self._engine.num_tree_per_iteration

    @property
    def num_class_(self) -> int:
        return self._engine.num_tree_per_iteration

    # -- evaluation -----------------------------------------------------
    def eval(self, data: "Dataset", name: str, feval=None):
        """Evaluate on a previously-registered dataset (ref: basic.py:4245
        Booster.eval — the data must be the training set or one added via
        add_valid, like the reference's data_idx lookup)."""
        if data is self.train_set:
            return [(name, n, v, h)
                    for _d, n, v, h in self.eval_train(feval)]
        for vs, vname in zip(self.valid_sets, self.name_valid_sets):
            if data is vs:
                return [(name, n, v, h)
                        for n_d, n, v, h in self.eval_valid(feval)
                        if n_d == vname]
        raise LightGBMError(
            "Data for eval must be the training set or have been added "
            "with add_valid")

    def eval_train(self, feval=None):
        results = self._engine.eval_train()
        out = [(d, n, v, h) for d, n, v, h in results]
        if feval is not None:
            out.extend(self._run_feval(feval, "training", self.train_set,
                                       self._raw_train_score()))
        return out

    def eval_valid(self, feval=None):
        results = self._engine.eval_valid()
        out = [(d, n, v, h) for d, n, v, h in results]
        if feval is not None:
            for i, (vs, name) in enumerate(
                    zip(self.valid_sets, self.name_valid_sets)):
                score = np.asarray(self._engine.valid_sets[i].score,
                                   np.float64)
                sv = score[0] if score.shape[0] == 1 else score
                out.extend(self._run_feval(feval, name, vs, sv))
        return out

    def _run_feval(self, feval, data_name, dataset, raw_score):
        fevals = feval if isinstance(feval, (list, tuple)) else [feval]
        out = []
        for f in fevals:
            ret = f(raw_score, dataset)
            if isinstance(ret, list):
                for name, value, hib in ret:
                    out.append((data_name, name, value, hib))
            else:
                name, value, hib = ret
                out.append((data_name, name, value, hib))
        return out

    # -- prediction -----------------------------------------------------
    def predict(self, data, start_iteration: int = 0,
                num_iteration: Optional[int] = None,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, validate_features: bool = False,
                **kwargs) -> np.ndarray:
        """ref: basic.py:4625 Booster.predict -> Predictor (predictor.hpp).
        ``data`` may also be a text file path (CSV/TSV/LibSVM), like the
        reference; ``data_has_header=True`` in kwargs skips its header."""
        if isinstance(data, (str, Path)):
            from .io.file_loader import load_svm_or_csv
            # parse prediction files with the SAME column schema as
            # training (weight/group/ignore columns and aliases included)
            pcfg = dict(self.params)
            pcfg["header"] = bool(kwargs.get("data_has_header", False))
            data, _, _, _ = load_svm_or_csv(str(data), Config(pcfg))
            n_feat_model = self._engine.max_feature_idx + 1
            if data.shape[1] < n_feat_model:
                # LibSVM files legitimately omit trailing all-zero
                # features; size to the model like the reference parser
                data = np.concatenate(
                    [data, np.zeros((data.shape[0],
                                     n_feat_model - data.shape[1]))],
                    axis=1)
        if _is_scipy_sparse(data):
            # Row-blocked sparse prediction (≡ PredictForCSR's row-wise
            # iteration, c_api.cpp — never densify the full matrix): each
            # block densifies at most ~256 MB and reuses the dense path,
            # so wide-sparse inputs don't hit a memory cliff.
            import scipy.sparse as sp
            csr = data.tocsr()
            n_rows = csr.shape[0]
            block = int(kwargs.get(
                "predict_sparse_block_rows",
                max(1024, (1 << 25) // max(csr.shape[1], 1))))
            if n_rows > block:
                outs = [
                    self.predict(
                        csr[i:i + block], start_iteration=start_iteration,
                        num_iteration=num_iteration, raw_score=raw_score,
                        pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                        validate_features=validate_features, **kwargs)
                    for i in range(0, n_rows, block)
                ]
                if pred_contrib:
                    return sp.vstack(outs, format="csr")
                return np.concatenate(outs, axis=0)
            if pred_contrib:
                # sparse input -> sparse SHAP output (≡ the reference's
                # PredictSparseCSR contrib path, c_api.cpp — most
                # contributions of wide-sparse rows are exactly zero)
                dense = self.predict(
                    csr.toarray().astype(np.float64),
                    start_iteration=start_iteration,
                    num_iteration=num_iteration, raw_score=raw_score,
                    pred_leaf=False, pred_contrib=True,
                    validate_features=validate_features, **kwargs)
                return sp.csr_matrix(dense)
            X = csr.toarray().astype(np.float64)
        elif _is_arrow_table(data) or _has_arrow_c_stream(data):
            from .io.dataset_core import ArrowColumns
            if _has_arrow_c_stream(data):        # e.g. polars DataFrame
                data = _arrow_table_from_c(data)
            X = ArrowColumns(data).to_dense_f32().astype(np.float64)
        else:
            X, _ = _to_2d_numpy(data)
        disable_check = bool(kwargs.get(
            "predict_disable_shape_check",
            self.params.get("predict_disable_shape_check", False)))
        n_feat = self._engine.max_feature_idx + 1
        if X.shape[1] != n_feat and not disable_check:
            # ref: config predict_disable_shape_check + Predictor's fatal
            raise LightGBMError(
                f"The number of features in data ({X.shape[1]}) is not the "
                f"same as it was in training data ({n_feat}).\nYou can set "
                "predict_disable_shape_check=true to discard this error, "
                "but please be aware what you are doing.")
        if X.shape[1] < n_feat:
            # disabled check: the reference's Predictor zero-initializes
            # its per-row buffer, so absent trailing features read as 0.0
            # (predictor.hpp) — match that, not the NaN/missing routing
            X = np.concatenate(
                [X, np.zeros((X.shape[0], n_feat - X.shape[1]))], axis=1)
        eng = self._engine
        K = eng.num_tree_per_iteration
        n_total_iter = len(eng.models) // max(K, 1)
        if num_iteration is None or num_iteration < 0:
            num_iteration = (self.best_iteration
                             if self.best_iteration > 0 else n_total_iter)
        end_iteration = min(start_iteration + num_iteration, n_total_iter)

        if pred_leaf:
            out = np.zeros((X.shape[0], (end_iteration - start_iteration) * K),
                           dtype=np.int64)
            col = 0
            for it in range(start_iteration, end_iteration):
                for k in range(K):
                    t = eng.models[it * K + k]
                    out[:, col] = t.predict_leaf(X)
                    col += 1
            return out

        if pred_contrib:
            from .core.shap import predict_contrib
            # opt-in device explanation (predict(..., pred_contrib=True,
            # device=True)) through the packed SHAP path tensors
            # (ops/shap_pack.py, ISSUE 20): f32 EXTEND/UNWIND on device,
            # within f32-accumulation tolerance of the f64 host walk.
            # Linear trees / categorical splits / raw f64-only requests
            # fall back to the host walk LOUDLY ONCE per model — silent
            # per-call WARNING spam would drown serving logs, silence
            # would hide that the device never served.
            if kwargs.get("device",
                          self.params.get("tpu_predict_device", False)):
                try:
                    return eng.explain_device(X, start_iteration,
                                              end_iteration)
                except ValueError as e:
                    from .utils import log
                    log.info_once(
                        f"device explanation unavailable ({e}); using "
                        "the host predict_contrib walk")
            return predict_contrib(eng, X, start_iteration, end_iteration)

        # prediction early stopping (ref: src/boosting/
        # prediction_early_stop.cpp + gbdt_prediction.cpp:16 PredictRaw):
        # every `freq` iterations rows whose margin clears the threshold
        # stop accumulating further trees. binary margin = 2|p|;
        # multiclass margin = top1 - top2.
        es = bool(kwargs.get("pred_early_stop",
                             self.params.get("pred_early_stop", False)))
        es_freq = int(kwargs.get("pred_early_stop_freq",
                                 self.params.get("pred_early_stop_freq", 10)))
        es_margin = float(kwargs.get(
            "pred_early_stop_margin",
            self.params.get("pred_early_stop_margin", 10.0)))
        obj_name = getattr(eng.objective, "NAME", "") if eng.objective \
            else ""
        es = es and not raw_score and (K > 1 or obj_name == "binary")

        # opt-in device prediction (predict(..., device=True)) through the
        # packed-forest serving engine (ops/forest.py): device binning +
        # depth-bounded batched traversal — split-exact vs the host walk
        # (thresholds ARE bin boundaries). Models without in-session
        # mappers (loaded from file) serve over raw thresholds; linear
        # trees, raw categorical bitsets, empty ranges and prediction
        # early stop fall back to the host path. On success `raw` falls
        # through to the shared output tail.
        raw = None
        use_device = kwargs.get(
            "device", self.params.get("tpu_predict_device", False))
        if (use_device and not es):
            try:
                raw = eng.predict_device(X, start_iteration, end_iteration)
            except ValueError as e:
                from .utils import log
                log.warning(f"device prediction unavailable ({e}); "
                            "using the host path")

        if raw is None:
            raw = np.zeros((X.shape[0], K), dtype=np.float64)
            active = np.ones(X.shape[0], bool) if es else None
            Xa = X
            rounds_since_check = 0
            for it in range(start_iteration, end_iteration):
                for k in range(K):
                    t = eng.models[it * K + k]
                    if active is None:
                        raw[:, k] += t.predict(X)
                    elif len(Xa):
                        raw[active, k] += t.predict(Xa)
                if active is not None:
                    rounds_since_check += 1
                    if rounds_since_check == es_freq:
                        rounds_since_check = 0
                        if K > 1:
                            part = np.partition(raw, K - 2, axis=1)
                            margin = part[:, K - 1] - part[:, K - 2]
                        else:
                            margin = 2.0 * np.abs(raw[:, 0])
                        active &= margin <= es_margin
                        Xa = X[active]
        if getattr(eng, "average_output", False) and end_iteration > 0:
            raw /= (end_iteration - start_iteration)
        if not raw_score and eng.objective is not None:
            if K > 1:
                raw = eng.objective.convert_output(raw)
            else:
                raw[:, 0] = np.asarray(
                    eng.objective.convert_output(raw[:, 0]))
        return raw[:, 0] if K == 1 else raw

    def _predict_binned_raw(self, binned: BinnedDataset) -> np.ndarray:
        """Raw scores over an already-binned dataset (init-score path)."""
        import jax.numpy as jnp
        eng = self._engine
        K = eng.num_tree_per_iteration
        bins_dev = jnp.asarray(binned.ensure_logical_bins()
                               if binned.bins is None else binned.bins)
        score = np.zeros((K, binned.num_data), np.float64)
        for i, t in enumerate(eng.models):
            k = i % K
            score[k] += np.asarray(eng._tree_outputs(t, bins_dev, binned.raw))
        return score

    # -- model IO -------------------------------------------------------
    def save_model(self, filename, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split",
                   atomic: bool = False) -> "Booster":
        """``atomic=True`` routes through the crash-safe writer
        (robustness/checkpoint.py: tmp + fsync + rename) — a kill
        mid-write can never leave a torn model file."""
        from .io.model_io import save_model_file
        save_model_file(self._engine, self.config, str(filename),
                        num_iteration=num_iteration,
                        start_iteration=start_iteration,
                        importance_type=importance_type,
                        atomic=atomic)
        return self

    def model_to_string(self, num_iteration: Optional[int] = None,
                        start_iteration: int = 0,
                        importance_type: str = "split") -> str:
        from .io.model_io import model_to_string
        return model_to_string(self._engine, self.config,
                               num_iteration=num_iteration,
                               start_iteration=start_iteration,
                               importance_type=importance_type)

    def dump_model(self, num_iteration: Optional[int] = None,
                   start_iteration: int = 0,
                   importance_type: str = "split") -> Dict:
        from .io.model_io import dump_model_dict
        return dump_model_dict(self._engine, self.config,
                               num_iteration=num_iteration,
                               start_iteration=start_iteration,
                               importance_type=importance_type)

    def model_from_string(self, model_str: str) -> "Booster":
        """Replace this handle's model with one parsed from a string
        (ref: basic.py Booster.model_from_string)."""
        from .io.model_io import load_model_string
        self._engine, self.config = load_model_string(model_str)
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        self.train_data_name = name
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """ref: Booster.get_leaf_output / LGBM_BoosterGetLeafValue."""
        return float(self._engine.models[tree_id].leaf_value[leaf_id])

    def set_leaf_output(self, tree_id: int, leaf_id: int,
                        value: float) -> "Booster":
        """ref: Booster.set_leaf_output / Tree::SetLeafOutput."""
        t = self._engine.models[tree_id]
        t.leaf_value = np.asarray(t.leaf_value, np.float64).copy()
        t.leaf_value[leaf_id] = float(value)
        self._engine.invalidate_serving_cache()  # in-place content edit
        return self

    def trees_to_dataframe(self):
        """Flatten the model into a pandas DataFrame, one row per node
        (ref: basic.py Booster.trees_to_dataframe column schema)."""
        import pandas as pd

        names = self.feature_name()
        rows = []
        for tree_idx, t in enumerate(self._engine.models):

            def node_row(parent, depth, is_leaf, idx):
                if is_leaf:
                    return {
                        "tree_index": tree_idx, "node_depth": depth,
                        "node_index": f"{tree_idx}-L{idx}",
                        "left_child": None, "right_child": None,
                        "parent_index": parent, "split_feature": None,
                        "split_gain": None, "threshold": None,
                        "decision_type": None, "missing_direction": None,
                        "missing_type": None,
                        "value": float(t.leaf_value[idx]),
                        "weight": float(t.leaf_weight[idx]),
                        "count": int(t.leaf_count[idx])}
                f = int(t.split_feature[idx])
                is_cat = bool(t.decision_type[idx] & 1)
                dl = bool(t.decision_type[idx] & 2)
                mtype = (int(t.decision_type[idx]) >> 2) & 3
                if is_cat:
                    # the reference emits the ||-joined category values;
                    # threshold_real of a cat node is its cat_boundaries
                    # index (core/tree.py:263)
                    thr = "||".join(
                        str(v)
                        for v in t.cat_values(int(t.threshold_real[idx])))
                else:
                    thr = float(t.threshold_real[idx])
                return {
                    "tree_index": tree_idx, "node_depth": depth,
                    "node_index": f"{tree_idx}-S{idx}",
                    "left_child": None, "right_child": None,
                    "parent_index": parent,
                    "split_feature": names[f] if f < len(names) else f,
                    "split_gain": float(t.split_gain[idx]),
                    "threshold": thr,
                    "decision_type": "==" if is_cat else "<=",
                    "missing_direction": "left" if dl else "right",
                    "missing_type": ["None", "Zero", "NaN"][mtype],
                    "value": float(t.internal_value[idx]),
                    "weight": float(t.internal_weight[idx]),
                    "count": int(t.internal_count[idx])}

            if t.num_leaves <= 1:
                rows.append(node_row(None, 1, True, 0))
                continue

            # explicit stack — leaf-wise trees can be num_leaves deep
            stack = [(0, None, 1)]
            while stack:
                node, parent, depth = stack.pop()
                if node < 0:
                    rows.append(node_row(parent, depth, True, ~node))
                    continue
                row = node_row(parent, depth, False, node)
                rows.append(row)
                me = row["node_index"]
                lc, rc = int(t.left_child[node]), int(t.right_child[node])
                row["left_child"] = (f"{tree_idx}-S{lc}" if lc >= 0
                                     else f"{tree_idx}-L{~lc}")
                row["right_child"] = (f"{tree_idx}-S{rc}" if rc >= 0
                                      else f"{tree_idx}-L{~rc}")
                # push right first so the left subtree is emitted first
                stack.append((rc, me, depth + 1))
                stack.append((lc, me, depth + 1))
        return pd.DataFrame(rows)

    # -- introspection --------------------------------------------------
    def feature_name(self) -> List[str]:
        return list(self._engine.feature_names)

    def num_feature(self) -> int:
        return self._engine.max_feature_idx + 1

    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        """ref: gbdt.cpp FeatureImportance."""
        eng = self._engine
        n = eng.max_feature_idx + 1
        out = np.zeros(n, np.float64)
        K = eng.num_tree_per_iteration
        limit = (len(eng.models) if iteration is None
                 else min(iteration * K, len(eng.models)))
        for t in eng.models[:limit]:
            for i in range(t.num_leaves - 1):
                f = int(t.split_feature[i])
                if importance_type == "split":
                    if t.split_gain[i] > 0:
                        out[f] += 1.0
                else:
                    out[f] += max(t.split_gain[i], 0.0)
        if importance_type == "split":
            return out.astype(np.int64)  # counts, like the reference
        return out

    def get_split_value_histogram(self, feature, bins=None,
                                  xgboost_style: bool = False):
        """Histogram of REAL threshold values used for `feature` across
        all trees (ref: basic.py:5044 get_split_value_histogram /
        c_api.cpp BoosterGetLeafValue..GetSplitValueHistogram role)."""
        eng = self._engine
        if isinstance(feature, str):
            if feature not in eng.feature_names:
                raise LightGBMError(f"Unknown feature name {feature!r}")
            feature = eng.feature_names.index(feature)
        values = []
        for t in eng.models:
            for i in range(t.num_leaves - 1):
                if (int(t.split_feature[i]) == feature and
                        not (t.decision_type[i] & 1)):  # numerical only
                    values.append(float(t.threshold_real[i]))
        values = np.asarray(values, np.float64)
        if bins is None or (isinstance(bins, str) and bins == "auto"):
            n_unique = len(np.unique(values))
            bins = max(min(n_unique, 10), 1) if len(values) else 1
        hist, edges = np.histogram(values, bins=bins)
        if xgboost_style:
            ret = np.column_stack((edges[1:], hist))
            return ret[ret[:, 1] > 0]
        return hist, edges

    def shuffle_models(self, start_iteration: int = 0,
                       end_iteration: int = -1) -> "Booster":
        """Randomly permute the trees of the given iteration window
        (ref: basic.py:4416 shuffle_models; used before refit)."""
        eng = self._engine
        eng.invalidate_serving_cache()  # packed forest is order-sensitive
        K = eng.num_tree_per_iteration
        n_iter = len(eng.models) // max(K, 1)
        end = n_iter if end_iteration <= 0 else min(end_iteration, n_iter)
        idx = np.arange(start_iteration, end)
        if len(idx) > 1:
            perm = np.random.permutation(idx)
            blocks = [eng.models[i * K:(i + 1) * K] for i in range(n_iter)]
            reordered = list(blocks)
            for dst, src in zip(idx, perm):
                reordered[dst] = blocks[src]
            eng.models = [t for b in reordered for t in b]
        return self

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """Map the reference's socket network config (basic.py:3725) onto
        the jax.distributed world: the first machine acts as coordinator.
        Prefer lightgbm_tpu.distributed.init_distributed directly — it
        also needs this process' rank, which the machine list alone does
        not determine."""
        from .utils import log
        if num_machines <= 1:
            log.warning("set_network with num_machines<=1 is a no-op")
            return self
        log.warning(
            "set_network: use lightgbm_tpu.distributed.init_distributed("
            f"coordinator_address=..., num_processes={num_machines}, "
            "process_id=<rank>) — the machine list alone cannot "
            "determine this process' rank; no network was configured")
        return self

    def lower_bound(self) -> float:
        eng = self._engine
        vals = [t.leaf_value.min() for t in eng.models if t.num_leaves >= 1]
        return float(sum(vals)) if vals else 0.0

    def upper_bound(self) -> float:
        eng = self._engine
        vals = [t.leaf_value.max() for t in eng.models if t.num_leaves >= 1]
        return float(sum(vals)) if vals else 0.0

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """ref: Booster::ResetConfig (c_api.cpp)."""
        self.params.update(params)
        self.config.update(params)
        self._engine.config = self.config
        self._engine.shrinkage_rate = float(self.config.learning_rate)
        if hasattr(self._engine, "sample_strategy"):
            self._engine.sample_strategy.reset_config(self.config)
        return self

    def __copy__(self):
        # ref: Booster.__copy__ delegates to __deepcopy__ — a copy is an
        # independent serving handle, never an alias
        return self.__deepcopy__(None)

    # -- pickling (ref: basic.py Booster.__getstate__/__setstate__:
    # the live engine holds jitted closures and device buffers, so the
    # pickled form carries the model TEXT; unpickling yields a serving
    # handle, exactly like the reference) ------------------------------
    def __getstate__(self):
        state = self.__dict__.copy()
        # _live_server (ISSUE 13): a ModelServer holds locks, a queue
        # and a dispatcher thread — unpicklable and meaningless in a
        # copy; the unpickled booster simply has no live server
        for heavy in ("_engine", "train_set", "valid_sets",
                      "_train_metrics", "_live_server"):
            state.pop(heavy, None)
        state["_model_str"] = (self.model_to_string()
                               if self._engine is not None else None)
        return state

    def __setstate__(self, state):
        model_str = state.pop("_model_str", None)
        self.__dict__.update(state)
        self.train_set = None
        self.valid_sets = []
        self._engine = None
        if model_str is not None:
            self.model_from_string(model_str)

    def __deepcopy__(self, memo):
        out = type(self).__new__(type(self))
        if memo is not None:
            memo[id(self)] = out
        out.__setstate__(copy.deepcopy(self.__getstate__(), memo or {}))
        return out

    def free_dataset(self) -> "Booster":
        self.train_set = None
        self.valid_sets = []
        return self

    def free_network(self) -> "Booster":
        self._network_initialized = False
        return self
