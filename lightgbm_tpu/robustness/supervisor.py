"""Heartbeat-aware child supervision (ISSUE 4 tentpole, parent side).

Replaces the blind wall-clock slots in bench.py and
scripts/tpu_session_auto.py with phase-aware liveness deadlines over the
heartbeat protocol (robustness/heartbeat.py):

- a child whose heartbeats advance (phase change, progress change, or a
  live keepalive within its phase's stall budget) is NEVER killed or
  parked before the hard deadline — a multi-minute XLA compile that
  keeps beating is benign, not wedged;
- a child silent past ``silent_sec``, or sitting in one phase past that
  phase's ``stall_sec``, is classified hung: the supervisor asks it to
  exit (SIGTERM — Python cleanup still runs), waits a grace period, and
  raises :class:`DeviceStallError` (transient under the shared
  RetryPolicy, so the caller's retry loop relaunches — with the
  persistent compile cache warm, the relaunch skips the compile that
  spent the first attempt);
- a child still alive AND advancing at the hard deadline raises
  :class:`StillAlive` — the caller parks it (leaves it running, skips
  further claims), exactly the no-SIGKILL wedge discipline from
  docs/TPU_RUNBOOK.md. SIGKILL is never sent: the mid-compile
  claim-holder kill is the documented machine-wide wedge trigger that
  zeroed BENCH_r03-r05.

No jax import in this module; importing it through the package root
does import jax (module import only — safe), but a supervisor must
never run a jax op or initialize a backend: backend init is what hangs
on a wedged tunnel.
"""
from __future__ import annotations

import os
import subprocess
import time
from typing import Callable, Optional

from ..utils import log
from .heartbeat import (ALIVE, SILENT, STALLED, WAITING,  # noqa: F401
                        DeviceStallError, EXIT_STALLED, HeartbeatRecord,
                        StallPolicy, read)

__all__ = ["DeviceStallError", "StallPolicy", "StillAlive",
            "watch_child", "EXIT_STALLED", "terminate_gently"]


class StillAlive(Exception):
    """The hard deadline passed with the child alive and NOT classified
    hung. The caller must park it (leave it running, make no further
    device claims) — never kill it."""

    def __init__(self, msg: str, pid: int):
        super().__init__(msg)
        self.pid = pid


def watch_child(proc: subprocess.Popen, hb_path: str,
                policy: Optional[StallPolicy] = None,
                hard_deadline: Optional[float] = None,
                poll: float = 1.0,
                label: str = "child",
                term_grace: float = 15.0,
                clock: Callable[[], float] = time.monotonic,
                sleep: Callable[[float], None] = time.sleep,
                on_status: Optional[Callable[[str, Optional[
                    HeartbeatRecord]], None]] = None,
                relay=None) -> int:
    """Supervise ``proc`` against its heartbeat file until it exits.

    Returns the child's return code. Raises:

    - :class:`DeviceStallError` when the child is classified hung
      (silent past ``policy.silent_sec``, one phase past its
      ``stall_sec``, or it self-exited with :data:`EXIT_STALLED`). The
      child is SIGTERMed first and given ``term_grace`` seconds; if it
      refuses to die it is left running (noted in the message) — the
      caller's retry decision still stands, but no SIGKILL is ever
      sent.
    - :class:`StillAlive` when ``hard_deadline`` (monotonic, same clock)
      passes while the child is alive and NOT hung — the caller parks.

    A child that never heartbeats at all (uninstrumented) is governed by
    ``startup_grace`` then ``silent_sec`` like any wedged child — every
    supervised entry point in this repo installs the heartbeat before
    its first device touch, so "no file" past the grace means wedged
    imports/backend init, which retrying also fixes more often than
    waiting does.

    ``relay``: an optional :class:`~.heartbeat.Heartbeat` of THIS
    process; every observed child advance is re-beaten onto it, so
    supervision composes hierarchically (the session supervisor sees a
    bench parent as alive exactly as long as the bench's grandchild is).
    """
    policy = policy if policy is not None else StallPolicy.from_env()
    started = clock()
    stall_started: Optional[float] = None
    last_verdict = WAITING
    last_rec: Optional[HeartbeatRecord] = None
    while True:
        rc = proc.poll()
        now = clock()
        if rc is not None:
            if rc == EXIT_STALLED:
                raise DeviceStallError(
                    f"{label} (pid={proc.pid}) self-watchdogged: its "
                    "training loop was wedged at a device sync and it "
                    f"exited rc={EXIT_STALLED}")
            return rc
        rec = read(hb_path)
        if relay is not None and rec is not None and \
                rec.advanced_over(last_rec):
            relay.beat(rec.phase, rec.progress)
        last_rec = rec
        verdict = policy.classify(rec, now, started)
        if verdict != last_verdict:
            if on_status is not None:
                on_status(verdict, rec)
            last_verdict = verdict
        if verdict in (STALLED, SILENT):
            if stall_started is None:
                stall_started = now
            # one extra poll interval of hysteresis: a beat landing
            # between our read and the verdict must not kill an attempt
            if now - stall_started >= poll:
                phase = rec.phase if rec is not None else "<no heartbeat>"
                detail = (
                    f"{label} (pid={proc.pid}) classified hung: "
                    f"{verdict} in phase {phase!r} "
                    f"(beat age {now - rec.t:.0f}s, keepalive age "
                    f"{now - rec.ka:.0f}s)" if rec is not None else
                    f"{label} (pid={proc.pid}) classified hung: no "
                    f"heartbeat {now - started:.0f}s after launch")
                terminate_gently(proc, term_grace, label)
                raise DeviceStallError(detail)
        else:
            stall_started = None
        if hard_deadline is not None and now >= hard_deadline and \
                verdict not in (STALLED, SILENT):
            # only a NOT-hung child parks; one already classified
            # SILENT/STALLED but still inside the hysteresis window
            # finishes classification on the next poll (bounded
            # deadline overrun of ~poll) and earns the SIGTERM + retry
            # instead of a false "advancing" park
            raise StillAlive(
                f"{label} (pid={proc.pid}) alive (verdict {verdict}) "
                "at the hard deadline; parking — no kill",
                pid=proc.pid)
        sleep(poll)


def terminate_gently(proc: subprocess.Popen, grace: float,
                      label: str) -> None:
    """SIGTERM + bounded wait; NEVER SIGKILL (wedge discipline). A child
    that ignores SIGTERM is left running and noted — it was already
    classified hung, and a SIGKILL there risks the machine-wide wedge."""
    try:
        proc.terminate()
    except OSError:
        return
    try:
        proc.wait(timeout=max(grace, 1.0))
    except subprocess.TimeoutExpired:
        log.warning(
            f"{label} (pid={proc.pid}) ignored SIGTERM for {grace:.0f}s; "
            "leaving it running (no SIGKILL — wedge discipline)")
