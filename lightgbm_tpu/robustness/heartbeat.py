"""Phase-tagged liveness heartbeats (ISSUE 4 tentpole).

Rounds 3-5 of the bench all reported 0.0/``device_unreachable`` while
unattended sessions measured 3.1-9.9 it/s the same round: the
supervisors enforced blind wall-clock slots and could not tell a benign
multi-minute XLA compile from a truly hung dispatch, so they parked
healthy children and repaid the full compile on every retry. This module
is the TPU-native equivalent of the reference's distributed liveness
layer (socket timeouts + rank heartbeats in ``src/network/``), applied
to a single flaky accelerator in the spirit of Dean & Barroso's
tail-tolerance techniques (PAPERS.md):

- **Writer** (:class:`Heartbeat`): instrumented children — the gbdt
  training loop, bench measurement children, session stages — append
  phase-tagged beats (``compiling`` / ``warmup`` / ``measuring`` /
  ``iter`` + progress counter, monotonic timestamp, pid) to a
  crash-safe single-line-rewrite file (tmp + ``os.replace``; a torn or
  half-written line is unreadable, never wrong). A daemon keepalive
  thread refreshes a separate ``ka`` timestamp so "process alive" and
  "loop advancing" are independently observable.
- **Reader** (:func:`read`, :class:`StallPolicy`): supervisors replace
  fixed slots with phase-aware liveness deadlines. A child whose phase/
  progress advances is never parked; a child whose keepalive went
  silent, or whose phase sat unchanged past that phase's ``stall_sec``,
  is classified hung (:class:`DeviceStallError` — its message carries
  ``DEADLINE_EXCEEDED`` so the existing retry classifier treats it as
  transient).
- **In-child watchdog** (:class:`TrainingWatchdog`, driven from
  models/gbdt.py): monitors the *in-memory* age of the training loop's
  last beat attempt — a main thread wedged inside a device sync stops
  calling :meth:`Heartbeat.beat`, the watchdog raises the process out
  of the hang (interrupt, then a hard exit with :data:`EXIT_STALLED`)
  instead of letting it block forever. Injected ``hang`` faults
  suppress only the *writes* (the file goes silent for the supervisor)
  while beat *calls* continue, so the harness exercises the supervisor
  path, not the self-watchdog.

Timestamps are ``time.monotonic()`` — on Linux that is CLOCK_MONOTONIC,
which is system-wide, so writer and supervisor clocks are directly
comparable across processes. ``wall`` (epoch seconds) rides along for
humans reading the file.

No jax import anywhere in this module. Note the hazard boundary
precisely: importing the *package* (``lightgbm_tpu.robustness``) does
import jax at module level via the package root — which is safe — but
supervisors must never run a jax operation or touch devices, because
BACKEND INITIALIZATION is what can hang on a wedged tunnel (the bench
parent has shipped this way since the retry runtime landed).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..utils import log
from . import faults

ENV_HEARTBEAT = "LGBM_TPU_HEARTBEAT"
# default per-phase stall budget override (seconds, applies to every
# phase without a more specific env); per-phase:
# LGBM_TPU_STALL_SEC_COMPILING etc.
ENV_STALL = "LGBM_TPU_STALL_SEC"
ENV_STALL_EXIT = "LGBM_TPU_STALL_EXIT"
# keepalive refresh cadence (seconds); tests shrink it so silence is
# detectable in seconds instead of a minute
ENV_KEEPALIVE = "LGBM_TPU_HEARTBEAT_KA"

PHASE_COMPILING = "compiling"
PHASE_WARMUP = "warmup"
PHASE_MEASURING = "measuring"
PHASE_ITER = "iter"
# sharded-ingest construction (dataset_core._from_columns_sharded):
# beaten per protocol step (counts / summaries / mappers / binning /
# metadata) so a gang supervisor can tell a rank grinding through a big
# allgather from one wedged on a dead peer
PHASE_INGEST = "ingest"


def rank_path(path: str, rank: int) -> str:
    """Per-rank heartbeat file for gang workers: the supervisor exports
    ONE base path (``LGBM_TPU_HEARTBEAT``) and every rank writes
    ``base.r<rank>`` — the shared convention between the gang
    supervisor (robustness/gang.py), models/gbdt.py's install, the
    sharded-ingest constructor, and the bench ingest children."""
    return f"{path}.r{int(rank)}"

# exit code of a self-watchdogged child: the supervisor maps it to the
# same DeviceStallError classification a silent child earns
EXIT_STALLED = 86


@dataclasses.dataclass(frozen=True)
class HeartbeatRecord:
    """One parsed heartbeat line."""

    phase: str
    progress: int          # iteration / step counter within the phase
    t: float               # monotonic ts of the last SUBSTANTIVE beat
    ka: float              # monotonic ts of the last keepalive refresh
    pid: int
    seq: int               # total substantive beats written
    wall: float            # epoch seconds (for humans/logs only)

    def advanced_over(self, prev: Optional["HeartbeatRecord"]) -> bool:
        """True when this record shows loop progress over ``prev``
        (phase change, progress change, or a fresh substantive beat)."""
        if prev is None:
            return True
        return (self.phase != prev.phase or
                self.progress != prev.progress or
                self.seq != prev.seq)


def read(path: str) -> Optional[HeartbeatRecord]:
    """Parse the heartbeat file; None on missing/torn/garbage content.

    Torn-write tolerance is the reader's job: the writer's tmp+replace
    makes torn lines rare, but a reader must survive a file caught
    mid-create, truncated by a dying fs, or plain corrupted — any
    parse/shape failure reads as "no heartbeat", never as a crash."""
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            line = f.read()
    except OSError:
        return None
    line = line.strip()
    if not line:
        return None
    try:
        d = json.loads(line)
        return HeartbeatRecord(
            phase=str(d["phase"]), progress=int(d["progress"]),
            t=float(d["t"]), ka=float(d["ka"]), pid=int(d["pid"]),
            seq=int(d["seq"]), wall=float(d.get("wall", 0.0)))
    except (ValueError, KeyError, TypeError):
        return None


class Heartbeat:
    """Crash-safe single-line heartbeat writer.

    ``beat(phase, progress)`` is the substantive signal (refreshes
    ``t``); the keepalive thread refreshes only ``ka``. Both rewrite
    the whole line atomically (tmp + ``os.replace``) so a reader never
    sees a torn record — and a crash between beats loses at most the
    final beat, which is exactly the information a crash invalidates.

    The injected ``hang`` fault (faults.py) suppresses writes from the
    moment it fires — including keepalives — while leaving the
    in-memory beat bookkeeping (``last_attempt``) running, so the
    supervisor sees a silent child while the child itself keeps
    "working" (see module docstring).
    """

    def __init__(self, path: str, pid: Optional[int] = None,
                 keepalive_interval: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.path = path
        self.pid = pid if pid is not None else os.getpid()
        self.keepalive_interval = float(keepalive_interval)
        self.clock = clock
        self.phase = ""
        self.progress = 0
        self.seq = 0
        self.last_beat = clock()       # last substantive WRITE (t field)
        self.last_attempt = clock()    # last beat() CALL (in-memory only)
        self._hung = False             # injected hang fired: stop writing
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._ka_thread: Optional[threading.Thread] = None
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)

    # -- writing -------------------------------------------------------
    def _write(self, t: float, ka: float) -> None:
        if self._hung:
            return
        rec = {"phase": self.phase, "progress": self.progress,
               "t": t, "ka": ka, "pid": self.pid, "seq": self.seq,
               "wall": time.time()}
        tmp = f"{self.path}.{self.pid}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(json.dumps(rec))
            os.replace(tmp, self.path)
        except OSError as e:        # liveness reporting must never kill
            log.debug(f"heartbeat write failed: {e!r}")  # the workload

    def beat(self, phase: str, progress: int = 0) -> None:
        """Record a substantive liveness event (phase entry or loop
        progress). Call sites sit at the points a wedge would freeze:
        before compiles, per warmup/timed/boosting iteration, around
        device sync fetches."""
        now = self.clock()
        with self._lock:
            self.last_attempt = now
            if faults.check("hang"):
                # simulate a child whose runtime wedged so hard even the
                # keepalive thread is stuck: the FILE goes silent, the
                # process keeps going (supervisor-path harness)
                self._hung = True
                return
            if self._hung:
                return
            self.phase = str(phase)
            self.progress = int(progress)
            self.seq += 1
            self.last_beat = now
            # conlint: disable=CL002 — deliberate: the lock serializes
            # beat/touch writers so tmp+rename stays crash-consistent;
            # the write is a few hundred bytes to a local file
            self._write(t=now, ka=now)
        if phase == PHASE_COMPILING:
            # injected compile stretch: the phase sits still while the
            # keepalive thread keeps proving the process alive — the
            # exact signature a healthy slow remote compile produces
            faults.maybe_delay("slow_compile")

    def touch(self) -> None:
        """Keepalive refresh: proves the process (and this thread) are
        alive without claiming loop progress."""
        with self._lock:
            if self._hung:
                return
            # conlint: disable=CL002 — same single-writer file-I/O
            # serialization as beat(); see above
            self._write(t=self.last_beat, ka=self.clock())

    # -- keepalive thread ----------------------------------------------
    def start_keepalive(self) -> "Heartbeat":
        if self._ka_thread is None or not self._ka_thread.is_alive():
            self._stop.clear()
            self._ka_thread = threading.Thread(
                target=self._ka_loop, name="lgbm-tpu-heartbeat",
                daemon=True)
            self._ka_thread.start()
        return self

    def _ka_loop(self) -> None:
        while not self._stop.wait(self.keepalive_interval):
            self.touch()

    def close(self) -> None:
        self._stop.set()
        if self._ka_thread is not None:
            self._ka_thread.join(timeout=1.0)


# ---------------------------------------------------------------------------
# process-global instance (installed from env by supervised children)
# ---------------------------------------------------------------------------

_current: Optional[Heartbeat] = None
_watchdog = None        # process-global TrainingWatchdog (one thread)


def current() -> Optional[Heartbeat]:
    return _current


def install(path: str,
            keepalive_interval: Optional[float] = None) -> Heartbeat:
    """Install the process-global heartbeat at ``path`` (keepalive
    thread started). Idempotent per path. The keepalive cadence
    resolves explicit argument > ``LGBM_TPU_HEARTBEAT_KA`` > 5 s, so a
    supervisor that tightened its silence policy via the env reaches
    param-configured (``tpu_heartbeat_file``) workloads too."""
    global _current, _watchdog
    if keepalive_interval is None:
        ka = (os.environ.get(ENV_KEEPALIVE) or "").strip()
        keepalive_interval = float(ka) if ka else 5.0
    if _current is not None and _current.path == os.path.abspath(path):
        return _current
    if _current is not None:
        _current.close()
        if _watchdog is not None:
            _watchdog.stop()
            _watchdog = None
    _current = Heartbeat(os.path.abspath(path),
                         keepalive_interval=keepalive_interval)
    _current.start_keepalive()
    return _current


def uninstall() -> None:
    """Tear down the process-global heartbeat + watchdog (tests; a
    workload whose supervision ended)."""
    global _current, _watchdog
    if _watchdog is not None:
        _watchdog.stop()
        _watchdog = None
    if _current is not None:
        _current.close()
        _current = None


def stall_pending() -> bool:
    """True while a classified stall is ARMED and not yet consumed by
    ``check()`` — lets a top-level handler distinguish a
    watchdog-provoked KeyboardInterrupt from a user's Ctrl-C. Armed
    state is consumed when it surfaces as DeviceStallError, so a
    genuine Ctrl-C minutes after a handled stall propagates untouched."""
    wd = _watchdog
    return wd is not None and wd.stalled is not None


def training_watchdog(policy=None):
    """The process-global :class:`TrainingWatchdog` bound to the
    installed heartbeat (None when unsupervised). ONE daemon thread per
    process regardless of how many boosters train — each caller
    re-arms it per iteration via begin()/end(). A non-None ``policy``
    replaces the active one (last configured booster wins)."""
    global _watchdog
    hb = _current
    if hb is None:
        return None
    if _watchdog is None or _watchdog.hb is not hb:
        if _watchdog is not None:
            _watchdog.stop()
        _watchdog = TrainingWatchdog(hb, policy=policy).start()
    elif policy is not None:
        _watchdog.policy = policy
    return _watchdog


def install_from_env(env=None) -> Optional[Heartbeat]:
    """Install from ``LGBM_TPU_HEARTBEAT`` (no-op without it). Hooked by
    the instrumented entry points (bench children, the gbdt loop), NOT
    at package import: a heartbeat claims "this process is the
    supervised workload", which only the workload itself knows."""
    e = env if env is not None else os.environ
    path = (e.get(ENV_HEARTBEAT) or "").strip()
    if not path:
        return None
    ka = (e.get(ENV_KEEPALIVE) or "").strip()
    return install(path, keepalive_interval=float(ka) if ka else None)


def beat(phase: str, progress: int = 0) -> None:
    """Convenience: beat the process-global heartbeat (no-op when no
    supervisor asked for one)."""
    hb = _current
    if hb is not None:
        hb.beat(phase, progress)


# ---------------------------------------------------------------------------
# stall classification (the supervisor side)
# ---------------------------------------------------------------------------

class DeviceStallError(Exception):
    """A supervised child (or this process's own training loop) sat
    silent past its phase's stall budget: classified hung, not slow.

    The message carries ``DEADLINE_EXCEEDED`` so
    :func:`..retry.is_transient_error` treats a stall exactly like the
    device symptom it is — a retried attempt (with the compile cache
    warm) may well succeed."""

    def __init__(self, msg: str):
        super().__init__(f"DEADLINE_EXCEEDED: {msg}")


# verdicts returned by StallPolicy.classify
ALIVE = "alive"          # advancing, or within its phase's stall budget
STALLED = "stalled"      # file updating (keepalive) but phase sat still
SILENT = "silent"        # file not updating at all
WAITING = "waiting"      # no heartbeat yet, within startup grace


# Default per-phase stall budgets (seconds): how long a phase may sit
# with NO substantive beat before it is hung. Compiling is generous —
# the documented remote-compile pathology is minutes (a 31-leaf probe
# compile alone took 254 s, docs/TPU_RUNBOOK.md); iterations are tight —
# a loop that beat per iteration and stopped is wedged, not thinking.
DEFAULT_STALL: Dict[str, float] = {
    PHASE_COMPILING: 1200.0,
    PHASE_WARMUP: 420.0,
    PHASE_MEASURING: 300.0,
    PHASE_ITER: 300.0,
    # one sharded-ingest protocol step (each is a collective round or a
    # local binning pass; the 10.5M×28 A/B measured 63 s end to end —
    # pod-scale payloads should raise LGBM_TPU_STALL_SEC_INGEST)
    PHASE_INGEST: 600.0,
}
DEFAULT_STALL_FALLBACK = 420.0
# keepalives come every ~5 s; 60 s of file silence means even the
# beater thread is stuck (or the process died without the supervisor's
# waitpid noticing yet) — hung at a level no phase budget excuses
DEFAULT_SILENT_SEC = 60.0
DEFAULT_STARTUP_GRACE = 120.0


@dataclasses.dataclass(frozen=True)
class StallPolicy:
    """Phase-aware liveness deadlines (the supervisor's contract).

    - ``stall_sec``: per-phase budget for a phase sitting still
      (substantive beat age). A phase/progress change resets the clock —
      a child advancing iterations is never parked.
    - ``silent_sec``: max heartbeat-file age (keepalive included)
      before the child is hung regardless of phase.
    - ``startup_grace``: time a child may run before its FIRST beat
      (interpreter + imports + backend init).
    """

    stall_sec: Dict[str, float] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_STALL))
    default_stall: float = DEFAULT_STALL_FALLBACK
    silent_sec: float = DEFAULT_SILENT_SEC
    startup_grace: float = DEFAULT_STARTUP_GRACE

    def stall_for(self, phase: str) -> float:
        return float(self.stall_sec.get(phase, self.default_stall))

    @classmethod
    def from_env(cls, env=None, **overrides) -> "StallPolicy":
        """``LGBM_TPU_STALL_SEC`` scales every phase budget (and the
        fallback); ``LGBM_TPU_STALL_SEC_<PHASE>`` pins one phase."""
        e = env if env is not None else os.environ
        kw: Dict = {}
        table = dict(DEFAULT_STALL)
        default_stall = DEFAULT_STALL_FALLBACK
        base = (e.get(ENV_STALL) or "").strip()
        if base:
            default_stall = float(base)
            table = {p: float(base) for p in table}
        for phase in list(table):
            v = (e.get(f"{ENV_STALL}_{phase.upper()}") or "").strip()
            if v:
                table[phase] = float(v)
        kw["stall_sec"] = table
        kw["default_stall"] = default_stall
        v = (e.get(f"{ENV_STALL}_SILENT") or "").strip()
        if v:
            kw["silent_sec"] = float(v)
        v = (e.get(f"{ENV_STALL}_GRACE") or "").strip()
        if v:
            kw["startup_grace"] = float(v)
        kw.update(overrides)
        return cls(**kw)

    def classify(self, rec: Optional[HeartbeatRecord], now: float,
                 started_at: float) -> str:
        """One verdict from one observation (see ALIVE/STALLED/SILENT/
        WAITING). ``started_at`` is when the child was launched (same
        monotonic clock)."""
        if rec is None:
            if now - started_at <= self.startup_grace:
                return WAITING
            return SILENT
        if now - rec.ka > self.silent_sec:
            return SILENT
        if now - rec.t > self.stall_for(rec.phase):
            return STALLED
        return ALIVE


# ---------------------------------------------------------------------------
# in-child training watchdog (driven from models/gbdt.py)
# ---------------------------------------------------------------------------

def _stall_exit_enabled(env=None) -> bool:
    """Hard-exit escalation default: ON when a supervisor asked for
    heartbeats (it will classify the exit code and relaunch), overridable
    via LGBM_TPU_STALL_EXIT=0/1."""
    e = env if env is not None else os.environ
    v = (e.get(ENV_STALL_EXIT) or "").strip().lower()
    if v:
        return v not in ("0", "false", "off", "no")
    return bool((e.get(ENV_HEARTBEAT) or "").strip())


class TrainingWatchdog:
    """Monitors the *in-memory* beat-attempt age of this process's own
    training loop and refuses to hang forever.

    The gbdt loop beats once per iteration and around device sync
    points; a main thread wedged inside a blocking runtime call stops
    calling ``beat``. When the attempt age exceeds the current phase's
    stall budget the watchdog (a daemon thread):

    1. logs the stall loudly and arms ``stalled`` — the training loop
       raises :class:`DeviceStallError` at its next checkpoint;
    2. calls ``_thread.interrupt_main()`` so a Python-level wait (e.g.
       a retry sleep) unblocks;
    3. if the main thread is wedged in a native call that nothing can
       interrupt, hard-exits with :data:`EXIT_STALLED` after one more
       grace period — a classified death the supervisor retries, which
       is strictly better than a silent forever-hang (escalation is on
       only under supervision or LGBM_TPU_STALL_EXIT=1).
    """

    def __init__(self, hb: Heartbeat, policy: Optional[StallPolicy] = None,
                 poll: float = 2.0, exit_on_stall: Optional[bool] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.hb = hb
        self.policy = policy if policy is not None else \
            StallPolicy.from_env()
        self.poll = float(poll)
        self.exit_on_stall = (_stall_exit_enabled() if exit_on_stall
                              is None else bool(exit_on_stall))
        self.clock = clock
        self.stalled: Optional[str] = None   # armed with a description
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # re-entrant arm window: the watchdog only judges beat age while
        # an iteration is actually in flight — a trained model sitting
        # idle (predict/serve) must never be "stalled"
        self._depth = 0
        self._depth_lock = threading.Lock()

    def start(self) -> "TrainingWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="lgbm-tpu-stall-watchdog",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def begin(self) -> None:
        """Arm the watchdog for an iteration (re-entrant: nested
        begin/end — e.g. the async stop-check's sync replay — keep it
        armed until the outermost end)."""
        with self._depth_lock:
            self._depth += 1

    def end(self) -> None:
        with self._depth_lock:
            self._depth = max(0, self._depth - 1)

    def check(self) -> None:
        """Raise if the watchdog armed while we were blocked — the
        training loop calls this at iteration boundaries so a stall
        surfaces as a classified exception, not a hang."""
        if self.stalled is not None:
            msg, self.stalled = self.stalled, None
            raise DeviceStallError(msg)

    def _loop(self) -> None:
        interrupted_at: Optional[float] = None
        while not self._stop.wait(self.poll):
            if self._depth <= 0:
                interrupted_at = None
                continue
            now = self.clock()
            phase = self.hb.phase or PHASE_COMPILING
            budget = self.policy.stall_for(phase)
            age = now - self.hb.last_attempt
            if age <= budget:
                interrupted_at = None
                continue
            if self.stalled is None:
                self.stalled = (
                    f"training loop silent for {age:.0f}s in phase "
                    f"{phase!r} (budget {budget:.0f}s) — device sync "
                    "presumed hung")
                log.warning(f"stall watchdog: {self.stalled}; "
                            "interrupting the main thread")
                import _thread
                try:
                    _thread.interrupt_main()
                except Exception:   # noqa: BLE001
                    pass
                interrupted_at = now
            elif (self.exit_on_stall and interrupted_at is not None and
                    now - interrupted_at > max(budget * 0.25, 30.0)):
                log.warning(
                    f"stall watchdog: main thread still wedged "
                    f"{now - interrupted_at:.0f}s after interrupt; "
                    f"hard-exiting rc={EXIT_STALLED} so the supervisor "
                    "can classify and retry instead of waiting forever")
                os._exit(EXIT_STALLED)
