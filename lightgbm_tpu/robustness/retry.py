"""Reusable retry policy: bounded attempts, decorrelated-jitter backoff,
an overall deadline, and a transient-error classifier for jax/XLA.

Every bench round to date (BENCH_r01-r05) died with
``device_unreachable``: the TPU tunnel cycles through
``UNAVAILABLE: TPU backend setup/compile error`` while recovering
(docs/TPU_RUNBOOK.md), and a single unretried failure turned a
recovering device into a dead run. This module is the one shared answer:
``init_distributed``, the injected-collective call sites
(distributed.py) and the bench probe (bench.py) all retry through the
same policy, so "how long do we believe in a flaky device" is configured
in exactly one place.

Backoff is decorrelated jitter (Brooker, "Exponential Backoff And
Jitter", AWS builders' library): ``sleep = min(cap, uniform(base,
prev_sleep * 3))`` — spreads concurrent retriers apart instead of
re-synchronizing them the way plain exponential backoff does.

Classifier table (ISSUE 17) — every failure a call site may see falls
in exactly one class, and this table is the single place the classes
are defined (tests assert the table, the docstring and the classifiers
stay in sync):

- ``TRANSIENT`` — device/network flake (UNAVAILABLE / ABORTED /
  connection errors): a later attempt of the SAME call may succeed, so
  :func:`retry_call` burns budget on it. Markers:
  :data:`TRANSIENT_MARKERS` / :data:`TRANSIENT_TYPES`.
- ``DEADLINE`` — a liveness budget expired (DEADLINE_EXCEEDED /
  timeouts). Retried like TRANSIENT (the next attempt gets a fresh
  sub-slot), but reported distinctly by :func:`classify_error` so
  forensics can tell a flake from a wedge. Markers:
  :data:`DEADLINE_MARKERS` / ``TimeoutError``.
- ``RESOURCE_EXHAUSTED`` — an allocation failed (XLA
  RESOURCE_EXHAUSTED / "out of memory" / ``MemoryError``). Retrying
  the SAME allocation is futile, so the classifier returns
  non-transient and :func:`retry_call` propagates immediately; the
  call site must ADAPT the request instead — the serving dispatcher
  bisects the batch (serving/server.py), the fleet evicts cold packs
  (serving/fleet.py), the trainer shrinks its window
  (service/trainer.py). Markers: :data:`OOM_MARKERS` /
  :data:`OOM_TYPES`.
- ``DATA_CORRUPTION`` — the call RAN but produced wrong bits
  (NaN-poisoned gradients, a canary parity mismatch, diverged gang
  digests — the :mod:`.integrity` exception family). NOT transient:
  retrying the identical call re-produces the identical corruption, so
  :func:`retry_call` propagates immediately and the call site must
  RECOVER — the continual trainer rolls back to the newest CRC-valid
  checkpoint (service/trainer.py), the serving tier quarantines the
  afflicted route and repairs the pack (serving/fleet.py), the gang
  supervisor relaunches from the manifest (robustness/gang.py).
  Markers: :data:`CORRUPTION_MARKERS`.
- ``FATAL`` — everything else (a code bug): propagates immediately,
  never retried, never adapted around.

No jax import at module scope (the classifier matches on type/message
strings precisely so it can run in processes that must not initialize a
backend).
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional

from ..utils import log

# Substrings of exception text (or type name) that mark a failure as
# transient — retry may succeed. gRPC/XLA status names cover the
# device-tunnel failure modes measured in BENCH_r01-r05; the plain
# words cover socket/timeout errors raised by launchers.
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "connection reset",
    "connection refused",
    "timed out",
    "timeout",
)

# Exception type names treated as transient regardless of message.
TRANSIENT_TYPES = (
    "TimeoutError",
    "ConnectionError",
    "ConnectionResetError",
    "ConnectionRefusedError",
    "BrokenPipeError",
)

# The DEADLINE sub-class of the transient markers: budget expiries that
# classify_error reports distinctly (still retried by retry_call).
DEADLINE_MARKERS = (
    "DEADLINE_EXCEEDED",
    "timed out",
    "timeout",
)

# Substrings marking RESOURCE_EXHAUSTED: the allocation itself failed,
# so re-attempting the SAME call is futile — the caller must shrink,
# bisect or evict (ISSUE 17). XLA's OOM status is the gRPC name; the
# plain phrases cover allocator messages and host MemoryError reprs.
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "failed to allocate",
)

# Exception type names treated as RESOURCE_EXHAUSTED regardless of
# message (host-side allocation failures during re-bin / pack build).
OOM_TYPES = (
    "MemoryError",
)

# Substrings marking DATA_CORRUPTION: the call ran and returned wrong
# bits (ISSUE 19). Every integrity.IntegrityError message carries the
# marker, so classification works across process boundaries (a child
# trainer's corruption surfaces to its supervisor as text).
CORRUPTION_MARKERS = (
    "DATA_CORRUPTION",
)

# The classifier table, machine-readable: class name -> one-line
# contract. tests/test_robustness.py asserts every class here appears
# in the module docstring (the drift check of the ISSUE 17 satellite).
ERROR_CLASSES = {
    "TRANSIENT": "device/network flake — retry the same call",
    "DEADLINE": "liveness budget expired — retry with a fresh slot",
    "RESOURCE_EXHAUSTED": "allocation failed — adapt, never retry",
    "DATA_CORRUPTION": "wrong bits produced — roll back, never retry",
    "FATAL": "code bug — propagate immediately",
}


def is_oom_error(exc: BaseException) -> bool:
    """True when ``exc`` is RESOURCE_EXHAUSTED-classified: the
    allocation failed, so retrying the identical call cannot succeed.
    Callers adapt instead (bisect the batch / evict a pack / shrink
    the window)."""
    for t in type(exc).__mro__:
        if t.__name__ in OOM_TYPES:
            return True
    text = f"{type(exc).__name__}: {exc}"
    upper = text.upper()
    return any(m.upper() in upper for m in OOM_MARKERS)


def is_corruption_error(exc: BaseException) -> bool:
    """True when ``exc`` is DATA_CORRUPTION-classified: the call ran
    but produced wrong bits, so retrying it re-produces the identical
    corruption. Callers roll back / quarantine / relaunch instead
    (integrity.py is the exception family; matching is on the message
    marker so child-process corruption classifies identically)."""
    text = f"{type(exc).__name__}: {exc}"
    upper = text.upper()
    return any(m.upper() in upper for m in CORRUPTION_MARKERS)


def is_transient_error(exc: BaseException) -> bool:
    """True when ``exc`` looks like a device/network failure that a
    later attempt may survive (UNAVAILABLE / DEADLINE_EXCEEDED /
    timeouts), False for anything that smells like a code bug.

    RESOURCE_EXHAUSTED is explicitly NOT transient even when the
    runtime dresses it in otherwise-transient text: retrying the same
    allocation burns the whole budget on attempts that cannot succeed
    (ISSUE 17) — :func:`retry_call` propagates it so the dispatch
    layer can adapt. DATA_CORRUPTION is NOT transient for the same
    reason (ISSUE 19): the retried call would re-produce the same
    wrong bits; the caller must roll back or repair instead.

    jaxlib's XlaRuntimeError carries the gRPC status name in its
    message, so string matching is the stable contract across jaxlib
    versions (the exception classes themselves moved modules twice).
    """
    if is_oom_error(exc) or is_corruption_error(exc):
        return False
    for t in type(exc).__mro__:
        if t.__name__ in TRANSIENT_TYPES:
            return True
    text = f"{type(exc).__name__}: {exc}"
    upper = text.upper()
    return any(m.upper() in upper for m in TRANSIENT_MARKERS)


def classify_error(exc: BaseException) -> str:
    """Classify ``exc`` into one of :data:`ERROR_CLASSES`.

    Precedence: RESOURCE_EXHAUSTED beats DATA_CORRUPTION beats
    DEADLINE beats TRANSIENT (an OOM whose message also mentions a
    timeout is still an OOM); anything unrecognized is FATAL."""
    if is_oom_error(exc):
        return "RESOURCE_EXHAUSTED"
    if is_corruption_error(exc):
        return "DATA_CORRUPTION"
    if not is_transient_error(exc):
        return "FATAL"
    for t in type(exc).__mro__:
        if t.__name__ == "TimeoutError":
            return "DEADLINE"
    upper = f"{type(exc).__name__}: {exc}".upper()
    if any(m.upper() in upper for m in DEADLINE_MARKERS):
        return "DEADLINE"
    return "TRANSIENT"


class RetryError(Exception):
    """All attempts failed (or the deadline passed). ``last`` holds the
    final underlying exception; ``attempts`` how many were made."""

    def __init__(self, msg: str, last: Optional[BaseException],
                 attempts: int):
        super().__init__(msg)
        self.last = last
        self.attempts = attempts


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with decorrelated-jitter backoff and a deadline.

    - ``max_attempts``: total tries (first call included).
    - ``base_delay`` / ``max_delay``: jitter window bounds in seconds.
    - ``deadline``: wall-clock budget across ALL attempts (None = no
      deadline). No new attempt starts after it passes, and the
      pre-attempt sleep is clipped to it, so the policy can never
      outlive its budget — the property the bench watchdog relies on.
    - ``classifier``: exception -> bool (True = transient, retry).
    """

    max_attempts: int = 5
    base_delay: float = 0.5
    max_delay: float = 30.0
    deadline: Optional[float] = None
    classifier: Callable[[BaseException], bool] = is_transient_error

    def next_delay(self, prev_delay: float,
                   rng: random.Random) -> float:
        """Decorrelated jitter: uniform(base, prev*3) capped."""
        hi = max(self.base_delay, prev_delay * 3.0)
        return min(self.max_delay, rng.uniform(self.base_delay, hi))

    def from_env_overrides(self, env) -> "RetryPolicy":
        """LGBM_TPU_RETRY_* env knobs override individual fields
        (ATTEMPTS / BASE_DELAY / MAX_DELAY / DEADLINE)."""
        kw = {}
        if env.get("LGBM_TPU_RETRY_ATTEMPTS"):
            kw["max_attempts"] = int(env["LGBM_TPU_RETRY_ATTEMPTS"])
        if env.get("LGBM_TPU_RETRY_BASE_DELAY"):
            kw["base_delay"] = float(env["LGBM_TPU_RETRY_BASE_DELAY"])
        if env.get("LGBM_TPU_RETRY_MAX_DELAY"):
            kw["max_delay"] = float(env["LGBM_TPU_RETRY_MAX_DELAY"])
        if env.get("LGBM_TPU_RETRY_DEADLINE"):
            kw["deadline"] = float(env["LGBM_TPU_RETRY_DEADLINE"])
        return dataclasses.replace(self, **kw) if kw else self


# Policy used by the in-band training call sites (collectives,
# init_distributed): short sleeps — a training step is stalled while we
# wait — but enough attempts to ride out a p=0.2 injected failure rate
# with margin (P[5 consecutive failures] = 0.032%).
COLLECTIVE_POLICY = RetryPolicy(max_attempts=5, base_delay=0.05,
                                max_delay=2.0, deadline=120.0)

# Policy for device acquisition (probe / init): patient — the measured
# recovery signature is a claim that waits minutes before succeeding.
DEVICE_POLICY = RetryPolicy(max_attempts=6, base_delay=2.0,
                            max_delay=60.0, deadline=900.0)

# Policy for the serving dispatcher (serving/server.py, ISSUE 9): very
# short sleeps — every queued request is stalled while a batch retries —
# and a tight deadline: past it the server flips to the degraded
# host-walk route instead of holding its whole client population
# hostage to one wedged device.
SERVING_POLICY = RetryPolicy(max_attempts=3, base_delay=0.02,
                             max_delay=0.5, deadline=5.0)


def retry_call(fn: Callable, *args,
               policy: RetryPolicy = RetryPolicy(),
               what: str = "",
               rng: Optional[random.Random] = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic,
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None,
               budget_kw: Optional[str] = None,
               **kwargs):
    """Call ``fn(*args, **kwargs)`` under ``policy``.

    Transient failures (per ``policy.classifier``) are retried with
    decorrelated-jitter sleeps until attempts or deadline run out;
    non-transient exceptions propagate immediately (a code bug must
    never burn the retry budget). Raises :class:`RetryError` when the
    budget is exhausted.

    Window accounting (ISSUE 4 satellite — the r05 log showed a probe
    attempt granted a 750 s slot inside an already half-spent window):

    - no attempt STARTS at or past the deadline (previously the
      deadline was only consulted after a failure, so a sleep could
      run the clock out and a fresh attempt still launch);
    - a backoff sleep that alone would exhaust the remaining deadline
      is skipped — the remaining window is spent on one final attempt
      instead of slept away;
    - ``budget_kw``: when set, every attempt receives the policy's
      remaining deadline (seconds, or None without a deadline) as that
      keyword argument, so callables that grant their own sub-slots
      (the bench probe's child timeout) can clip them to the window
      that actually remains.
    """
    rng = rng if rng is not None else random.Random()
    label = what or getattr(fn, "__name__", "call")
    start = clock()
    deadline_at = (start + policy.deadline
                   if policy.deadline is not None else None)
    delay = policy.base_delay
    last: Optional[BaseException] = None
    attempts = 0
    while attempts < policy.max_attempts:
        if (deadline_at is not None and clock() >= deadline_at and
                attempts > 0):
            break
        attempts += 1
        try:
            if budget_kw is not None:
                remaining = (max(0.0, deadline_at - clock())
                             if deadline_at is not None else None)
                return fn(*args, **{budget_kw: remaining}, **kwargs)
            return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — classifier decides
            if not policy.classifier(e):
                raise
            last = e
            if attempts >= policy.max_attempts:
                break
            if deadline_at is not None and clock() >= deadline_at:
                break
            delay = policy.next_delay(delay, rng)
            if deadline_at is not None and \
                    clock() + delay >= deadline_at:
                # the backoff alone would exhaust the window — spend
                # what remains on a final immediate attempt instead
                delay = 0.0
            if on_retry is not None:
                on_retry(attempts, e)
            log.warning(f"{label}: transient failure (attempt "
                        f"{attempts}/{policy.max_attempts}): {e!r}; "
                        f"retrying in {delay:.2f}s")
            if delay > 0.0:
                sleep(delay)
    raise RetryError(
        f"{label}: gave up after {attempts} attempt(s) over "
        f"{clock() - start:.1f}s: {last!r}", last, attempts)


# ---------------------------------------------------------------------------
# Graceful degradation: device acquisition with CPU fallback
# (config: tpu_fallback_to_cpu — ref motivation: the reference treats
# interruption as normal; we additionally treat "device never came up"
# as survivable when the user opted in).
# ---------------------------------------------------------------------------

def probe_device() -> int:
    """One device-acquisition attempt: list devices and run a trivial
    computation (forces backend init through the tunnel). Honors the
    fault harness's ``probe_timeout`` class so CPU tests can exercise
    the retry/fallback paths."""
    from . import faults
    faults.maybe_fail("probe_timeout")
    import jax
    devs = jax.devices()
    jax.block_until_ready(jax.numpy.zeros(8) + 1)
    return len(devs)


def ensure_device_or_fallback(fallback: bool = False,
                              policy: RetryPolicy = DEVICE_POLICY
                              ) -> bool:
    """Acquire the configured device under the retry policy; on terminal
    failure either fall back to CPU (``fallback=True``, from
    ``tpu_fallback_to_cpu``; loud warning, returns False) or re-raise.
    Returns True when the device came up.

    Call sites: engine.train (before the boosting loop) and the CLI
    runner. A no-op returning True on runs already pinned to CPU.
    """
    try:
        import os
        n = retry_call(
            probe_device,
            policy=policy.from_env_overrides(os.environ),
            what="device probe")
        log.debug(f"device probe ok ({n} device(s))")
        return True
    except Exception as e:  # noqa: BLE001
        # only a transient-classified terminal failure earns the CPU
        # fallback: a code bug (ImportError, TypeError, ...) must still
        # crash loudly rather than masquerade as a flaky device
        if not fallback or not (isinstance(e, RetryError) or
                                is_transient_error(e)):
            raise
        log.warning(
            "=" * 60 + "\n"
            f"DEVICE UNREACHABLE after retry policy exhausted: {e!r}\n"
            "tpu_fallback_to_cpu=true — CONTINUING ON CPU. Training "
            "will be correct but slow; fix the accelerator and restart "
            "to regain device speed.\n" + "=" * 60)
        import jax
        jax.config.update("jax_platforms", "cpu")
        return False
