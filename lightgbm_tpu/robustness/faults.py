"""Fault-injection harness: deterministic transient failures on demand.

Mirrors the ``LGBM_TPU_GUARDS`` install pattern (analysis/guards.py):
``LGBM_TPU_FAULTS`` is read once at package import (install_from_env in
lightgbm_tpu/__init__.py), so ANY process — bench, CLI, tests, worker
subprocesses — can be run under injected faults without code changes;
:func:`inject` is the scoped context-manager equivalent for tests.

Grammar (comma-separated fault specs, colon-separated options)::

    LGBM_TPU_FAULTS="collective:p=0.2,probe_timeout,write_kill"
    LGBM_TPU_FAULTS="collective:p=0.2:seed=7,write_kill:n=1:after=3"

Fault classes (the ``site`` argument of :func:`maybe_fail`):

- ``collective``  — the injected-collective host callables
  (distributed.make_injected_hooks) raise :class:`FaultInjected`
  (classified transient: its message carries ``UNAVAILABLE``).
- ``probe_timeout`` — device probes (robustness.retry.probe_device,
  which bench.py's probe child routes through) raise a transient
  failure, simulating the tunnel's recovery cycling.
- ``write_kill`` — checkpoint writes die MID-WRITE (after the payload
  is partially written, before the atomic rename), simulating a kill
  -9 during snapshotting; raises :class:`WriteKilled`.
- ``hang`` — the heartbeat writer (robustness/heartbeat.py) stops
  writing from the moment the fault fires: the child keeps running but
  its liveness file goes silent mid-phase, which is exactly what a
  wedged runtime looks like to a supervisor. Consulted via
  :func:`check` (non-raising) inside ``Heartbeat.beat``.
- ``slow_compile`` — stretches the ``compiling`` phase by ``sec``
  seconds (default 30) while keepalives keep flowing: a benign slow
  remote compile, the case phase-aware supervision must NOT park.
  Consulted via :func:`maybe_delay` at compile-phase entry.
- ``dispatch_error`` — the serving dispatcher's device scoring
  (serving/server.py ``_device_scores``) raises a transient
  :class:`FaultInjected` BEFORE the real dispatch; each retry under the
  serving RetryPolicy re-consults the fault, and the degraded server's
  background recovery probe consults it too (so a persistent plan keeps
  the server degraded until the plan disarms).
- ``slow_dispatch`` — stretches ONE serving dispatch by ``sec`` seconds
  (default 30) via :func:`maybe_delay`: the wedged-device shape that
  request deadlines must convert into ``DEADLINE_EXCEEDED`` failures
  for the requests queued behind it, never an unbounded stall.
- ``publish_fail`` — the serving hot-swap dies: consulted in
  ``ModelServer.publish()`` (before the snapshot is built — call 1) and
  again inside the incremental pack append (ops/forest.py
  ``_IncrementalPack._append``, pre-commit — call 2), so both the
  server-level rollback and the pack's no-torn-state commit are
  exercised; a bare spec fires at the server site, ``after=1`` reaches
  the append site.
- ``rank_kill`` — one gang rank hard-exits (``os._exit`` with
  :data:`EXIT_RANK_KILLED` — no cleanup, no flush: a real kill -9
  shape) at an iteration boundary. Consulted via
  :func:`maybe_kill_rank` at the top of the gbdt training iteration;
  the ``rank=R`` option selects which rank dies (default: any rank
  that consults) and ``after=N`` skips that rank's first N iterations,
  so a chaos harness can kill rank R after exactly N iterations. The
  survivors' recovery (collective deadline + gang supervisor SIGTERM +
  relaunch-from-manifest) is the ISSUE 10 chaos gate
  (scripts/gang_chaos_smoke.py).
- ``collective_delay`` — stretches ONE injected-collective /
  allgather attempt by ``sec`` seconds via :func:`maybe_delay`, INSIDE
  the collective liveness deadline (distributed.call_with_deadline):
  the blocked-dead-peer shape that must surface as
  ``CollectiveTimeout`` (DEADLINE_EXCEEDED) instead of wedging the
  rank to the whole-gang timeout.
- ``oom`` — an allocation fails: raises :class:`OOMInjected`, whose
  message carries ``RESOURCE_EXHAUSTED`` so the retry classifier files
  it as non-transient (retrying the same allocation is futile — the
  caller must adapt, ISSUE 17). One site name, three consult points
  selected with ``p=``/``after=`` exactly like ``publish_fail``: the
  serving dispatch (serving/server.py ``_device_scores`` and
  serving/fleet.py ``_bucket_scores`` — the bisection ladder), the
  fleet pack upload (ops/forest.py ``upload_window`` — publish-forced
  eviction), and the trainer re-bin (service/trainer.py — window
  auto-shrink).
- ``bitflip`` — silent data corruption (ISSUE 19): wrong bits appear
  where correct bits were written, via :func:`check` at four
  site-targeted consult points selected with the ``where=`` option:
  ``where=dev`` corrupts a freshly uploaded device pack
  (ops/forest.py ``upload_window`` and the solo server's published
  snapshot — sign bits of the slot-0 tree's leaf outputs, guaranteed
  canary-observable), ``where=host`` corrupts the retained HOST
  window copy (serving/fleet.py ``_build_bucket`` — caught by the CRC
  fingerprint before any re-upload), ``where=ckpt`` flips one byte of
  a committed checkpoint file (robustness/checkpoint.py — caught by
  the CRC32 footer on read, so recovery anchors on the previous valid
  generation), ``where=digest`` lies about one rank's committed-tree
  digest (models/gbdt.py ``_gang_digest_check`` — the gang agreement
  sync must refuse the iteration on every rank). Without ``where=``
  the first consulted point fires.
- ``nan_grad`` — one boosting iteration's gradients are poisoned to
  NaN after the objective computes them (models/gbdt.py sync path,
  via :func:`check`): the numeric-health guard must fail the
  iteration as ``DATA_CORRUPTION`` and the continual trainer must
  roll back to the newest CRC-valid checkpoint instead of committing
  or publishing the poisoned model.
- ``loss_spike`` — the numeric-health guard's loss observation is
  inflated past its spike threshold (robustness/integrity.py
  ``NumericHealthGuard.observe_loss`` via :func:`check`): the
  finite-but-wrong corruption signature, distinct from NaN.
- ``disk_full`` — the atomic checkpoint writer's payload write raises
  ``ENOSPC`` (robustness/checkpoint.py ``atomic_write_text``): the
  publish channel's disk filled mid-write. ``write_checkpoint``
  answers by pruning beyond ``keep_last`` and retrying ONCE — the
  continual service survives one full-disk episode without losing its
  newest committed generation.

Options per spec:

- ``p=<float>``  — failure probability per call (default 1.0).
- ``n=<int>``    — at most this many injected failures, then the fault
  disarms (default: unlimited for p<1, 1 for p=1 — a bare
  ``write_kill`` kills exactly one write).
- ``after=<int>`` — skip this many calls before arming (lets a test
  kill the k-th checkpoint write precisely).
- ``seed=<int>`` — per-fault RNG seed (default 0): injections are
  deterministic and reproducible across runs and threads.
- ``sec=<float>`` — duration for delay-style faults (``slow_compile``,
  ``slow_dispatch`` and ``collective_delay``; default 30.0).
- ``rank=<int>`` — gang rank filter (``rank_kill``): only the matching
  rank's consults count or fire (default: every rank).
- ``where=<name>`` — consult-point filter (``bitflip``): only consults
  passing a matching ``where=`` count or fire (``dev`` / ``host`` /
  ``ckpt``); without it the first consulted point fires. The same
  targeting idea as ``rank=``, for corruption sites.

Counters are PER-PROCESS: an env-installed plan re-arms in every
subprocess (each child re-runs install_from_env with fresh counters).
For flows that spawn one process per attempt — the bench probe — a
count-limited spec like ``probe_timeout:n=2`` therefore fails EVERY
child, which deterministically exercises the retry-exhaustion leg
(rc=4); to exercise the retry-then-recover leg use ``p=<1`` (each
child flips its own coin) or in-process injection (``inject(...)``
around ``robustness.retry.probe_device``, as
tests/test_robustness.py::test_probe_retries_then_succeeds does).
"""
from __future__ import annotations

import os
import random
import threading
from typing import Dict, List, Optional

from ..utils import log

ENV_FAULTS = "LGBM_TPU_FAULTS"

KNOWN_SITES = ("collective", "probe_timeout", "write_kill", "hang",
               "slow_compile", "dispatch_error", "slow_dispatch",
               "publish_fail", "rank_kill", "collective_delay", "oom",
               "bitflip", "nan_grad", "loss_spike", "disk_full")

# exit code of an injected rank_kill: the gang supervisor annotates it
# in the per-rank diagnosis (distinct from EXIT_STALLED=86 so forensics
# can tell an injected death from a self-watchdogged wedge)
EXIT_RANK_KILLED = 87


class FaultInjected(Exception):
    """An injected TRANSIENT failure (message carries UNAVAILABLE so the
    retry classifier treats it exactly like the real device symptom)."""


class WriteKilled(FaultInjected):
    """An injected mid-write kill: the write never completed; whatever
    bytes hit the disk are garbage that recovery must survive."""


class OOMInjected(FaultInjected):
    """An injected allocation failure — the NON-transient member of the
    family: its message carries ``RESOURCE_EXHAUSTED`` so the retry
    classifier refuses to burn budget on it and the call site must
    adapt (bisect / evict / shrink) instead."""


class _Fault:
    def __init__(self, site: str, p: float = 1.0,
                 n: Optional[int] = None, after: int = 0,
                 seed: int = 0, sec: float = 30.0,
                 rank: Optional[int] = None,
                 where: Optional[str] = None):
        self.site = site
        self.p = float(p)
        self.sec = float(sec)
        self.rank = int(rank) if rank is not None else None
        self.where = str(where) if where is not None else None
        # a bare always-on fault (p=1, no n) fires once then disarms:
        # "kill the write" means one kill, not an unrecoverable loop
        self.n = n if n is not None else (1 if self.p >= 1.0 else None)
        self.after = int(after)
        self.calls = 0
        self.fired = 0
        self.rng = random.Random(seed)
        self.lock = threading.Lock()

    def should_fire(self) -> bool:
        with self.lock:
            self.calls += 1
            if self.calls <= self.after:
                return False
            if self.n is not None and self.fired >= self.n:
                return False
            if self.rng.random() >= self.p:
                return False
            self.fired += 1
            return True

    def __repr__(self):
        return (f"_Fault({self.site}, p={self.p}, n={self.n}, "
                f"after={self.after}, fired={self.fired}/"
                f"calls={self.calls})")


class FaultPlan:
    """Parsed set of active faults, keyed by site."""

    def __init__(self, faults: Dict[str, _Fault]):
        self.faults = faults

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults: Dict[str, _Fault] = {}
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            site = parts[0].strip()
            if site not in KNOWN_SITES:
                raise ValueError(
                    f"unknown fault class {site!r}; expected one of "
                    f"{KNOWN_SITES}")
            kw = {}
            for opt in parts[1:]:
                if "=" not in opt:
                    raise ValueError(
                        f"malformed fault option {opt!r} in {entry!r} "
                        "(expected key=value)")
                k, _, v = opt.partition("=")
                k = k.strip()
                if k == "p":
                    kw["p"] = float(v)
                elif k == "n":
                    kw["n"] = int(v)
                elif k == "after":
                    kw["after"] = int(v)
                elif k == "seed":
                    kw["seed"] = int(v)
                elif k == "sec":
                    kw["sec"] = float(v)
                elif k == "rank":
                    kw["rank"] = int(v)
                elif k == "where":
                    kw["where"] = v.strip()
                else:
                    raise ValueError(
                        f"unknown fault option {k!r} in {entry!r}")
            if site in faults:
                raise ValueError(f"duplicate fault class {site!r}")
            faults[site] = _Fault(site, **kw)
        return cls(faults)

    def __repr__(self):
        return f"FaultPlan({list(self.faults.values())})"


_active: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _active


def maybe_fail(site: str) -> None:
    """Raise the configured injected failure for ``site`` (no-op when no
    plan is installed or the site's fault doesn't fire this call).

    Call sites sit immediately BEFORE the real operation, so a fired
    fault means the operation did not run this attempt — exactly the
    semantics of a request lost to a flaky device."""
    plan = _active
    if plan is None:
        return
    f = plan.faults.get(site)
    if f is None or not f.should_fire():
        return
    if site == "write_kill":
        raise WriteKilled(
            f"injected mid-write kill (write #{f.calls})")
    if site == "disk_full":
        # the REAL exception shape (OSError/ENOSPC), not a FaultInjected
        # wrapper: the writer's recovery path must classify by errno,
        # exactly as it would for a genuinely full disk
        import errno
        raise OSError(errno.ENOSPC,
                      f"injected disk_full fault (write #{f.calls}, "
                      f"injection #{f.fired})")
    if site == "oom":
        raise OOMInjected(
            f"RESOURCE_EXHAUSTED: injected oom fault "
            f"(call #{f.calls}, injection #{f.fired})")
    raise FaultInjected(
        f"UNAVAILABLE: injected {site} fault "
        f"(call #{f.calls}, injection #{f.fired})")


def check(site: str, where: Optional[str] = None) -> bool:
    """Non-raising consult: True when ``site``'s fault fires this call.

    For fault kinds whose effect is behavioral rather than an exception
    (``hang`` suppresses heartbeat writes, ``bitflip`` corrupts bytes)
    the call site decides what "failing" means; counters/probability/
    arming work exactly like :func:`maybe_fail`. ``where`` names the
    consult point for site-targeted faults: a fault armed with
    ``where=X`` only counts or fires at consults passing ``where="X"``
    (consults elsewhere don't burn ``after=`` budget, mirroring the
    ``rank=`` filter)."""
    plan = _active
    if plan is None:
        return False
    f = plan.faults.get(site)
    if f is None:
        return False
    if f.where is not None and where != f.where:
        return False
    return f.should_fire()


def maybe_delay(site: str, sleep=None) -> float:
    """Delay-style injection: sleep the fault's ``sec`` when it fires
    and return the seconds slept (0.0 otherwise). Used by
    ``slow_compile`` to stretch the compiling phase without touching
    liveness."""
    plan = _active
    if plan is None:
        return 0.0
    f = plan.faults.get(site)
    if f is None or not f.should_fire():
        return 0.0
    log.warning(f"injected {site} delay: sleeping {f.sec:.1f}s "
                f"(call #{f.calls}, injection #{f.fired})")
    import time
    (sleep if sleep is not None else time.sleep)(f.sec)
    return f.sec


def maybe_kill_rank(rank: int, _exit=os._exit) -> None:
    """``rank_kill`` consult (gbdt iteration boundary): when the fault
    fires for THIS rank, hard-exit with :data:`EXIT_RANK_KILLED` — an
    ``os._exit`` so no cleanup or atexit runs, the closest injectable
    shape to a kill -9 mid-gang. A ``rank=R`` option restricts both the
    call accounting and the kill to rank R (so ``after=N`` means "after
    N of rank R's iterations"); without it every consulting rank is
    eligible, each with per-process counters.

    ``_exit`` is injectable so tests and the fault smoke can observe
    the exit code without dying."""
    plan = _active
    if plan is None:
        return
    f = plan.faults.get("rank_kill")
    if f is None:
        return
    if f.rank is not None and int(rank) != f.rank:
        return
    if not f.should_fire():
        return
    log.warning(f"injected rank_kill: rank {rank} hard-exiting "
                f"rc={EXIT_RANK_KILLED} (call #{f.calls}, injection "
                f"#{f.fired})")
    try:
        import sys
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:   # noqa: BLE001 — dying anyway
        pass
    _exit(EXIT_RANK_KILLED)


class inject:
    """Scoped fault injection::

        with faults.inject("collective:p=0.2:seed=3"):
            ...train...

    Nestable in the trivial sense (restores the previous plan on exit).
    Also usable as ``inject(None)`` to suppress an env-installed plan
    within the block.
    """

    def __init__(self, spec: Optional[str]):
        self.plan = FaultPlan.parse(spec) if spec else None
        self._saved: List[Optional[FaultPlan]] = []

    def __enter__(self) -> Optional[FaultPlan]:
        global _active
        self._saved.append(_active)
        _active = self.plan
        return self.plan

    def __exit__(self, *exc) -> None:
        global _active
        _active = self._saved.pop()


def install_from_env(env=None) -> bool:
    """Process-wide plan from ``LGBM_TPU_FAULTS`` (returns True if a
    plan was installed). Hooked into lightgbm_tpu/__init__.py so any
    importing process — including bench/probe child processes, which
    inherit the env var — runs under the plan."""
    global _active
    e = env if env is not None else os.environ
    spec = (e.get(ENV_FAULTS) or "").strip()
    if not spec or spec.lower() in ("0", "false", "off", "no"):
        return False
    _active = FaultPlan.parse(spec)
    log.warning(f"fault injection ACTIVE ({ENV_FAULTS}): {_active!r}")
    return True
