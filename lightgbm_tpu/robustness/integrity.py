"""Silent-corruption defense: canaries, numeric health, gang agreement.

Every robustness layer before this one (retry/backoff, heartbeat
supervision, serving chaos, the gang, OOM survival) defends against
processes that die, stall, or run out of memory. This module defends
against the worse failure: a process that KEEPS RUNNING and returns
wrong answers — a flipped bit in a device pack serving wrong scores, a
NaN-poisoned boosting iteration committing a garbage model, a diverged
rank committing a forked model, a full disk tearing the publish
channel. The contract is always the same: detect, quarantine, repair,
account — never silently serve or commit wrong bits, and never crash
-loop on a fault the caller can adapt past.

Four legs, one fault grammar (``robustness/faults.py``: ``bitflip``,
``nan_grad``, ``loss_spike``, ``disk_full``) and one counter contract
(``serving/metrics.py``: ``integrity_probes`` / ``integrity_mismatches``
/ ``quarantines`` / ``repairs``):

1. **Serving canary parity probes** — at pack/publish/rebuild time the
   serving tier records a host-walk golden score vector for a small
   fixed canary batch (:func:`canary_batch`, deterministic per feature
   width, padded through the EXISTING row buckets so probes add zero
   steady-state traces). A background :class:`IntegrityProbe` replays
   the canary through every resident device route and bit-compares
   against the golden; a mismatch quarantines only the afflicted
   route/tenant to the bit-identical host walk, repairs (re-upload from
   the CRC-verified host pack, or full rebuild when the host pack
   itself is corrupt), re-probes and un-quarantines on clean parity.
2. **Host pack fingerprints** — :func:`crc32_fingerprint` over a host
   pack pytree, recorded at pack time and re-verified on lazy rebuild
   and repair, distinguishes host-side from device-side corruption.
3. **Training numeric health** — :class:`NumericHealthGuard` checks
   grad/hess sums, leaf outputs and the eval/loss series every
   iteration and raises :class:`NumericHealthError` (classified
   ``DATA_CORRUPTION`` by ``retry.classify_error``; NOT transient —
   retrying the same poisoned iteration is futile). The continual
   trainer answers by rolling back to the newest CRC-valid checkpoint.
4. **Gang agreement** — ranks periodically compare a cheap digest of
   the freshly grown trees (the direct product of the post-reduce root
   histograms, compared BEFORE the iteration's model is committed);
   :func:`check_gang_digests` raises :class:`GangDivergence` on
   disagreement so the gang supervisor relaunches from the manifest
   instead of committing a forked model.

No ``jax`` import at module scope (same hazard boundary as
``checkpoint.py``/``gang.py``: supervisors import this before choosing
a backend); pytree walking is structural over tuples/lists/dicts.
"""
from __future__ import annotations

import threading
import zlib
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..utils import log

#: substring every integrity exception carries — retry.classify_error
#: files anything with this marker under the DATA_CORRUPTION class.
CORRUPTION_MARKER = "DATA_CORRUPTION"


class IntegrityError(RuntimeError):
    """Base of the corruption family; the message always carries the
    DATA_CORRUPTION marker so string-level classification (the same
    convention FaultInjected/OOMInjected use) works across process
    boundaries."""

    def __init__(self, msg: str):
        if CORRUPTION_MARKER not in msg:
            msg = f"{CORRUPTION_MARKER}: {msg}"
        super().__init__(msg)


class NumericHealthError(IntegrityError):
    """A boosting iteration produced non-finite or wildly spiked
    numerics (NaN/Inf grad/hess/leaf outputs, loss spike). Retrying the
    same iteration is futile; the caller must roll back."""


class CanaryMismatch(IntegrityError):
    """A device route returned canary scores that differ bit-wise from
    the host-walk golden — the pack (device or host side) is corrupt."""


class GangDivergence(IntegrityError):
    """Ranks disagree on the post-reduce tree digest: at least one rank
    reduced different bits. Relaunch from the manifest; do not commit."""


# ---------------------------------------------------------------------------
# Fingerprints + canaries
# ---------------------------------------------------------------------------

def _walk_arrays(obj):
    """Yield every ndarray in a host pytree (tuples — incl. NamedTuples
    — lists, dicts and scalars; no jax dependency)."""
    if obj is None:
        return
    if isinstance(obj, np.ndarray):
        yield obj
        return
    if isinstance(obj, dict):
        for k in sorted(obj):
            yield from _walk_arrays(obj[k])
        return
    if isinstance(obj, (tuple, list)):
        for v in obj:
            yield from _walk_arrays(v)
        return
    if isinstance(obj, (int, float, bool, np.generic)):
        yield np.asarray(obj)


def crc32_fingerprint(tree) -> int:
    """CRC32 over every array's dtype, shape and bytes in ``tree``.

    Structure-sensitive (an array moved between leaves changes the
    digest) and cheap: one pass over host memory, no copies beyond
    non-contiguous leaves. This is the host mega-pack fingerprint —
    recorded at pack time, re-verified before any re-upload, so repair
    never pushes corrupt host bytes back to the device."""
    crc = 0
    for a in _walk_arrays(tree):
        crc = zlib.crc32(str((a.dtype.str, a.shape)).encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
    return crc & 0xFFFFFFFF


def canary_batch(n_features: int, rows: int = 16,
                 seed: int = 0) -> np.ndarray:
    """Deterministic canary rows for one feature width: f64 values that
    are exactly f32-representable (the serving tier's raw route demands
    it), derived from ``(seed, n_features)`` alone so every process —
    publisher, prober, chaos gate — regenerates identical bits. Small
    enough that the replay pads into the minimum row bucket: the probe
    rides shapes steady-state traffic already compiled, adding zero
    traces."""
    rng = np.random.default_rng(1_000_003 * (seed + 1) + n_features)
    x = rng.standard_normal((int(rows), int(n_features)))
    return x.astype(np.float32).astype(np.float64)


def corrupt_pack(host):
    """Return a copy of a host window/pack pytree with the sign bit of
    every leaf output of the FIRST tree slot flipped — the ``bitflip``
    fault's host-side payload. Slot 0 is always a real tree and its
    leaf outputs feed every request of the slot-0 tenant, so the
    corruption is deterministic AND guaranteed observable by a canary
    replay (a flip landing in pad bytes would be an injection that
    proves nothing). Works on both window layouts (binned ``PackedTree``
    — leaf values under ``.tree`` — and raw ``RawTreeArrays``)."""
    inner = getattr(host, "tree", None)
    carrier = inner if inner is not None else host
    lv = np.array(carrier.leaf_value, copy=True)
    lv[0] = np.negative(lv[0])
    carrier = carrier._replace(leaf_value=lv)
    return host._replace(tree=carrier) if inner is not None else carrier


# ---------------------------------------------------------------------------
# Training numeric health
# ---------------------------------------------------------------------------

class NumericHealthGuard:
    """Per-iteration numeric watchdog for the boosting loop.

    Three checks, all host-side floats (the caller reduces on device
    and hands tiny scalars over — one fused reduction dispatch per
    iteration, no [K, N] pulls):

    - :meth:`check_gradients`: NaN/Inf in the grad/hess sums poisons
      every histogram downstream; fail the iteration immediately.
    - :meth:`check_leaves`: NaN/Inf leaf outputs would be committed
      into the model text and served forever.
    - :meth:`observe_loss`: a rolling-window spike detector over the
      train/eval loss series — ``spike_factor`` × the rolling median
      (plus an absolute epsilon floor so near-zero converged losses
      don't false-positive) flags corruption that stays finite. The
      ``loss_spike`` fault site injects exactly this signature.

    All raises are :class:`NumericHealthError` → ``DATA_CORRUPTION``:
    not transient (the same window re-poisons), not fatal (the caller
    rolls back to the newest CRC-valid checkpoint and continues).
    """

    def __init__(self, window: int = 8, spike_factor: float = 100.0,
                 what: str = "training"):
        self.window = max(int(window), 2)
        self.spike_factor = float(spike_factor)
        self.what = what
        self._losses: List[float] = []

    def check_gradients(self, grad_sum: float, hess_sum: float,
                        iteration: int) -> None:
        if not (np.isfinite(grad_sum) and np.isfinite(hess_sum)):
            raise NumericHealthError(
                f"{self.what} iteration {iteration}: non-finite "
                f"gradient/hessian sums (grad_sum={grad_sum!r}, "
                f"hess_sum={hess_sum!r}) — the objective saw corrupt "
                "scores or labels; this iteration must not be "
                "committed")

    def check_leaves(self, leaf_values: np.ndarray,
                     iteration: int) -> None:
        if not np.isfinite(leaf_values).all():
            bad = int(np.count_nonzero(~np.isfinite(leaf_values)))
            raise NumericHealthError(
                f"{self.what} iteration {iteration}: {bad} non-finite "
                "leaf output(s) in the freshly grown tree — refusing "
                "to commit a model that scores NaN")

    def observe_loss(self, loss: float, iteration: int,
                     what: str = "loss") -> None:
        from . import faults
        if faults.check("loss_spike"):
            loss = (abs(loss) + 1.0) * self.spike_factor * 10.0
        if not np.isfinite(loss):
            raise NumericHealthError(
                f"{self.what} iteration {iteration}: non-finite {what} "
                f"({loss!r})")
        hist = self._losses
        if len(hist) >= self.window:
            med = float(np.median(hist[-self.window:]))
            if abs(loss) > self.spike_factor * max(abs(med), 1e-6):
                spiked = loss
                self._losses = []     # re-seed after the rollback
                raise NumericHealthError(
                    f"{self.what} iteration {iteration}: {what} spiked "
                    f"to {spiked!r} (> {self.spike_factor}× the rolling "
                    f"median {med!r} over the last {self.window} "
                    "observations) — numeric poisoning, roll back")
        hist.append(float(loss))
        if len(hist) > 4 * self.window:
            del hist[:-self.window]


# ---------------------------------------------------------------------------
# Gang agreement
# ---------------------------------------------------------------------------

def iteration_digest(host_trees) -> int:
    """CRC32 digest of one iteration's freshly grown tree(s): split
    features, thresholds/bins and leaf outputs. These arrays are pure
    functions of the post-reduce root histograms, so ranks whose
    reductions diverged produce different digests HERE — one iteration
    before the committed models fork. 8 bytes on the wire per rank."""
    crc = 0
    for t in host_trees:
        n = int(t.num_leaves)
        for name in ("split_feature", "threshold", "threshold_bin",
                     "left_child", "right_child", "leaf_value"):
            a = getattr(t, name, None)
            if a is None:
                continue
            a = np.ascontiguousarray(np.asarray(a)[:max(n - 1, 0)]
                                     if name != "leaf_value"
                                     else np.asarray(a)[:n])
            crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def check_gang_digests(digests: Sequence[int], iteration: int,
                       rank: Optional[int] = None,
                       what: str = "gang") -> None:
    """All ranks must report the same digest; raise
    :class:`GangDivergence` (listing every rank's value) otherwise.
    Pure function — the transport (allgather/allreduce) is the
    caller's; the smoke gates exercise this logic without a world."""
    vals = [int(d) & 0xFFFFFFFF for d in digests]
    if len(set(vals)) <= 1:
        return
    who = f" (this rank: {rank})" if rank is not None else ""
    listing = ", ".join(f"r{i}={v:08x}" for i, v in enumerate(vals))
    raise GangDivergence(
        f"{what} iteration {iteration}: post-reduce tree digests "
        f"diverged across ranks{who}: {listing} — at least one rank "
        "reduced different bits; refusing to commit a forked model "
        "(relaunch from the newest committed manifest)")


def digest_reduction(digest: int) -> np.ndarray:
    """One rank's digest encoded for an allreduce-SUM transport (the
    only collective every injected world guarantees): the crc32 split
    into two 16-bit halves plus their squares, ``[hi, lo, hi², lo²]``
    f64. All values stay < 2**32, so a world's sums are exact in f64
    and :func:`check_digest_reduction` can decide agreement from the
    sums alone — no allgather needed, and every rank reaches the SAME
    verdict from the same reduced bytes."""
    d = int(digest) & 0xFFFFFFFF
    hi, lo = float(d >> 16), float(d & 0xFFFF)
    return np.asarray([hi, lo, hi * hi, lo * lo], np.float64)


def check_digest_reduction(total: np.ndarray, world: int, digest: int,
                           iteration: int, rank: Optional[int] = None,
                           what: str = "gang") -> None:
    """Verify an allreduce-summed :func:`digest_reduction`: per half,
    ``world × Σd² == (Σd)²`` holds iff every rank contributed the same
    value (Cauchy–Schwarz equality; sums are exact — each half is
    < 2**16, so ``world × Σd²`` fits f64 for any real world size).
    Raises :class:`GangDivergence` otherwise. Deterministic across
    ranks: the verdict is a pure function of the reduced array."""
    t = np.asarray(total, np.float64).reshape(-1)
    w = max(int(world), 1)
    agree = (w * t[2] == t[0] * t[0]) and (w * t[3] == t[1] * t[1])
    if agree:
        return
    who = f" (this rank: {rank}, digest {int(digest):08x})" \
        if rank is not None else ""
    raise GangDivergence(
        f"{what} iteration {iteration}: post-reduce tree digests "
        f"diverged across {w} ranks{who} — at least one rank reduced "
        "different bits; refusing to commit a forked model (relaunch "
        "from the newest committed manifest)")


# ---------------------------------------------------------------------------
# Background probe
# ---------------------------------------------------------------------------

class IntegrityProbe:
    """Always-on background canary prober (the steady-state sibling of
    ``DegradeControl._probe_loop``, which only runs while degraded).

    Runs ``fn()`` every ``interval_s`` seconds until closed; ``fn`` owns
    detection/quarantine/repair and must never raise for control flow —
    an escaped exception is logged and the cadence continues (a broken
    prober must not take serving down; it fails toward MORE probing,
    not less)."""

    def __init__(self, fn: Callable[[], None], interval_s: float,
                 what: str = "serving"):
        self._fn = fn
        self._interval = float(interval_s)
        self._close_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._what = what
        if self._interval > 0:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"lgbm-{what}-integrity-probe")
            self._thread.start()

    def _loop(self) -> None:
        while not self._close_evt.wait(self._interval):
            try:
                self._fn()
            except Exception as e:  # noqa: BLE001 — keep probing
                log.warning(f"{self._what} integrity probe error "
                            f"(probing continues): {e!r}")

    def close(self) -> None:
        self._close_evt.set()
        t = self._thread
        if t is not None:
            t.join(2.0)


def parity_equal(a, b) -> bool:
    """Bit-for-bit score comparison (NaN-safe, shape-strict) — the
    canary acceptance predicate. ``array_equal`` with NaN equality:
    a golden that legitimately contains NaN (it never should) must not
    read as a permanent mismatch loop."""
    a = np.asarray(a)
    b = np.asarray(b)
    return a.shape == b.shape and bool(
        np.array_equal(a, b, equal_nan=True))
