"""Fault-tolerant training runtime.

Three cooperating pieces (ISSUE 2; motivated by BENCH_r01-r05 all dying
with ``device_unreachable`` and losing every iteration of progress):

- :mod:`.retry` — a reusable retry policy (bounded attempts,
  decorrelated-jitter backoff, overall deadline) with an error
  classifier that knows which jax/XLA failures are transient
  (``UNAVAILABLE``, ``DEADLINE_EXCEEDED``, timeouts). Applied to
  ``distributed.init_distributed``, the injected-collective call sites,
  and the bench device probe; ``tpu_fallback_to_cpu=true`` degrades to
  CPU instead of aborting when the device never comes up.
- :mod:`.checkpoint` — atomic checkpoint writes (tmp + fsync + rename,
  CRC32 footer) of the full training state: model string plus loop
  state (iteration, best_iteration/best_score, eval history, bagging
  RNG snapshots). Resume auto-selects the newest *valid* checkpoint;
  corrupt/partial files are detected by CRC and skipped.
- :mod:`.faults` — a fault-injection harness (``LGBM_TPU_FAULTS`` env
  var or context manager, mirroring the ``LGBM_TPU_GUARDS`` install
  pattern) that injects transient failures into collectives, device
  probes, checkpoint writes, heartbeat liveness (``hang``) and compile
  duration (``slow_compile``), so the retry, atomicity and supervision
  guarantees are testable on CPU in tier-1.
- :mod:`.heartbeat` / :mod:`.supervisor` — phase-tagged liveness
  (ISSUE 4): instrumented children write crash-safe heartbeats
  (``compiling``/``warmup``/``measuring``/``iter N``), supervisors
  replace blind wall-clock slots with phase-aware stall deadlines
  (:class:`DeviceStallError` is transient under the retry policy), and
  an in-training watchdog raises instead of hanging forever at a
  wedged device sync.
- :mod:`.gang` — the multi-process extension (ISSUE 10): per-rank
  heartbeat supervision (:class:`~.gang.GangSupervisor` SIGTERMs the
  survivors of a dead rank instead of letting them wedge in a
  collective), coordinated gang manifests (world size + per-rank shard
  digests committed per checkpoint; resume refuses torn/mixed-world
  sets loudly), and bounded whole-gang auto-relaunch
  (:func:`~.gang.run_supervised` /
  ``distributed.launch_local(supervised=True)``).

jax is never imported at module import time (mirrors analysis/guards.py:
the CLI and host-side tools must be able to import this package without
initializing a backend).
"""
from .retry import (RetryError, RetryPolicy, is_transient_error,
                    retry_call)
from .checkpoint import (CheckpointError, atomic_write_text,
                         latest_valid_checkpoint, list_checkpoints,
                         prune_checkpoints, read_checkpoint,
                         write_checkpoint)
from .faults import (FaultInjected, active_plan, inject, install_from_env,
                     maybe_fail)
from .heartbeat import (DeviceStallError, Heartbeat, HeartbeatRecord,
                        StallPolicy, TrainingWatchdog)
from .supervisor import StillAlive, watch_child
from .gang import (GangError, GangSupervisor, GangTimeout,
                   latest_valid_manifest, run_supervised, write_manifest)

__all__ = [
    "GangError", "GangSupervisor", "GangTimeout", "run_supervised",
    "write_manifest", "latest_valid_manifest",
    "RetryPolicy", "RetryError", "retry_call", "is_transient_error",
    "CheckpointError", "atomic_write_text", "write_checkpoint",
    "read_checkpoint", "latest_valid_checkpoint", "list_checkpoints",
    "prune_checkpoints",
    "FaultInjected", "inject", "install_from_env", "maybe_fail",
    "active_plan",
    "DeviceStallError", "Heartbeat", "HeartbeatRecord", "StallPolicy",
    "TrainingWatchdog", "StillAlive", "watch_child",
]
