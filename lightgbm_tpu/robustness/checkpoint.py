"""Atomic checkpoints of full training state, with CRC-validated resume.

The reference treats interruption as normal (`snapshot_freq` +
`input_model` continued training, src/application/application.cpp); this
module upgrades that to crash-safe semantics:

- WRITES are atomic: payload goes to a tmp file in the target
  directory, is fsync'd, then renamed over the final name (POSIX rename
  atomicity), and the directory is fsync'd so the entry survives a
  crash. A kill at ANY byte leaves either the previous checkpoint set
  intact or a stray ``*.tmp.*`` file that recovery ignores — never a
  torn final file.
- READS are validated: every checkpoint carries a CRC32 + length
  footer over the payload; corrupt or truncated files are detected and
  skipped (with a warning) in favor of the next-newest valid one.

Checkpoint payload = one JSON "loop state" line (iteration,
best_iteration/best_score, eval history, bagging RNG snapshots from
models/gbdt.py) followed by the LightGBM-format model string
(io/model_io.py), so a checkpoint doubles as a loadable model file.

The ``write_kill`` fault class (robustness/faults.py) fires mid-write —
after roughly half the payload bytes are flushed, before the rename —
so tier-1 can prove the atomicity contract on CPU.
"""
from __future__ import annotations

import errno
import json
import os
import re
import zlib
from typing import Dict, List, Optional, Tuple

from ..utils import log
from . import faults

MAGIC = "LGBM_TPU_CKPT v1"
_FOOTER_RE = re.compile(
    rb"\n#CRC32=([0-9a-f]{8}) LEN=(\d+)\n$")
_CKPT_RE = re.compile(r"^ckpt_(\d{9})\.lgbmckpt$")


class CheckpointError(Exception):
    """A checkpoint file failed validation (CRC/length/parse)."""


def _json_default(o):
    # numpy scalars inside RNG states / eval history
    for attr in ("item",):
        if hasattr(o, attr):
            return o.item()
    raise TypeError(f"not JSON-serializable: {type(o)!r}")


# ---------------------------------------------------------------------------
# Atomic writes
# ---------------------------------------------------------------------------

def atomic_write_text(path: str, text: str, crc_footer: bool = False
                      ) -> None:
    """Write ``text`` to ``path`` atomically (tmp + fsync + rename +
    dir fsync). With ``crc_footer=True`` a CRC32+length footer line is
    appended (the checkpoint validation contract).

    Honors the ``write_kill`` injected fault: the kill fires after a
    partial flush of the tmp file, before the rename — the final path
    is never touched by a killed write. The ``disk_full`` fault fires
    at the same point as ``OSError(ENOSPC)`` — a disk that filled
    mid-payload; the stale tmp file is removed (freeing what it did
    claim) and the error propagates for the caller to classify."""
    if crc_footer:
        payload = text.encode("utf-8")
        text = text + (f"\n#CRC32={zlib.crc32(payload) & 0xffffffff:08x}"
                       f" LEN={len(payload)}\n")
    data = text.encode("utf-8")
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    closed = False
    try:
        try:
            half = len(data) // 2
            os.write(fd, data[:half])
            # injected kill-9 point: partial tmp bytes are on disk,
            # final file untouched
            faults.maybe_fail("write_kill")
            # injected/real ENOSPC point: same mid-payload spot
            faults.maybe_fail("disk_full")
            os.write(fd, data[half:])
            os.fsync(fd)
        except OSError as e:
            if e.errno == errno.ENOSPC:
                # a full disk must not also LEAK the partial tmp file:
                # reclaim it so the caller's prune-and-retry has the
                # bytes it just freed
                os.close(fd)
                closed = True
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            raise
    finally:
        if not closed:
            os.close(fd)
    os.replace(tmp, path)
    dfd = os.open(d, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


# ---------------------------------------------------------------------------
# Checkpoint format
# ---------------------------------------------------------------------------

def checkpoint_name(iteration: int) -> str:
    return f"ckpt_{int(iteration):09d}.lgbmckpt"


def write_checkpoint(directory: str, state: Dict,
                     keep_last: Optional[int] = None) -> str:
    """Atomically persist ``state`` (must carry ``iteration`` and
    ``model``; everything else is loop state) and return the path.

    Disk-full survival (ISSUE 19): an ``ENOSPC`` from the atomic
    writer — the publish channel's disk filled mid-write — prunes
    checkpoints beyond ``keep_last`` (plus tmp litter) to reclaim
    space and retries ONCE; a second ENOSPC propagates loudly. The
    committed generation set is never touched by the failure: the
    atomic writer's tmp-file discipline means a failed write leaves
    every existing checkpoint intact, and the prune keeps the newest
    ``keep_last`` — the retry can only ADD a newer generation.
    ``keep_last=None`` keeps the prior fail-fast behavior (callers
    that manage retention themselves).

    The ``bitflip:where=ckpt`` fault corrupts one byte of the COMMITTED
    file after a successful write: the CRC32 footer catches it on the
    next validated read, so recovery anchors on the previous valid
    generation (tested via ``latest_valid_checkpoint``)."""
    it = int(state["iteration"])
    model = state["model"]
    loop = {k: v for k, v in state.items() if k != "model"}
    header = json.dumps({"magic": MAGIC, **loop},
                        default=_json_default)
    path = os.path.join(directory, checkpoint_name(it))
    try:
        atomic_write_text(path, header + "\n" + model, crc_footer=True)
    except OSError as e:
        if e.errno != errno.ENOSPC or keep_last is None:
            raise
        removed = prune_checkpoints(directory, max(int(keep_last), 1))
        log.warning(
            f"checkpoint write hit ENOSPC ({e}); pruned {removed} "
            f"old checkpoint file(s) beyond keep_last={keep_last} "
            "and retrying once — a second failure is fatal")
        atomic_write_text(path, header + "\n" + model, crc_footer=True)
    if faults.check("bitflip", where="ckpt"):
        _flip_committed_byte(path)
    return path


def _flip_committed_byte(path: str) -> None:
    """``bitflip:where=ckpt`` payload: XOR one mid-payload byte of the
    committed checkpoint file in place — silent at-rest corruption the
    CRC footer must catch on the next validated read."""
    try:
        with open(path, "r+b") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            pos = size // 2
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0x01]))
        log.warning(f"injected bitflip: corrupted one byte of {path}")
    except OSError as e:   # injection best-effort; never crash a write
        log.warning(f"bitflip injection failed on {path}: {e}")


def read_validated_text(path: str) -> str:
    """CRC-validated payload of an ``atomic_write_text(crc_footer=True)``
    file. Raises CheckpointError on a missing/invalid footer, length or
    CRC mismatch — shared by checkpoint reads and the gang-manifest
    reads (robustness/gang.py), so there is exactly one copy of the
    footer validation.

    Works on raw bytes — CRC validation runs BEFORE any decoding, so
    corruption that breaks UTF-8 is still reported as a checkpoint
    error, never as a codec crash."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CheckpointError(f"unreadable checkpoint {path}: {e}")
    m = _FOOTER_RE.search(blob)
    if m is None:
        raise CheckpointError(
            f"{path}: missing CRC footer (truncated or not a "
            "checkpoint)")
    payload = blob[:m.start()]
    if len(payload) != int(m.group(2)):
        raise CheckpointError(
            f"{path}: length mismatch (footer says "
            f"{int(m.group(2))}, payload is {len(payload)})")
    crc = zlib.crc32(payload) & 0xffffffff
    if crc != int(m.group(1), 16):
        raise CheckpointError(
            f"{path}: CRC mismatch (footer "
            f"{m.group(1).decode()}, computed {crc:08x})")
    try:
        return payload.decode("utf-8")
    except UnicodeDecodeError as e:
        raise CheckpointError(f"{path}: undecodable payload: {e}")


def read_checkpoint(path: str) -> Dict:
    """Parse + validate one checkpoint file. Raises CheckpointError on
    a missing/invalid footer, CRC mismatch, or unparseable header."""
    body = read_validated_text(path)
    nl = body.find("\n")
    header_line = body if nl < 0 else body[:nl]
    try:
        loop = json.loads(header_line)
    except json.JSONDecodeError as e:
        raise CheckpointError(f"{path}: bad header JSON: {e}")
    if loop.get("magic") != MAGIC:
        raise CheckpointError(
            f"{path}: wrong magic {loop.get('magic')!r}")
    loop.pop("magic", None)
    loop["model"] = "" if nl < 0 else body[nl + 1:]
    return loop


def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """(iteration, path) pairs, newest first. Ignores tmp litter from
    killed writes and anything not matching the checkpoint name."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)),
                        os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def latest_valid_checkpoint(directory: str
                            ) -> Optional[Tuple[str, Dict]]:
    """Newest checkpoint that passes CRC validation, or None.

    Corrupt/partial files are SKIPPED with a warning (never deleted —
    they are evidence), falling back to the next-newest valid one."""
    for it, path in list_checkpoints(directory):
        try:
            state = read_checkpoint(path)
        except CheckpointError as e:
            log.warning(f"skipping invalid checkpoint: {e}")
            continue
        return path, state
    return None


# litter from a killed atomic_write_text: <final name>.tmp.<pid>
_TMP_RE = re.compile(r"^(.*)\.tmp\.\d+$")


def prune_numbered(directory: str, pattern, keep_last: int) -> int:
    """Shared retention sweep: keep the newest ``keep_last`` files in
    ``directory`` whose basename matches ``pattern`` (a compiled regex;
    group 1 is the ordering number), delete older matches, and delete
    any atomic-write tmp litter whose final name matches the pattern.
    Used by both checkpoint retention and the CLI's snapshot pruning so
    there is exactly one copy of the keep-last/tmp-cleanup logic.
    Returns how many files were removed."""
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    kept = []
    for name in names:
        tm = _TMP_RE.match(name)
        if tm is not None:
            if pattern.match(tm.group(1)):
                try:
                    os.remove(os.path.join(directory, name))
                    removed += 1
                except OSError:
                    pass
            continue
        m = pattern.match(name)
        if m:
            kept.append((int(m.group(1)), name))
    if keep_last >= 1:
        kept.sort(reverse=True)
        for _, name in kept[keep_last:]:
            try:
                os.remove(os.path.join(directory, name))
                removed += 1
            except OSError:
                pass
    return removed


def prune_checkpoints(directory: str, keep_last: int) -> int:
    """Delete all but the newest ``keep_last`` checkpoints (and any
    stale tmp litter). Returns how many files were removed."""
    return prune_numbered(directory, _CKPT_RE, keep_last)


# ---------------------------------------------------------------------------
# Booster <-> checkpoint state
# ---------------------------------------------------------------------------

def booster_state(booster, iteration: int,
                  eval_history: Optional[Dict] = None) -> Dict:
    """Full training state of a live Booster at ``iteration``."""
    eng = booster._engine
    return {
        "iteration": int(iteration),
        "model": booster.model_to_string(),
        "best_iteration": int(getattr(booster, "best_iteration", -1)),
        "best_score": getattr(booster, "best_score", {}) or {},
        "eval_history": eval_history or {},
        "rng": (eng.rng_snapshot()
                if hasattr(eng, "rng_snapshot") else {}),
    }


def restore_into_booster(booster, state: Dict) -> None:
    """Apply the loop-state half of a checkpoint onto a freshly
    constructed Booster (the model half goes through init_model /
    init_from_model as usual)."""
    booster.best_iteration = int(state.get("best_iteration", -1))
    if state.get("best_score"):
        booster.best_score = state["best_score"]
    eng = booster._engine
    if hasattr(eng, "restore_rng"):
        eng.restore_rng(state.get("rng") or {})
