"""Fault-tolerant training gang (ISSUE 10 tentpole).

PR7's sharded ingestion made multi-process training real, but its
failure story was a blunt whole-gang ``timeout=600`` kill: a rank dying
mid-run wedged every survivor inside a gloo collective, the supervisor
learned nothing about *why*, and the kill-and-relaunch-resume path was a
manual ``@slow`` test. This module extends the single-process
supervision stack (heartbeats, :class:`~.heartbeat.StallPolicy`,
:class:`~.retry.RetryPolicy`, CRC checkpoints, the fault grammar) from
one child to N ranks:

- **Per-rank supervision** (:class:`GangSupervisor`): every rank writes
  the existing phase-tagged heartbeats to a per-rank file
  (:func:`~.heartbeat.rank_path` — models/gbdt.py installs the
  rank-suffixed path automatically in a multi-process world, and the
  sharded-ingest constructor beats from the first collective). The
  supervisor generalizes ``supervisor.watch_child`` to N children,
  classifying each rank stall-vs-alive-vs-dead under the shared
  StallPolicy; on any rank death or classified stall it SIGTERMs the
  survivors (never SIGKILL — the claim-holder wedge discipline) instead
  of letting them hang in a collective, and raises :class:`GangError`
  carrying a per-rank diagnosis (last phase, beat age, exit codes).
- **Coordinated checkpoints** (gang manifests): sharded runs commit a
  per-iteration manifest next to each CRC checkpoint — world size,
  per-rank row counts, per-rank sampled shard-content digests
  (io/dataset_core.py), the checkpoint it commits — written with the
  same atomic tmp+fsync+rename+CRC machinery. A manifest *commits* its
  checkpoint: :func:`latest_valid_manifest` skips any manifest whose
  CRC fails or whose referenced checkpoint is missing, corrupt, or
  disagrees on the iteration (a torn commit), and
  :func:`validate_and_select_resume` refuses mixed-world or
  different-sharding checkpoint sets loudly with a per-rank diagnosis.
- **Auto-relaunch** (:func:`run_supervised`, reachable as
  ``distributed.launch_local(supervised=True)``): a failed gang is
  relaunched whole under a bounded RetryPolicy — each rank resumes from
  the newest valid manifest via the workers' ordinary
  ``resume_from=`` path — so one rank death costs one resume, not the
  session. :class:`GangError` carries ``DEADLINE_EXCEEDED`` so the
  shared transient classifier treats gang failure as retryable.

The collective-liveness half (a rank blocked on a dead peer's
allgather raising :class:`~..distributed.CollectiveTimeout` within a
deadline instead of wedging) lives in distributed.py; a rank wedged
inside a *jitted* collective is covered by the PR4 in-training watchdog
(beat age → ``EXIT_STALLED``), which this supervisor classifies.

**Gang agreement** (ISSUE 19): silent model divergence — ranks that
are all alive, all beating, but no longer training the SAME model
(bit-rot in one rank's committed trees, a miscompiled reduction, a
corrupted host buffer) — is invisible to liveness supervision. The
integrity leg closes it: every ``tpu_integrity_digest_every``
iterations each rank folds its freshly committed trees into a
fingerprint (robustness/integrity.py ``iteration_digest``) and the
gang allreduces the :func:`~.integrity.digest_reduction` moments;
any disagreement makes EVERY rank raise
:class:`~.integrity.GangDivergence` (a nonzero exit) at the same
iteration boundary. To this supervisor a divergence is just N ranks
dying loudly — the ordinary relaunch path resumes the whole gang from
the newest valid manifest, whose checkpoint predates the divergence,
so the rot is discarded rather than trained forward.

No jax import anywhere in this module — same hazard boundary as
supervisor.py: a supervisor must never initialize a backend.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import tempfile
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from ..utils import log
from . import checkpoint as _ckpt
from . import faults
from .heartbeat import (ALIVE, SILENT, STALLED, WAITING,  # noqa: F401
                        EXIT_STALLED, HeartbeatRecord, StallPolicy,
                        rank_path, read)
from .retry import RetryPolicy, retry_call

__all__ = [
    "GangError", "GangTimeout", "GangSupervisor", "run_supervised",
    "rank_diagnosis", "MANIFEST_MAGIC", "manifest_name",
    "write_manifest", "read_manifest", "list_manifests",
    "latest_valid_manifest", "prune_manifests",
    "validate_and_select_resume", "ENV_GANG_RELAUNCHES",
]

# how many RELAUNCHES a supervised gang earns after its first attempt
# (total attempts = relaunches + 1); overridable per launch via the
# attempts= argument
ENV_GANG_RELAUNCHES = "LGBM_TPU_GANG_RELAUNCHES"
DEFAULT_GANG_RELAUNCHES = 2


class GangError(Exception):
    """The gang failed as a unit: a rank died, self-watchdogged, was
    classified hung, or the whole gang overran its deadline. Survivors
    were SIGTERMed (never SIGKILLed). The message carries
    ``DEADLINE_EXCEEDED`` so :func:`~.retry.is_transient_error`
    classifies it transient — the relaunch-from-manifest policy in
    :func:`run_supervised` retries it under bounded attempts.

    ``reports`` holds one ``(rank, rc, HeartbeatRecord|None)`` triple
    per rank (rc None = still alive when the gang was torn down)."""

    def __init__(self, msg: str,
                 reports: Sequence[Tuple[int, Optional[int],
                                         Optional[HeartbeatRecord]]] = ()):
        super().__init__(f"DEADLINE_EXCEEDED: {msg}")
        self.reports = list(reports)


class GangTimeout(subprocess.TimeoutExpired):
    """``launch_local``'s blunt-timeout error, upgraded with per-rank
    forensics: subclasses TimeoutExpired so every existing caller's
    ``except subprocess.TimeoutExpired`` still catches it, but the
    message now answers "why did it die" — each rank's last phase and
    beat age instead of nothing."""

    def __init__(self, cmd, timeout: float, diagnosis: str = ""):
        super().__init__(cmd, timeout)
        self.diagnosis = diagnosis

    def __str__(self) -> str:
        base = super().__str__()
        return f"{base}\n{self.diagnosis}" if self.diagnosis else base


# ---------------------------------------------------------------------------
# Per-rank diagnosis (the r03-style forensics gap, gang edition)
# ---------------------------------------------------------------------------

def _describe_rc(rc: Optional[int]) -> str:
    if rc is None:
        return "alive"
    if rc == EXIT_STALLED:
        return f"rc={rc} (self-watchdogged: wedged at a device sync)"
    if rc == faults.EXIT_RANK_KILLED:
        return f"rc={rc} (injected rank_kill)"
    return f"rc={rc}"


def rank_diagnosis(hb_paths: Sequence[str],
                   rcs: Optional[Sequence[Optional[int]]] = None,
                   clock: Callable[[], float] = time.monotonic) -> str:
    """One line per rank: exit state, last phase/progress, beat and
    keepalive ages. Heartbeat timestamps are CLOCK_MONOTONIC, which is
    system-wide on Linux, so ages computed here are directly comparable
    with the writers' clocks."""
    now = clock()
    lines = []
    for r, path in enumerate(hb_paths):
        state = _describe_rc(rcs[r] if rcs is not None else None)
        rec = read(path)
        if rec is None:
            lines.append(f"  rank {r}: {state}; no heartbeat written "
                         f"({path})")
        else:
            lines.append(
                f"  rank {r}: {state}; last phase {rec.phase!r}/"
                f"{rec.progress}, beat {now - rec.t:.1f}s ago, "
                f"keepalive {now - rec.ka:.1f}s ago (pid {rec.pid})")
    return "\n".join(lines)


def gang_hb_paths(hb_base: str, world: int) -> List[str]:
    """The per-rank heartbeat paths a supervised gang writes: the bare
    base for a world of one (single-process workloads keep their
    existing file), ``rank_path(base, r)`` otherwise — the SAME
    convention models/gbdt.py and the sharded-ingest constructor use to
    pick their write path from ``LGBM_TPU_HEARTBEAT``."""
    if world <= 1:
        return [hb_base]
    return [rank_path(hb_base, r) for r in range(world)]


# ---------------------------------------------------------------------------
# GangSupervisor: watch_child generalized to N ranks
# ---------------------------------------------------------------------------

class GangSupervisor:
    """Supervise a gang of rank processes against per-rank heartbeats.

    ``procs`` are ``subprocess.Popen``-likes in rank order (objects with
    ``poll``/``pid``/``terminate``/``stdout`` — tests pass fakes).
    Stdout pipes are drained by daemon threads so a chatty rank can
    never deadlock on a full pipe while the supervisor polls.

    :meth:`watch` returns ``[(rc, combined_output), ...]`` when every
    rank exits 0, and raises :class:`GangError` — after SIGTERMing all
    survivors — when any rank dies non-zero, self-watchdogs
    (:data:`EXIT_STALLED`), is classified ``stalled``/``silent`` under
    the StallPolicy, or the gang deadline passes. SIGKILL is never
    sent: on real hardware the ranks are claim-holders and the
    mid-compile SIGKILL is the documented machine-wide wedge trigger.
    """

    def __init__(self, procs: Sequence, hb_base: str,
                 hb_paths: Optional[Sequence[str]] = None,
                 policy: Optional[StallPolicy] = None,
                 poll: float = 0.5,
                 label: str = "gang",
                 term_grace: float = 15.0,
                 escalate_kill: bool = False,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 on_status: Optional[Callable] = None):
        self.procs = list(procs)
        n = len(self.procs)
        self.hb_paths = (list(hb_paths) if hb_paths is not None
                         else gang_hb_paths(hb_base, n))
        if len(self.hb_paths) != n:
            raise ValueError(
                f"{len(self.hb_paths)} heartbeat paths for {n} ranks")
        self.policy = policy if policy is not None else \
            StallPolicy.from_env()
        self.poll = float(poll)
        self.label = label
        self.term_grace = float(term_grace)
        # SIGKILL escalation after the SIGTERM grace. Default OFF — on
        # real hardware the ranks are device claim-holders and the
        # mid-compile SIGKILL is the documented machine-wide wedge
        # trigger. CPU-ONLY gangs (virtual-device rehearsals, the bench
        # ingest gang, smokes/tests) should pass True: a rank wedged in
        # a gloo collective can sit out SIGTERM (the distributed
        # runtime's handler hangs on the dead barrier), and leaking it
        # would poison the relaunch's cores.
        self.escalate_kill = bool(escalate_kill)
        self.clock = clock
        self.sleep = sleep
        self.on_status = on_status
        self._outputs: List[List[str]] = [[] for _ in range(n)]
        self._readers: List[Optional[threading.Thread]] = [None] * n
        for r, p in enumerate(self.procs):
            if getattr(p, "stdout", None) is not None:
                t = threading.Thread(target=self._drain, args=(r,),
                                     name=f"lgbm-gang-out-r{r}",
                                     daemon=True)
                t.start()
                self._readers[r] = t

    def _drain(self, r: int) -> None:
        try:
            for line in self.procs[r].stdout:
                self._outputs[r].append(line)
        except (OSError, ValueError):   # pipe torn down mid-read
            pass

    def output(self, r: int) -> str:
        return "".join(self._outputs[r])

    def _join_readers(self, timeout: float = 2.0) -> None:
        for t in self._readers:
            if t is not None:
                t.join(timeout=timeout)

    # -- teardown ------------------------------------------------------
    def _terminate_all(self, rcs: List[Optional[int]]) -> None:
        """SIGTERM every live rank, then wait up to ``term_grace`` for
        the gang to drain. A rank that ignores SIGTERM is left running
        and noted — never SIGKILLed (wedge discipline)."""
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = self.clock() + max(self.term_grace, 1.0)
        while self.clock() < deadline:
            if all(p.poll() is not None for p in self.procs):
                break
            self.sleep(min(self.poll, 0.2))
        for r, p in enumerate(self.procs):
            rcs[r] = p.poll()
            if rcs[r] is None:
                if self.escalate_kill:
                    log.warning(
                        f"{self.label}: rank {r} (pid={p.pid}) ignored "
                        f"SIGTERM for {self.term_grace:.0f}s; "
                        "escalating to SIGKILL (CPU gang)")
                    try:
                        p.kill()
                        p.wait(timeout=5.0)
                        rcs[r] = p.poll()
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                else:
                    log.warning(
                        f"{self.label}: rank {r} (pid={p.pid}) ignored "
                        f"SIGTERM for {self.term_grace:.0f}s; leaving "
                        "it running (no SIGKILL — wedge discipline)")

    def _fail(self, reason: str, rcs: List[Optional[int]]) -> None:
        self._terminate_all(rcs)
        self._join_readers()
        diag = rank_diagnosis(self.hb_paths, rcs, clock=self.clock)
        reports = [(r, rcs[r], read(self.hb_paths[r]))
                   for r in range(len(self.procs))]
        raise GangError(
            f"{self.label}: {reason}; survivors SIGTERMed. "
            f"Per-rank diagnosis:\n{diag}", reports)

    # -- the watch loop ------------------------------------------------
    def watch(self, timeout: Optional[float] = None) -> List[Tuple[int,
                                                                   str]]:
        n = len(self.procs)
        start = self.clock()
        deadline = start + timeout if timeout else None
        rcs: List[Optional[int]] = [None] * n
        stall_since: List[Optional[float]] = [None] * n
        last_verdict = [WAITING] * n
        while True:
            for r, p in enumerate(self.procs):
                if rcs[r] is None:
                    rc = p.poll()
                    if rc is None:
                        continue
                    rcs[r] = rc
                    if rc == EXIT_STALLED:
                        self._fail(f"rank {r} self-watchdogged "
                                   f"(rc={EXIT_STALLED}: its loop was "
                                   "wedged at a device sync)", rcs)
                    if rc != 0:
                        self._fail(f"rank {r} died ({_describe_rc(rc)})",
                                   rcs)
            if all(rc is not None for rc in rcs):
                self._join_readers()
                return [(rcs[r], self.output(r)) for r in range(n)]
            now = self.clock()
            for r, p in enumerate(self.procs):
                if rcs[r] is not None:
                    continue
                rec = read(self.hb_paths[r])
                verdict = self.policy.classify(rec, now, start)
                if verdict != last_verdict[r]:
                    if self.on_status is not None:
                        self.on_status(r, verdict, rec)
                    last_verdict[r] = verdict
                if verdict in (STALLED, SILENT):
                    if stall_since[r] is None:
                        stall_since[r] = now
                    # one poll of hysteresis: a beat landing between our
                    # read and the verdict must not tear the gang down
                    elif now - stall_since[r] >= self.poll:
                        phase = rec.phase if rec is not None else \
                            "<no heartbeat>"
                        self._fail(
                            f"rank {r} (pid={p.pid}) classified hung: "
                            f"{verdict} in phase {phase!r}", rcs)
                else:
                    stall_since[r] = None
            if deadline is not None and now >= deadline:
                self._fail(f"gang exceeded its {timeout:.0f}s deadline",
                           rcs)
            self.sleep(self.poll)


# ---------------------------------------------------------------------------
# Auto-relaunch: one rank death costs one resume, not the session
# ---------------------------------------------------------------------------

def default_attempts(env=None) -> int:
    e = env if env is not None else os.environ
    v = (e.get(ENV_GANG_RELAUNCHES) or "").strip()
    relaunches = int(v) if v else DEFAULT_GANG_RELAUNCHES
    return max(1, relaunches + 1)


def run_supervised(argv: Sequence[str], num_processes: int, *,
                   cpu_devices_per_process: int = 0,
                   coordinator_port: Optional[int] = None,
                   timeout: float = 600.0,
                   env_extra: Optional[dict] = None,
                   attempts: Optional[int] = None,
                   stall_policy: Optional[StallPolicy] = None,
                   poll: float = 0.5,
                   label: str = "gang",
                   term_grace: float = 15.0,
                   escalate_kill: bool = False,
                   attempt_env: Optional[Callable[[int], dict]] = None,
                   on_status: Optional[Callable] = None
                   ) -> List[Tuple[int, str]]:
    """Launch ``argv`` × ``num_processes`` as one supervised gang and
    auto-relaunch it on failure (``launch_local(supervised=True)``).

    Each attempt gets a fresh coordinator port (unless pinned) and a
    fresh per-attempt heartbeat base exported as ``LGBM_TPU_HEARTBEAT``
    (a dead attempt's stale heartbeat file must never be classified as
    this attempt's silence); each rank writes
    ``rank_path(base, rank)`` — models/gbdt.py derives that path
    automatically in a multi-process world. On :class:`GangError` the
    WHOLE gang is relaunched under a bounded RetryPolicy
    (``attempts`` total; default ``LGBM_TPU_GANG_RELAUNCHES`` + 1 = 3):
    workers resume from the newest valid gang manifest through their
    ordinary ``resume_from=`` path, so the relaunch converges instead
    of restarting from zero.

    ``attempt_env(i)`` (0-based attempt index) merges extra environment
    per attempt — chaos harnesses use it to inject a fault plan into
    the first launch only (an env-installed plan re-arms its counters
    in every subprocess, which would otherwise kill every relaunch
    too). Returns ``[(rc, output), ...]`` in rank order on success;
    raises :class:`~.retry.RetryError` (last cause: the final
    :class:`GangError`) when every attempt failed.
    """
    from ..distributed import spawn_local
    from .heartbeat import ENV_HEARTBEAT

    if attempts is None:
        attempts = default_attempts()
    hb_dir = tempfile.mkdtemp(prefix="lgbm_gang_hb_")
    counter = {"i": -1}

    def _attempt():
        counter["i"] += 1
        i = counter["i"]
        extra = dict(env_extra or {})
        if attempt_env is not None:
            extra.update({k: str(v)
                          for k, v in (attempt_env(i) or {}).items()})
        hb_base = os.path.join(hb_dir, f"attempt{i}.hb")
        extra[ENV_HEARTBEAT] = hb_base
        if i:
            log.warning(
                f"{label}: relaunching the whole gang (attempt "
                f"{i + 1}/{attempts}) — workers resume from the newest "
                "valid gang manifest")
        procs = spawn_local(
            argv, num_processes, coordinator_port=coordinator_port,
            cpu_devices_per_process=cpu_devices_per_process,
            env_extra=extra)
        sup = GangSupervisor(procs, hb_base, policy=stall_policy,
                             poll=poll,
                             label=f"{label} (attempt {i + 1})",
                             term_grace=term_grace,
                             escalate_kill=escalate_kill,
                             on_status=on_status)
        return sup.watch(timeout=timeout)

    try:
        policy = RetryPolicy(max_attempts=attempts, base_delay=0.5,
                             max_delay=5.0, deadline=None)
        return retry_call(_attempt, policy=policy, what=label)
    finally:
        shutil.rmtree(hb_dir, ignore_errors=True)


# ---------------------------------------------------------------------------
# Gang manifests: coordinated checkpoints for sharded worlds
# ---------------------------------------------------------------------------

MANIFEST_MAGIC = "LGBM_TPU_GANG v1"
_MANIFEST_RE = re.compile(r"^gang_(\d{9})\.manifest$")


class ManifestError(_ckpt.CheckpointError):
    """A gang manifest failed validation (CRC/parse/fields)."""


def manifest_name(iteration: int) -> str:
    return f"gang_{int(iteration):09d}.manifest"


def write_manifest(directory: str, iteration: int,
                   checkpoint_name: str, shard) -> str:
    """Atomically commit the gang manifest for ``checkpoint_name``:
    world size, per-rank row counts, per-rank sampled shard-content
    digests (``ShardInfo.digests``), CRC footer. Written AFTER its
    checkpoint — the manifest IS the commit marker: a crash between the
    two leaves an uncommitted checkpoint that resume skips in favor of
    the newest manifested one."""
    digests = getattr(shard, "digests", None)
    if not digests:
        raise ValueError("shard carries no content digests — gang "
                         "manifests require a sharded-ingest dataset")
    rec = {
        "magic": MANIFEST_MAGIC,
        "iteration": int(iteration),
        "world": int(shard.world),
        "row_counts": [int(c) for c in shard.row_counts],
        "digests": [f"{int(d) & 0xffffffff:08x}" for d in digests],
        "checkpoint": str(checkpoint_name),
    }
    path = os.path.join(directory, manifest_name(iteration))
    _ckpt.atomic_write_text(path, json.dumps(rec), crc_footer=True)
    return path


def read_manifest(path: str) -> dict:
    """Parse + CRC-validate one manifest. Raises :class:`ManifestError`
    on a torn/corrupt/foreign file."""
    try:
        body = _ckpt.read_validated_text(path)
    except _ckpt.CheckpointError as e:
        raise ManifestError(str(e))
    try:
        man = json.loads(body)
    except json.JSONDecodeError as e:
        raise ManifestError(f"{path}: bad manifest JSON: {e}")
    if man.get("magic") != MANIFEST_MAGIC:
        raise ManifestError(f"{path}: wrong magic {man.get('magic')!r}")
    for key in ("iteration", "world", "row_counts", "digests",
                "checkpoint"):
        if key not in man:
            raise ManifestError(f"{path}: missing field {key!r}")
    if len(man["digests"]) != int(man["world"]) or \
            len(man["row_counts"]) != int(man["world"]):
        raise ManifestError(
            f"{path}: per-rank fields disagree with world="
            f"{man['world']}")
    return man


def list_manifests(directory: str) -> List[Tuple[int, str]]:
    """(iteration, path) pairs, newest first (tmp litter ignored)."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _MANIFEST_RE.match(name)
        if m:
            out.append((int(m.group(1)),
                        os.path.join(directory, name)))
    out.sort(reverse=True)
    return out


def latest_valid_manifest(directory: str
                          ) -> Optional[Tuple[dict, str]]:
    """Newest manifest that is COMMITTED: CRC-valid itself, and its
    referenced checkpoint exists, passes CRC, and agrees on the
    iteration. Torn commits (manifest without its checkpoint, or a
    checkpoint/manifest iteration mismatch) are skipped with a warning
    in favor of the next-newest — never resumed from.

    Returns ``(manifest_dict, checkpoint_path)`` or None."""
    for it, path in list_manifests(directory):
        try:
            man = read_manifest(path)
        except ManifestError as e:
            log.warning(f"skipping invalid gang manifest: {e}")
            continue
        ckpt_path = os.path.join(directory, man["checkpoint"])
        try:
            state = _ckpt.read_checkpoint(ckpt_path)
        except _ckpt.CheckpointError as e:
            log.warning(f"skipping uncommitted gang manifest "
                        f"{os.path.basename(path)}: its checkpoint "
                        f"failed validation ({e})")
            continue
        if int(state.get("iteration", -1)) != int(man["iteration"]):
            log.warning(
                f"skipping torn gang manifest {os.path.basename(path)}: "
                f"manifest says iteration {man['iteration']} but "
                f"{man['checkpoint']} holds iteration "
                f"{state.get('iteration')}")
            continue
        return man, ckpt_path
    return None


def prune_manifests(directory: str, keep_last: int) -> int:
    """Keep the newest ``keep_last`` manifests (+ drop atomic-write tmp
    litter) — same retention sweep as the checkpoints they commit."""
    return _ckpt.prune_numbered(directory, _MANIFEST_RE, keep_last)


def validate_and_select_resume(directory: str, shard,
                               selected_state: Optional[dict]
                               ) -> Optional[dict]:
    """Gang-resume gate for sharded worlds (called by engine.train after
    dataset construction, SPMD on every rank — the decision depends only
    on the shared checkpoint directory and this world's ShardInfo, so
    all ranks agree deterministically).

    - No checkpoints at all → None (fresh start).
    - Checkpoints but no committed manifest → FATAL: the set cannot be
      proven to belong to this sharding (disable via
      ``tpu_gang_manifest=false`` to resume a legacy set).
    - Manifest world/row-counts/digests disagreeing with the live
      ShardInfo → FATAL with a per-rank diagnosis naming every
      mismatching rank.
    - Otherwise: returns the loop state of the MANIFESTED checkpoint —
      which may be older than the newest raw checkpoint
      (``selected_state``) when the newest write's commit was torn;
      resuming from the manifested iteration is what keeps every rank
      (and every relaunch) agreeing on where training restarts.
    """
    have_ckpts = bool(_ckpt.list_checkpoints(directory))
    found = latest_valid_manifest(directory)
    if found is None:
        if have_ckpts:
            log.fatal(
                f"resume_from={directory!r}: the checkpoint set has no "
                "valid committed gang manifest, so it cannot be "
                "verified to belong to this sharded world "
                f"(world={shard.world}). Refusing to resume — a "
                "mixed-world or different-sharding resume silently "
                "trains on wrong data. Set tpu_gang_manifest=false "
                "only to resume a trusted legacy (pre-manifest) set.")
        return None
    man, ckpt_path = found
    if int(man["world"]) != int(shard.world):
        log.fatal(
            f"resume_from={directory!r}: gang manifest "
            f"{manifest_name(int(man['iteration']))} was written by a "
            f"world of {man['world']} but this gang has world="
            f"{shard.world} — refusing a mixed-world resume "
            "(relaunch with the original world size, or start fresh "
            "in a new directory)")
    live_counts = [int(c) for c in shard.row_counts]
    live_digests = [int(d) & 0xffffffff
                    for d in (getattr(shard, "digests", None) or ())]
    man_counts = [int(c) for c in man["row_counts"]]
    man_digests = [int(d, 16) for d in man["digests"]]
    bad = []
    for r in range(int(man["world"])):
        problems = []
        if man_counts[r] != live_counts[r]:
            problems.append(f"rows {man_counts[r]} != {live_counts[r]}")
        if live_digests and man_digests[r] != live_digests[r]:
            problems.append(f"shard digest {man_digests[r]:08x} != "
                            f"{live_digests[r]:08x}")
        if problems:
            bad.append(f"  rank {r}: " + ", ".join(problems))
    if bad:
        log.fatal(
            f"resume_from={directory!r}: the checkpoint set belongs to "
            "a DIFFERENT sharding of the data — refusing to resume. "
            "Per-rank diagnosis (manifest vs this run):\n"
            + "\n".join(bad))
    if selected_state is not None and \
            int(selected_state.get("iteration", -1)) == \
            int(man["iteration"]):
        # common case: the newest checkpoint IS the manifested one —
        # return the state the caller already read/parsed so the
        # engine keeps its Booster instead of rebuilding it
        state = selected_state
    else:
        state = _ckpt.read_checkpoint(ckpt_path)
        if selected_state is not None:
            log.warning(
                f"newest checkpoint (iteration "
                f"{selected_state.get('iteration')}) has no committed "
                f"gang manifest (torn commit); resuming from the "
                f"manifested iteration {man['iteration']} so every "
                "rank and every relaunch agree on the restart point")
    log.info(f"gang manifest validated: world={man['world']}, "
             f"resuming at iteration {man['iteration']} "
             f"({man['checkpoint']})")
    return state
