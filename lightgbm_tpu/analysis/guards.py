"""Runtime dispatch guards for the training hot path (opt-in).

jaxlint (the static side of this subsystem) catches what the AST can see;
these guards catch the same hazard classes at runtime:

- :class:`CompileCounter` / :func:`compile_budget` count jit retrace/
  lower events, so a training loop that recompiles per iteration fails
  its budget instead of silently running 100x slow. Counting hooks the
  "Compiling <name> ..." records jax's lowering path emits (logger
  ``jax._src.interpreters.pxla``; jax 0.4.x) — persistent-XLA-cache hits
  still lower, so the count reflects Python-level retraces, which is
  exactly the per-iteration recompile signal.
- :func:`no_implicit_transfers` wraps ``jax.transfer_guard("disallow")``:
  implicit device->host syncs (``float(arr)``, ``arr.item()``,
  ``np.asarray(arr)`` — ``__array__`` counts as implicit) raise, while
  explicit ``jax.device_get`` / ``jax.device_put`` stay allowed — the
  deliberate fetches in models/gbdt.py (_flush_pending,
  _async_stop_check) go through ``jax.device_get`` and keep working.
- :func:`install_from_env` wires both process-wide from the
  ``LGBM_TPU_GUARDS`` env var (``1``/``log`` = log mode, ``strict`` =
  disallow implicit transfers; ``LIGHTGBM_TPU_GUARDS`` is an alias).
  lightgbm_tpu/__init__.py calls it at import, so any run — bench,
  scripts, tests — is audited without code changes.
- ``LGBM_TPU_GUARDS`` is comma-separable: the ``lockorder`` token
  installs the runtime lock-order tracker (:mod:`.lockorder` — pure
  stdlib, no jax) and the REMAINING tokens keep their transfer-guard
  meaning, so ``LGBM_TPU_GUARDS=lockorder,strict`` turns on both.
  ``lockorder`` alone does not initialize a backend.

jax is imported lazily: importing this module (e.g. from the jaxlint CLI
process) must not initialize a backend.
"""
from __future__ import annotations

import logging
import os
from contextlib import contextmanager
from typing import List, Optional

# jax 0.4.x emits "Compiling <name> with global shapes and types ..." from
# these loggers when a function is traced+lowered (DEBUG unless
# jax_log_compiles); dispatch.py carries the "Finished XLA compilation"
# companion records; compiler.py logs "Persistent compilation cache
# hit for '<name>' ..." when the lowered program is served from the
# on-disk cache instead of XLA-compiled (the signal the ISSUE-4
# relaunch-skips-recompilation test asserts on).
_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch",
                    "jax._src.compiler")


class CompileBudgetExceeded(AssertionError):
    """Raised by compile_budget() — AssertionError so pytest renders it as
    a plain test failure, not an error."""


class CompileCounter(logging.Handler):
    """Context manager counting jit retrace/lower events while active.

    ``names`` records what compiled (eager primitive ops appear under
    their primitive name, e.g. "broadcast_in_dim"; jitted functions under
    their function name). After a warmed-up training loop ANY event is a
    recompile symptom, so the budget tests count them all.
    """

    def __init__(self):
        super().__init__(level=logging.DEBUG)
        self.names: List[str] = []
        # programs served from the PERSISTENT on-disk cache while
        # active: these lowered (so they appear in ``names`` too) but
        # did NOT pay an XLA compile — the warm-relaunch signal
        self.cache_hits: List[str] = []
        self._saved = []

    @property
    def count(self) -> int:
        return len(self.names)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        if msg.startswith("Compiling "):
            self.names.append(msg.split(" ", 2)[1])
        elif msg.startswith("Persistent compilation cache hit"):
            # "Persistent compilation cache hit for '<name>' with key …"
            try:
                self.cache_hits.append(msg.split("'", 2)[1])
            except IndexError:
                self.cache_hits.append(msg)

    def __enter__(self) -> "CompileCounter":
        # when the user asked for the compile audit (jax_log_compiles,
        # e.g. via LGBM_TPU_GUARDS), records must keep flowing to their
        # handlers even while we count — only silence the DEBUG spray
        # that exists solely because of our own level lowering
        keep_propagating = False
        try:
            import jax
            keep_propagating = bool(jax.config.jax_log_compiles)
        except Exception:
            pass
        for name in _COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            self._saved.append((lg, lg.level, lg.propagate))
            lg.addHandler(self)
            lg.setLevel(logging.DEBUG)
            if not keep_propagating:
                lg.propagate = False
        return self

    def __exit__(self, *exc) -> None:
        for lg, level, prop in self._saved:
            lg.removeHandler(self)
            lg.setLevel(level)
            lg.propagate = prop
        self._saved.clear()


@contextmanager
def compile_budget(max_compiles: int, where: str = ""):
    """Fail (CompileBudgetExceeded) if the block compiles more than
    ``max_compiles`` distinct programs. Use AFTER a warmup pass: a warmed
    steady-state training loop should sit at ~0.

        with compile_budget(2, "train_one_iter x5"):
            for _ in range(5):
                booster.update()
    """
    with CompileCounter() as counter:
        yield counter
    if counter.count > max_compiles:
        label = f" in {where}" if where else ""
        raise CompileBudgetExceeded(
            f"compile budget exceeded{label}: {counter.count} "
            f"compilation(s) > budget {max_compiles}; compiled: "
            f"{counter.names[:12]}"
            + (" ..." if counter.count > 12 else ""))


@contextmanager
def no_implicit_transfers():
    """Disallow implicit device<->host transfers inside the block.

    ``float(arr)`` / ``arr.item()`` / ``np.asarray(arr)`` raise
    XlaRuntimeError (jax treats the ``__array__`` protocol as an IMPLICIT
    transfer); only explicit ``jax.device_get``/``device_put`` stay
    allowed, so deliberate materialization points must use those — as
    models/gbdt.py's batched fetches do.
    """
    import jax
    with jax.transfer_guard("disallow"):
        yield


def install_from_env(env=None) -> bool:
    """Process-wide guards from ``LGBM_TPU_GUARDS`` (returns True if on).

    - ``1`` / ``log``: log-mode transfer guard + jax_log_compiles — every
      implicit transfer and every compile shows up on stderr.
    - ``strict`` / ``disallow``: implicit transfers RAISE (the training
      hot path must be transfer-free); compiles are logged.
    - ``lockorder`` (combinable: ``lockorder,strict``): install the
      runtime lock-order tracker over the instrumented threaded modules
      — pure stdlib, raises LockOrderViolation at the acquisition that
      closes an inversion cycle. This token alone never imports jax.
    """
    tokens = _guard_tokens(env)
    on = False
    if "lockorder" in tokens:
        # BEFORE any jax work and before package submodules import, so
        # their module-level locks are created through the patched
        # factories
        from . import lockorder
        lockorder.install()
        on = True
    mode = guard_mode(env)
    if mode is None:
        return on
    import jax
    jax.config.update("jax_transfer_guard", mode)
    jax.config.update("jax_log_compiles", True)
    return True


def _guard_tokens(env=None) -> List[str]:
    e = env if env is not None else os.environ
    val = (e.get("LGBM_TPU_GUARDS") or
           e.get("LIGHTGBM_TPU_GUARDS") or "").strip().lower()
    return [t.strip() for t in val.split(",") if t.strip()]


def guard_mode(env=None) -> Optional[str]:
    """The LGBM_TPU_GUARDS transfer-guard mode install_from_env applies
    (the ``lockorder`` token is orthogonal and ignored here).

    ``LIGHTGBM_TPU_GUARDS`` is honored as an alias so the toggle also
    answers to the package's established env-var prefix
    (LIGHTGBM_TPU_PLATFORM / LIGHTGBM_TPU_DEBUG_CHECKS)."""
    tokens = [t for t in _guard_tokens(env) if t != "lockorder"]
    if not tokens or tokens[0] in ("0", "false", "off", "no"):
        return None
    return ("disallow" if any(t in ("strict", "disallow", "2")
                              for t in tokens) else "log")
