"""Concurrency lint ("conlint", rules CL001-CL005) over the threaded tier.

Static half of the ISSUE-16 race tooling: an AST pass over the repo's
lock-bearing modules (serving/service/robustness/native — the PR 8-14
threading layer) that builds a per-module lock-acquisition graph and
flags the defect classes every shipped race so far has fallen into:

CL001  lock-order inversion: the module's acquisition graph (lock B
       taken while lock A is held => edge A->B, including one level of
       same-module call expansion) contains a cycle — two threads
       entering the cycle from different ends deadlock.
CL002  blocking call while holding a lock: ``queue.put/get``,
       socket/HTTP I/O, ``subprocess`` spawn/wait, ``time.sleep``,
       thread ``join`` / event ``wait``, file I/O, and jax device sync
       (``block_until_ready``, ``device_get``, ``np.asarray`` on a
       device value) — each one stretches the critical section by an
       unbounded external latency and starves every waiter.
CL003  shared-state escape: a ``self.attr`` written OUTSIDE any lock in
       a method reachable from one thread entry point while another
       entry point reads it — the classic unsynchronized publish.
       (GIL-atomic single-reference swaps are a deliberate idiom here;
       they get a suppression with a reason, which is the audit.)
CL004  ``Condition.wait`` outside a ``while`` predicate loop — wakeups
       are spurious and stealable; an ``if`` check sleeps forever or
       proceeds on a consumed predicate.
CL005  ``threading.Thread`` without daemon/join discipline: a
       non-daemon thread that nobody joins outlives shutdown and hangs
       interpreter exit (or leaks into the next test).

Reuses jaxlint's machinery wholesale: :class:`~.jaxlint.FileContext`
(suppression comments + finding fingerprints) and the baseline
load/diff helpers. Suppress in source with ``# conlint: disable=CL00x``
(the ``jaxlint:`` tag works too — one regex serves both passes) plus a
reason; accepted findings live in ``concurrency_baseline.json`` where —
unlike jaxlint's — EVERY entry must carry a one-line ``reason``: the
baseline is the triage record, and a reasonless entry fails the gate.

The runtime half (lock-order tracking under ``LGBM_TPU_GUARDS=
lockorder``) lives in :mod:`.lockorder` and shares :class:`LockGraph`.

CLI: ``python scripts/jaxlint.py --pass concurrency`` (or ``all``).
Pure stdlib — no jax import.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .jaxlint import (FileContext, Finding, FuncInfo, iter_py_files,
                      load_baseline_records)
from .rules import callee_chain

BASELINE_NAME = "concurrency_baseline.json"

# the lock-bearing modules this pass instruments (repo-relative); the
# runtime tracker (lockorder.py) wraps lock creation in the same set
TARGET_MODULES = (
    "lightgbm_tpu/serving/server.py",
    "lightgbm_tpu/serving/batcher.py",
    "lightgbm_tpu/serving/fleet.py",
    "lightgbm_tpu/serving/metrics.py",
    "lightgbm_tpu/service/__init__.py",
    "lightgbm_tpu/service/trainer.py",
    "lightgbm_tpu/service/frontdoor.py",
    "lightgbm_tpu/robustness/heartbeat.py",
    "lightgbm_tpu/robustness/faults.py",
    "lightgbm_tpu/native/__init__.py",
)

LOCK_CTORS = {"Lock", "RLock", "Condition"}
# with-target names that count as locks even without a visible ctor
# (cross-file attributes, fixtures)
_LOCKISH_RE = re.compile(r"(^|_)(lock|lk|mutex|cv|cond)s?$", re.I)

_NUMPY_ALIASES = {"np", "numpy", "onp", "_np"}
_QUEUEISH_RE = re.compile(r"(^|_)(q|queue|inbox|outbox)s?$", re.I)
_SOCKET_ATTRS = {"recv", "recvfrom", "recv_into", "accept", "connect",
                 "sendall", "makefile", "urlopen", "getresponse"}
# `.join` / `.wait` receivers that look like threads/processes — a bare
# attr match would flag every `", ".join(...)` string join
_THREADISH_RE = re.compile(
    r"(thread|proc|work|child|pump|loop|supervis|keepaliv|dispatch|"
    r"writer|server|gang|rank|watch)|(^|\.)_?t\d*$", re.I)
_FILE_CALLS = {"open", "os.replace", "os.rename", "os.fsync"}


def _iter_own_exprs(node: ast.AST):
    """Yield the expression nodes belonging to ``node`` itself, without
    descending into nested statements or nested function bodies — so a
    lock-scope walker can attribute each access to the held-lock context
    it actually executes under."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.stmt, ast.excepthandler,
                              ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.append(c)


def _name_of(expr: ast.AST) -> str:
    """Dotted name of a plain Name/Attribute chain ('' otherwise)."""
    return callee_chain(expr)


class ModuleLocks:
    """Lock inventory + per-function lock behavior for one module."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        # dotted target name -> ctor kind ("Lock"/"RLock"/"Condition")
        self.declared: Dict[str, str] = {}
        self.condition_names: Set[str] = set()
        self._collect_declared()
        # function-id -> summary dicts, filled lazily
        self._acq_memo: Dict[int, Set[str]] = {}
        self._blk_memo: Dict[int, List[Tuple[str, ast.AST]]] = {}

    # -- inventory ------------------------------------------------------
    def _collect_declared(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not (isinstance(node, ast.Assign) and
                    isinstance(node.value, ast.Call)):
                continue
            chain = callee_chain(node.value.func)
            base, _, tail = chain.rpartition(".")
            if tail not in LOCK_CTORS or base not in ("", "threading"):
                continue
            for tgt in node.targets:
                name = _name_of(tgt)
                if not name:
                    continue
                self.declared[name] = tail
                if tail == "Condition":
                    self.condition_names.add(name)

    def is_lock_expr(self, expr: ast.AST) -> Optional[str]:
        """Dotted name when ``expr`` denotes a lock (declared in this
        module, or lock-ish by name); None otherwise."""
        name = _name_of(expr)
        if not name:
            return None
        if name in self.declared:
            return name
        if _LOCKISH_RE.search(name.rpartition(".")[2]):
            return name
        return None

    def qualify(self, name: str, fi: Optional[FuncInfo]) -> str:
        """Stable per-module lock identity: self attrs are scoped to the
        enclosing class, locals to the enclosing function."""
        if fi is None:
            return name
        if name.startswith("self."):
            cls = fi.qualname.rpartition(".")[0]
            return f"{cls}.{name}" if cls else name
        if name in self.declared:        # module-level lock
            return name
        return f"{fi.qualname}.{name}"

    # -- per-function summaries (transitive through same-module calls) --
    def _resolve_call(self, call: ast.Call,
                      fi: Optional[FuncInfo]) -> List[FuncInfo]:
        """Same-module callees of ``f(...)`` / ``self.m(...)``."""
        func = call.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif (isinstance(func, ast.Attribute) and
                isinstance(func.value, ast.Name) and
                func.value.id == "self"):
            name = func.attr
        if name is None:
            return []
        cands = self.ctx._by_name.get(name, [])
        if (fi is not None and isinstance(func, ast.Attribute) and
                len(cands) > 1):
            # prefer the method of the SAME class
            cls = fi.qualname.rpartition(".")[0]
            same = [c for c in cands
                    if c.qualname.rpartition(".")[0] == cls]
            if same:
                return same
        return list(cands)

    def acquired_anywhere(self, fi: FuncInfo,
                          _stack: Optional[Set[int]] = None) -> Set[str]:
        """Qualified lock names acquired anywhere inside ``fi``,
        transitively through same-module simple calls."""
        nid = id(fi.node)
        if nid in self._acq_memo:
            return self._acq_memo[nid]
        stack = _stack if _stack is not None else set()
        if nid in stack:
            return set()
        stack.add(nid)
        out: Set[str] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.With):
                for item in node.items:
                    name = self.is_lock_expr(item.context_expr)
                    if name:
                        out.add(self.qualify(name, fi))
            elif isinstance(node, ast.Call):
                chain = callee_chain(node.func)
                base, _, tail = chain.rpartition(".")
                if tail == "acquire" and self.is_lock_expr(node.func.value):
                    out.add(self.qualify(base, fi))
                for cal in self._resolve_call(node, fi):
                    out |= self.acquired_anywhere(cal, stack)
        stack.discard(nid)
        self._acq_memo[nid] = out
        return out

    def blocking_anywhere(self, fi: FuncInfo,
                          _stack: Optional[Set[int]] = None
                          ) -> List[Tuple[str, ast.AST]]:
        """(label, node) blocking operations inside ``fi``, transitively
        through same-module calls (label prefixed with the callee path)."""
        nid = id(fi.node)
        if nid in self._blk_memo:
            return self._blk_memo[nid]
        stack = _stack if _stack is not None else set()
        if nid in stack:
            return []
        stack.add(nid)
        out: List[Tuple[str, ast.AST]] = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            label = blocking_label(node)
            if label:
                out.append((label, node))
            for cal in self._resolve_call(node, fi):
                for lab, _n in self.blocking_anywhere(cal, stack):
                    out.append((f"{cal.qualname}: {lab}", node))
        stack.discard(nid)
        self._blk_memo[nid] = out
        return out


def blocking_label(node: ast.Call) -> Optional[str]:
    """Category label when ``node`` is a blocking call (CL002), else
    None. Curated for this codebase's I/O surface."""
    chain = callee_chain(node.func)
    base, _, tail = chain.rpartition(".")
    recv_tail = base.rpartition(".")[2]
    if tail in ("put", "get") and _QUEUEISH_RE.search(recv_tail):
        return f"queue {tail} (`{chain}`)"
    if chain == "time.sleep":
        return "time.sleep"
    if tail == "join" and isinstance(node.func, ast.Attribute) and \
            _THREADISH_RE.search(base):
        return f"thread/process join (`{chain}`)"
    if tail == "wait" and isinstance(node.func, ast.Attribute):
        return f"wait (`{chain}`)"
    if chain.startswith("subprocess.") and tail in (
            "run", "Popen", "call", "check_call", "check_output"):
        return f"subprocess spawn/wait (`{chain}`)"
    if tail == "communicate":
        return f"subprocess communicate (`{chain}`)"
    if chain.split(".", 1)[0] == "socket" or tail in _SOCKET_ATTRS:
        return f"socket/HTTP I/O (`{chain}`)"
    if tail == "block_until_ready":
        return "jax device sync (`block_until_ready`)"
    if chain in ("jax.device_get", "jax.device_put"):
        return f"jax device sync (`{chain}`)"
    if base in _NUMPY_ALIASES | {"jnp", "jax.numpy"} and \
            tail in ("asarray", "array"):
        return f"possible device sync / host copy (`{chain}`)"
    if chain in _FILE_CALLS:
        return f"file I/O (`{chain}`)"
    return None


class LockGraph:
    """Directed lock-acquisition-order graph with cycle detection.

    Shared by the CL001 static rule and the runtime tracker
    (:mod:`.lockorder`): nodes are lock identities, an edge A->B means
    "B was acquired while A was held", and a cycle is a lock-order
    inversion (two threads entering from different ends deadlock).
    """

    def __init__(self):
        self.edges: Dict[str, Dict[str, object]] = {}

    def add_edge(self, a: str, b: str,
                 site: object = None) -> Optional[List[str]]:
        """Record A->B; returns the cycle path ``[b, ..., a, b]`` when
        this edge closes one (the edge stays recorded), else None."""
        if a == b:          # reentrant acquisition is not an inversion
            return None
        fresh = b not in self.edges.get(a, ())
        self.edges.setdefault(a, {}).setdefault(b, site)
        if not fresh:
            return None
        path = self.find_path(b, a)
        if path is not None:
            return path + [b]
        return None

    def find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src->dst along recorded edges, or None."""
        seen: Set[str] = set()
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in self.edges.get(node, ()):
                if nxt not in seen:
                    stack.append((nxt, path + [nxt]))
        return None

    def site(self, a: str, b: str) -> object:
        return self.edges.get(a, {}).get(b)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

class LockOrderRule:
    """CL001: cycle in the module's lock-acquisition graph."""

    rule = "CL001"

    def visit(self, ctx: FileContext, locks: ModuleLocks) -> List:
        graph = LockGraph()
        edge_sites: Dict[Tuple[str, str], Tuple[ast.AST, FuncInfo]] = {}

        def scan(body, held: Tuple[str, ...], fi: FuncInfo) -> None:
            for node in body:
                self._scan_node(node, held, fi, graph, edge_sites, locks)

        for fi in ctx.all_funcs:
            if fi.is_lambda:
                continue
            scan(fi.node.body, (), fi)

        out = []
        for (a, b), (node, fi) in sorted(
                edge_sites.items(),
                key=lambda kv: getattr(kv[1][0], "lineno", 0)):
            cyc = graph.find_path(b, a)
            if cyc is None:
                continue
            path = " -> ".join([a] + cyc)
            f = ctx.finding(
                self.rule, node, fi,
                f"lock-order inversion: `{b}` acquired while `{a}` held "
                f"closes the cycle [{path}] — another thread entering "
                "the cycle elsewhere deadlocks")
            if f:
                out.append(f)
        return out

    def _scan_node(self, node, held, fi, graph, edge_sites, locks) -> None:
        """Walk one statement, tracking held locks through nested withs
        and expanding same-module calls one transitive level."""
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                name = locks.is_lock_expr(item.context_expr)
                if name:
                    q = locks.qualify(name, fi)
                    for h in new_held:
                        graph.add_edge(h, q)
                        edge_sites.setdefault((h, q), (node, fi))
                    new_held = new_held + (q,)
            for sub in node.body:
                self._scan_node(sub, new_held, fi, graph, edge_sites,
                                locks)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return          # nested defs scanned from their own FuncInfo
        # calls made while holding: pull in the callee's acquisitions
        if held:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    for cal in locks._resolve_call(sub, fi):
                        for q in locks.acquired_anywhere(cal):
                            for h in held:
                                graph.add_edge(h, q)
                                edge_sites.setdefault((h, q), (sub, fi))
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.stmt, ast.excepthandler)):
                self._scan_node(sub, held, fi, graph, edge_sites, locks)
            elif hasattr(sub, "body") and isinstance(
                    getattr(sub, "body", None), list):
                for s in sub.body:
                    if isinstance(s, ast.stmt):
                        self._scan_node(s, held, fi, graph, edge_sites,
                                        locks)


class BlockingUnderLockRule:
    """CL002: blocking call while >=1 lock is held."""

    rule = "CL002"

    def visit(self, ctx: FileContext, locks: ModuleLocks) -> List:
        out: List = []
        seen: Set[int] = set()

        for fi in ctx.all_funcs:
            if fi.is_lambda:
                continue
            for node in fi.node.body:
                self._scan_node(node, (), fi, out, ctx, locks, seen)
        return out

    def _scan_node(self, node, held, fi, out, ctx, locks, seen) -> None:
        if isinstance(node, ast.With):
            new_held = held
            for item in node.items:
                name = locks.is_lock_expr(item.context_expr)
                if name:
                    new_held = new_held + (locks.qualify(name, fi),)
            for sub in node.body:
                self._scan_node(sub, new_held, fi, out, ctx, locks, seen)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if held:
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or id(sub) in seen:
                    continue
                seen.add(id(sub))
                label = blocking_label(sub)
                if label:
                    # Condition.wait on the innermost held lock RELEASES
                    # it while sleeping — that's CL004's domain, not a
                    # blocking-while-holding hazard unless outer locks
                    # stay pinned
                    recv = _name_of(sub.func)[:-5] \
                        if _name_of(sub.func).endswith(".wait") else None
                    if recv is not None:
                        rq = locks.qualify(recv, fi)
                        if rq == held[-1] and len(held) == 1:
                            continue
                    f = ctx.finding(
                        self.rule, sub, fi,
                        f"blocking {label} while holding "
                        f"{list(held)} — the critical section now waits "
                        "on external latency and starves every waiter")
                    if f:
                        out.append(f)
                    continue
                # one transitive level: callee that blocks
                for cal in locks._resolve_call(sub, fi):
                    blk = locks.blocking_anywhere(cal)
                    if blk:
                        lab = blk[0][0]
                        f = ctx.finding(
                            self.rule, sub, fi,
                            f"call to `{cal.qualname}` performs blocking "
                            f"{lab} while holding {list(held)}")
                        if f:
                            out.append(f)
                        break
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, (ast.stmt, ast.excepthandler)):
                self._scan_node(sub, held, fi, out, ctx, locks, seen)


class SharedStateEscapeRule:
    """CL003: unlocked ``self.attr`` write visible to another thread
    entry point. Only classes that actually spawn threads are audited;
    ``__init__`` writes (pre-thread) and threading/queue primitive
    attributes (internally synchronized) are exempt."""

    rule = "CL003"
    _SYNC_CTORS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
                   "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
                   "LifoQueue", "PriorityQueue", "local", "Thread",
                   "Timer"}

    def visit(self, ctx: FileContext, locks: ModuleLocks) -> List:
        out: List = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._visit_class(node, ctx, locks))
        return out

    def _visit_class(self, cls: ast.ClassDef, ctx: FileContext,
                     locks: ModuleLocks) -> List:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        thread_roots = self._thread_targets(cls) & set(methods)
        if not thread_roots:
            return []
        public_roots = {m for m in methods
                        if not m.startswith("_") or
                        m in ("__call__", "__enter__", "__exit__")}
        roots = thread_roots | public_roots

        # call graph over self.m() calls
        calls: Dict[str, Set[str]] = {m: set() for m in methods}
        for m, node in methods.items():
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call) and
                        isinstance(sub.func, ast.Attribute) and
                        isinstance(sub.func.value, ast.Name) and
                        sub.func.value.id == "self" and
                        sub.func.attr in methods):
                    calls[m].add(sub.func.attr)
        reach: Dict[str, Set[str]] = {}
        for r in roots:
            seen: Set[str] = set()
            stack = [r]
            while stack:
                m = stack.pop()
                if m in seen:
                    continue
                seen.add(m)
                stack.extend(calls.get(m, ()))
            reach[r] = seen
        root_sets: Dict[str, Set[str]] = {
            m: {r for r in roots if m in reach[r]} for m in methods}

        sync_attrs = self._sync_attrs(cls)
        # accesses: attr -> list of (method, kind, locked, node)
        accesses: Dict[str, List[Tuple[str, str, bool, ast.AST]]] = {}
        for m, node in methods.items():
            if m == "__init__":
                continue
            self._collect(node.body, m, (), accesses, locks, ctx)

        out: List = []
        for attr, accs in sorted(accesses.items()):
            if attr in sync_attrs or _LOCKISH_RE.search(attr):
                continue
            acc_roots: Set[str] = set()
            for meth, _k, _l, _n in accs:
                acc_roots |= root_sets.get(meth, set())
            if len(acc_roots) < 2 or not (acc_roots & thread_roots):
                continue
            has_read = any(k == "read" for _m, k, _l, _n in accs)
            for meth, kind, locked, node in accs:
                if kind != "write" or locked or not root_sets.get(meth):
                    continue
                if not has_read:
                    break
                readers = sorted({m2 for m2, k2, _l2, _n2 in accs
                                  if k2 == "read" and m2 != meth})
                f = ctx.finding(
                    self.rule, node, ctx.enclosing(node),
                    f"`self.{attr}` written without a lock in "
                    f"`{meth}` (reached from {sorted(root_sets[meth])}) "
                    f"but read from other thread entry points "
                    f"(via {readers[:3]}) — unsynchronized shared state")
                if f:
                    out.append(f)
                break       # one finding per (class, attr)
        return out

    def _collect(self, body, meth, held, accesses, locks, ctx) -> None:
        for node in body:
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    if locks.is_lock_expr(item.context_expr):
                        new_held = new_held + (1,)
                self._collect(node.body, meth, new_held, accesses,
                              locks, ctx)
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in _iter_own_exprs(node):
                if not (isinstance(sub, ast.Attribute) and
                        isinstance(sub.value, ast.Name) and
                        sub.value.id == "self"):
                    continue
                kind = ("write" if isinstance(sub.ctx,
                                              (ast.Store, ast.Del))
                        else "read")
                accesses.setdefault(sub.attr, []).append(
                    (meth, kind, bool(held), sub))
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.excepthandler):
                    self._collect(sub.body, meth, held, accesses,
                                  locks, ctx)
                elif isinstance(sub, ast.stmt):
                    self._collect([sub], meth, held, accesses, locks,
                                  ctx)

    @staticmethod
    def _thread_targets(cls: ast.ClassDef) -> Set[str]:
        """Method names handed to ``threading.Thread(target=self.m)``
        within this class (plus the conventional ``run``)."""
        out: Set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call) and
                    callee_chain(node.func).rpartition(".")[2] ==
                    "Thread"):
                continue
            for kw in node.keywords:
                if kw.arg == "target" and \
                        isinstance(kw.value, ast.Attribute) and \
                        isinstance(kw.value.value, ast.Name) and \
                        kw.value.value.id == "self":
                    out.add(kw.value.attr)
        if "run" in {n.name for n in cls.body
                     if isinstance(n, ast.FunctionDef)}:
            out.add("run")
        return out

    def _sync_attrs(self, cls: ast.ClassDef) -> Set[str]:
        """self attrs assigned from threading/queue primitives — they
        synchronize internally and are exempt from CL003."""
        out: Set[str] = set()
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Assign) and
                    isinstance(node.value, ast.Call)):
                continue
            tail = callee_chain(node.value.func).rpartition(".")[2]
            if tail not in self._SYNC_CTORS:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    out.add(tgt.attr)
        return out


class ConditionWaitRule:
    """CL004: ``Condition.wait`` outside a predicate ``while`` loop."""

    rule = "CL004"

    def visit(self, ctx: FileContext, locks: ModuleLocks) -> List:
        out: List = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr == "wait"):
                continue
            recv = _name_of(node.func.value)
            if recv not in locks.condition_names and not \
                    re.search(r"(^|_)(cv|cond)(ition)?s?$",
                              recv.rpartition(".")[2], re.I):
                continue
            cur = node
            in_while = False
            while cur is not None:
                cur = ctx._parents.get(id(cur))
                if isinstance(cur, ast.While):
                    in_while = True
                    break
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    break
            if in_while:
                continue
            f = ctx.finding(
                self.rule, node, ctx.enclosing(node),
                f"`{recv}.wait()` outside a `while` predicate loop — "
                "wakeups are spurious and stealable; re-check the "
                "predicate in a while loop (or use wait_for)")
            if f:
                out.append(f)
        return out


class ThreadDisciplineRule:
    """CL005: ``threading.Thread`` without daemon/join discipline."""

    rule = "CL005"

    def visit(self, ctx: FileContext, locks: ModuleLocks) -> List:
        out: List = []
        joined, daemonized = self._module_discipline(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = callee_chain(node.func)
            base, _, tail = chain.rpartition(".")
            if tail != "Thread" or base not in ("", "threading"):
                continue
            if any(kw.arg == "daemon" and
                   isinstance(kw.value, ast.Constant) and
                   kw.value.value is True for kw in node.keywords):
                continue
            # find the handle the Thread is bound to
            parent = ctx._parents.get(id(node))
            handle = None
            if isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    handle = _name_of(tgt) or handle
            if handle and (handle in joined or handle in daemonized):
                continue
            what = (f"`{handle}`" if handle
                    else "an unbound threading.Thread")
            f = ctx.finding(
                self.rule, node, ctx.enclosing(node),
                f"{what} created without daemon=True and never joined "
                "or daemonized — a non-daemon thread that nobody joins "
                "outlives shutdown and wedges interpreter exit")
            if f:
                out.append(f)
        return out

    @staticmethod
    def _module_discipline(tree: ast.Module
                           ) -> Tuple[Set[str], Set[str]]:
        """Names with a ``.join(...)`` call / ``.daemon = True`` assign
        anywhere in the module."""
        joined: Set[str] = set()
        daemonized: Set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute) and
                    node.func.attr == "join"):
                name = _name_of(node.func.value)
                if name:
                    joined.add(name)
                    # `for t in self._threads: t.join()` style: credit
                    # the container too
                    joined.add(name.rpartition(".")[0] or name)
            elif (isinstance(node, ast.Assign) and
                    isinstance(node.targets[0], ast.Attribute) and
                    node.targets[0].attr == "daemon" and
                    isinstance(node.value, ast.Constant) and
                    node.value.value is True):
                name = _name_of(node.targets[0].value)
                if name:
                    daemonized.add(name)
        return joined, daemonized


CONCURRENCY_RULES = (LockOrderRule, BlockingUnderLockRule,
                     SharedStateEscapeRule, ConditionWaitRule,
                     ThreadDisciplineRule)
CONCURRENCY_RULE_IDS = tuple(r.rule for r in CONCURRENCY_RULES)


# ---------------------------------------------------------------------------
# driving + baseline (reason-carrying variant of jaxlint's)
# ---------------------------------------------------------------------------

def lint_source(src: str, rel: str) -> List[Finding]:
    """Concurrency-lint one source string (rel names the module)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(rule="CL000", path=rel, line=e.lineno or 1,
                        col=0, scope="<module>",
                        message=f"syntax error: {e.msg}", line_text="")]
    ctx = FileContext(rel, src, tree, set())
    locks = ModuleLocks(ctx)
    findings: List[Finding] = []
    for rule_cls in CONCURRENCY_RULES:
        for f in rule_cls().visit(ctx, locks):
            if f is not None:
                findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def default_targets(root: str) -> List[str]:
    return [os.path.join(root, m) for m in TARGET_MODULES
            if os.path.exists(os.path.join(root, m))]


def run_paths(paths, root: str) -> List[Finding]:
    """Lint files/dirs (module-local analysis; no cross-file pass)."""
    findings: List[Finding] = []
    for f in sorted(iter_py_files(paths)):
        rel = os.path.relpath(os.path.abspath(f),
                              os.path.abspath(root)).replace(os.sep, "/")
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        findings.extend(lint_source(src, rel))
    return findings


def default_baseline_path(root: str) -> str:
    return os.path.join(root, BASELINE_NAME)


def save_baseline(path: str, findings: List[Finding],
                  keep_records: List[dict] = (),
                  prior_records: List[dict] = ()) -> None:
    """Write the triage baseline. Reasons survive regeneration (matched
    by fingerprint against ``prior_records``); new entries get a TODO
    placeholder that the gate refuses until a human fills it in."""
    reasons = {e.get("fingerprint"): e.get("reason", "")
               for e in prior_records}
    records = [
        {"fingerprint": f.fingerprint, "rule": f.rule, "file": f.path,
         "scope": f.scope, "line_text": f.line_text.strip(),
         "reason": reasons.get(f.fingerprint) or
         "TODO: one-line triage reason required"}
        for f in findings] + list(keep_records)
    records.sort(key=lambda e: (e.get("file", ""), e.get("rule", ""),
                                e.get("line_text", "")))
    data = {
        "version": 1,
        "tool": "conlint",
        "note": ("triaged concurrency findings; only NEW findings gate, "
                 "and every entry MUST carry a one-line reason (the "
                 "baseline is the triage record). Regenerate with: "
                 "python scripts/jaxlint.py --pass concurrency "
                 "--update-baseline"),
        "findings": records,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")


def reasonless_entries(records: List[dict]) -> List[dict]:
    return [e for e in records
            if not str(e.get("reason", "")).strip() or
            str(e.get("reason", "")).strip().lower().startswith("todo")]


def main(argv: Optional[List[str]] = None,
         root: Optional[str] = None) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="conlint",
        description="concurrency static analysis (rules CL001-CL005 "
                    "over the lock-bearing modules; see "
                    "lightgbm_tpu/analysis/concurrency.py)")
    parser.add_argument("paths", nargs="*")
    parser.add_argument("--baseline", default=None)
    parser.add_argument("--update-baseline", action="store_true")
    parser.add_argument("--no-baseline", action="store_true")
    parser.add_argument("--list", action="store_true", dest="list_all")
    args = parser.parse_args(argv)

    if root is None:
        root = os.getcwd()
    paths, missing = [], []
    for p in args.paths:
        if os.path.exists(p):
            paths.append(p)
        elif os.path.exists(os.path.join(root, p)):
            paths.append(os.path.join(root, p))
        else:
            missing.append(p)
    if missing:
        print(f"conlint: path(s) not found: {', '.join(missing)}")
        return 2
    if not args.paths:
        paths = default_targets(root)
    if not iter_py_files(paths):
        print("conlint: no .py files under the given path(s) — "
              "nothing was linted")
        return 2
    findings = run_paths(paths, root)
    findings_real = [f for f in findings if f.rule != "CL000"]
    syntax_errors = [f for f in findings if f.rule == "CL000"]

    bl_path = args.baseline or default_baseline_path(root)
    prior = load_baseline_records(bl_path)
    if args.update_baseline:
        if syntax_errors:
            for f in syntax_errors:
                print(f.format())
            print("conlint: refusing to update the baseline while files "
                  "fail to parse")
            return 1
        keep: List[dict] = []
        if args.paths:
            scanned = {
                os.path.relpath(os.path.abspath(f), os.path.abspath(root))
                .replace(os.sep, "/") for f in iter_py_files(paths)}
            keep = [e for e in prior if e.get("file") not in scanned]
        save_baseline(bl_path, findings_real, keep, prior)
        todo = reasonless_entries(load_baseline_records(bl_path))
        print(f"conlint: baseline updated with {len(findings_real)} "
              f"finding(s) -> {bl_path}")
        if todo:
            print(f"conlint: {len(todo)} entr(ies) still need a reason "
                  "— the gate fails until each carries one")
        return 0

    baseline = set() if args.no_baseline else \
        {e["fingerprint"] for e in prior}
    new, known = [], []
    for f in findings_real:
        (known if f.fingerprint in baseline else new).append(f)
    for f in syntax_errors:
        print(f.format())
    for f in new:
        print(f.format())
    if args.list_all:
        for f in known:
            print(f"{f.format()}  [known]")
    todo = [] if args.no_baseline else reasonless_entries(prior)
    for e in todo:
        print(f"conlint: baseline entry {e.get('fingerprint')} "
              f"({e.get('file')}: {e.get('rule')}) has no triage "
              "reason — every accepted finding must say why")
    by_rule: Dict[str, int] = {}
    for f in findings_real:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    breakdown = " ".join(f"{r}={by_rule.get(r, 0)}"
                         for r in CONCURRENCY_RULE_IDS)
    print(f"conlint: {len(findings_real)} finding(s): {len(new)} new, "
          f"{len(known)} known (baselined) [{breakdown}]")
    if new:
        print("conlint: new findings — fix them, add a targeted "
              "`# conlint: disable=<RULE>` with a reason, or accept "
              "via --update-baseline (then fill in the reason)")
    return 1 if (new or syntax_errors or todo) else 0
