"""jaxlint engine: AST jit-scope resolution, suppression, baseline diff.

Promotes the repo's ad-hoc lint precedent (scripts/r_lint.py structural R
gate, scripts/body_opcount.py HLO proxy) into a real static-analysis pass
over the Python/JAX sources. Pure stdlib — importable (and fast) without
jax, so the CLI runs anywhere, including the hardware-free CI image.

Jit-scope resolution (which functions count as "traced"):

1. functions decorated with ``@jax.jit`` / ``@jit`` / ``@pjit`` or a
   ``partial(jax.jit, ...)`` form;
2. functions passed by name to ``jax.jit(...)`` — including through one
   level of local assignment (``grow = make_x(...); jax.jit(grow)``);
3. callables handed to the traced higher-order ops (``lax.while_loop``,
   ``lax.cond``, ``lax.scan``, ``lax.fori_loop``, ``lax.switch``,
   ``vmap``, ``grad``, ...);
4. nested functions of "grower factories": any function whose CALL result
   is passed to ``jax.jit`` anywhere in the scanned tree (e.g.
   ``jax.jit(make_tree_grower(...))`` in models/gbdt.py marks the nested
   defs of ``make_tree_grower`` in core/grower.py) — the factory body
   itself runs at trace-setup time and is NOT jit scope;
5. transitively: functions called by simple name (or ``self.method``)
   from jit-scope code in the same module.

Suppression: ``# jaxlint: disable=JL001[,JL005]`` (or ``disable=all``) on
the flagged line, on its own line directly above, or on the enclosing
``def`` line (which suppresses the rule for the whole function).

Baseline: findings fingerprint on (file, rule, scope qualname, normalized
source line, occurrence) — stable across unrelated line drift — and
``jaxlint_baseline.json`` records the accepted pre-existing set so only
NEW findings gate (mirroring the reference repo's lint-gates-CI model).
"""
from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .rules import ALL_RULES, RULE_IDS, callee_chain

BASELINE_NAME = "jaxlint_baseline.json"
JIT_TAILS = {"jit", "pjit"}
# traced higher-order ops -> their CALLABLE argument positions. Operand
# positions must NOT be treated as callables: a Name bound from
# ``helper(...)`` sitting in an operand slot (``init = helper(x);
# lax.while_loop(cond, body, init)``) would wrongly mark ``helper`` a
# factory and exempt its body from jit scope.
TRACE_HOFS = {
    "while_loop": (0, 1), "fori_loop": (2,), "cond": (1, 2),
    "scan": (0,), "switch": (1,), "map": (0,),
    "associative_scan": (0,), "vmap": (0,), "pmap": (0,), "grad": (0,),
    "value_and_grad": (0,), "jacfwd": (0,), "jacrev": (0,),
    "checkpoint": (0,), "remat": (0,), "custom_vjp": (0,),
    "custom_jvp": (0,),
}
# files whose jit-scope code is the compute hot path (JL004 applies)
KERNEL_PATTERNS = ("lightgbm_tpu/ops/", "core/grower.py",
                   "core/level_grower.py")
# capture only the comma-separated rule list so a plain-word reason after
# it ("# jaxlint: disable=JL001 trace-time probe") can't swallow the token.
# `conlint:` is the concurrency pass's tag (analysis/concurrency.py);
# one regex serves both passes, so either tag suppresses either family.
_SUPPRESS_RE = re.compile(
    r"#\s*(?:jax|con)lint:\s*disable="
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


def _local_call_map(tree: ast.AST) -> Dict[str, str]:
    """One level of local dataflow: name -> callee tail of the Call it
    was assigned from (``grow = make_x(...)`` -> {"grow": "make_x"})."""
    local_calls: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            cal = callee_chain(node.value.func).rpartition(".")[2]
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and cal:
                    local_calls[tgt.id] = cal
    return local_calls


def _factory_from_jit_arg(arg: ast.AST,
                          local_calls: Dict[str, str]) -> Optional[str]:
    """Factory name F when a jit argument is ``F(...)`` or a local bound
    from ``F(...)``; None otherwise."""
    if isinstance(arg, ast.Call):
        return callee_chain(arg.func).rpartition(".")[2] or None
    if isinstance(arg, ast.Name):
        return local_calls.get(arg.id)
    return None


@dataclass
class FuncInfo:
    node: ast.AST                 # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    params: Set[str]
    def_line: int
    is_lambda: bool = False
    parent: Optional["FuncInfo"] = None


@dataclass
class Finding:
    rule: str
    path: str                     # repo-relative posix path
    line: int
    col: int
    scope: str
    message: str
    line_text: str
    occ: int = 0                  # disambiguates identical lines in a scope

    @property
    def fingerprint(self) -> str:
        key = "|".join((self.path, self.rule, self.scope,
                        self.line_text.strip(), str(self.occ)))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


class FileContext:
    """Everything the rule visitors need about one source file."""

    def __init__(self, rel: str, src: str, tree: ast.Module,
                 factory_names: Set[str],
                 extra_seeds: Optional[Set[str]] = None):
        self.rel = rel
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        self.kernel = any(p in rel for p in KERNEL_PATTERNS)
        self.suppressions = _collect_suppressions(self.lines)
        self.all_funcs: List[FuncInfo] = []
        self._by_name: Dict[str, List[FuncInfo]] = {}
        self._func_of_node: Dict[int, FuncInfo] = {}
        self._parents: Dict[int, ast.AST] = {}
        self._collect_funcs()
        self.jit_bindings = _collect_jit_bindings(tree)
        self.factory_names = factory_names
        self._precompute_callgraph()
        self._collect_static_seeds()
        self.jit_funcs: List[FuncInfo] = []
        self.resolve(extra_seeds or set())
        self._occ_seen: Dict[Tuple, int] = {}

    # -- construction ---------------------------------------------------
    def _collect_funcs(self) -> None:
        def walk(node, qual, parent_fi):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    name = getattr(child, "name", "<lambda>")
                    q = f"{qual}.{name}" if qual else name
                    fi = FuncInfo(
                        node=child, qualname=q,
                        params=_param_names(child),
                        def_line=child.lineno,
                        is_lambda=isinstance(child, ast.Lambda),
                        parent=parent_fi)
                    self.all_funcs.append(fi)
                    self._by_name.setdefault(name, []).append(fi)
                    self._func_of_node[id(child)] = fi
                    walk(child, q, fi)
                elif isinstance(child, ast.ClassDef):
                    q = f"{qual}.{child.name}" if qual else child.name
                    walk(child, q, parent_fi)
                else:
                    walk(child, qual, parent_fi)
        walk(self.tree, "", None)

    def _precompute_callgraph(self) -> None:
        """One AST walk per function: ids of nested function nodes plus
        the simple names it calls (bare ``f(...)`` and ``self.m(...)``).
        resolve() is then pure set algebra, so the cross-module fixpoint
        can re-resolve scopes without re-walking any tree."""
        self._nested: Dict[int, List[int]] = {}
        self._calls_bare: Dict[int, Set[str]] = {}
        self._calls_any: Dict[int, Set[str]] = {}
        for fi in self.all_funcs:
            nested: List[int] = []
            bare: Set[str] = set()
            any_: Set[str] = set()
            for sub in ast.walk(fi.node):
                if sub is not fi.node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                    nested.append(id(sub))
                if isinstance(sub, ast.Call):
                    if isinstance(sub.func, ast.Name):
                        bare.add(sub.func.id)
                        any_.add(sub.func.id)
                    elif (isinstance(sub.func, ast.Attribute) and
                            isinstance(sub.func.value, ast.Name) and
                            sub.func.value.id == "self"):
                        any_.add(sub.func.attr)
            self._nested[id(fi.node)] = nested
            self._calls_bare[id(fi.node)] = bare
            self._calls_any[id(fi.node)] = any_

    def _collect_static_seeds(self) -> None:
        """Seed-independent module scan (runs once): jit decorators,
        jit/HOF call sites, and locally-discovered factories. May grow
        ``self.factory_names`` (``grow = make_x(...); jax.jit(grow)``)."""
        self._static_seed_ids: Set[int] = set()

        def seed_name(name: str) -> None:
            for fi in self._by_name.get(name, ()):
                self._static_seed_ids.add(id(fi.node))

        def seed_arg(arg: ast.AST, local_calls: Dict[str, str]) -> None:
            if isinstance(arg, ast.Lambda):
                self._static_seed_ids.add(id(arg))
            elif isinstance(arg, ast.Name):
                if arg.id in self._by_name:
                    seed_name(arg.id)
                elif arg.id in local_calls:
                    self.factory_names.add(local_calls[arg.id])
            elif isinstance(arg, (ast.List, ast.Tuple)):
                # lax.switch takes a SEQUENCE of branch callables
                for e in arg.elts:
                    seed_arg(e, local_calls)

        local_calls = _local_call_map(self.tree)

        for fi in self.all_funcs:
            for dec in getattr(fi.node, "decorator_list", ()):
                if _mentions_jit(dec):
                    self._static_seed_ids.add(id(fi.node))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            tail = callee_chain(node.func).rpartition(".")[2]
            if tail in JIT_TAILS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Call):
                    fname = _factory_from_jit_arg(arg, local_calls)
                    if fname:
                        self.factory_names.add(fname)
                else:
                    seed_arg(arg, local_calls)
            elif tail in TRACE_HOFS:
                for idx in TRACE_HOFS[tail]:
                    if idx < len(node.args):
                        seed_arg(node.args[idx], local_calls)

    def resolve(self, extra_seeds: Set[str]) -> None:
        """(Re)compute ``jit_funcs`` for the given cross-module seed
        names. Cheap — no AST walks — so the repo fixpoint calls it
        repeatedly on the same context."""
        # factory BODIES run at trace-setup time and are never jit scope
        # (their nested defs are) — a traced function calling a factory
        # by name must not drag the factory body in, same-module or
        # cross-module. An explicit @jit decorator still wins (it sits
        # in _static_seed_ids).
        factory_ids = {id(fi.node)
                       for name in self.factory_names
                       for fi in self._by_name.get(name, ())}
        seeds: Set[int] = set(self._static_seed_ids)
        for name in extra_seeds:
            for fi in self._by_name.get(name, ()):
                if id(fi.node) not in factory_ids:
                    seeds.add(id(fi.node))
        # factory nested defs are jit scope (the factory body is not)
        for name in self.factory_names:
            for fi in self._by_name.get(name, ()):
                seeds.update(self._nested[id(fi.node)])

        # transitive closure over same-module simple calls
        changed = True
        while changed:
            changed = False
            for fi in self.all_funcs:
                nid = id(fi.node)
                if nid not in seeds:
                    continue
                for sub_id in self._nested[nid]:
                    if sub_id not in seeds:
                        seeds.add(sub_id)
                        changed = True
                for name in self._calls_any[nid]:
                    for cal in self._by_name.get(name, ()):
                        cal_id = id(cal.node)
                        if cal_id not in seeds and \
                                cal_id not in factory_ids:
                            seeds.add(cal_id)
                            changed = True
        self.jit_funcs = [fi for fi in self.all_funcs
                          if id(fi.node) in seeds]

    def traced_call_names(self) -> Set[str]:
        """Bare names called from this file's jit-scope code — candidates
        for cross-module traced functions (e.g. ops/split.py's scan entry
        points, called from core/grower.py's jitted body)."""
        names: Set[str] = set()
        for fi in self.jit_funcs:
            names |= self._calls_bare[id(fi.node)]
        return names

    # -- services for rules ---------------------------------------------
    def enclosing(self, node: ast.AST) -> Optional[FuncInfo]:
        cur = node
        while cur is not None:
            fi = self._func_of_node.get(id(cur))
            if fi is not None:
                return fi
            cur = self._parents.get(id(cur))
        return None

    def _comment_only(self, line: int) -> bool:
        return (0 < line <= len(self.lines) and
                self.lines[line - 1].lstrip().startswith("#"))

    def _suppressed(self, rule: str, anchor: int) -> bool:
        """Disable comment on the anchor line, or in the contiguous
        comment block directly above it."""
        def hit(line: int) -> bool:
            sup = self.suppressions.get(line)
            return bool(sup and ("all" in sup or rule in sup))

        if hit(anchor):
            return True
        ln = anchor - 1
        while ln > 0 and self._comment_only(ln):
            if hit(ln):
                return True
            ln -= 1
        return False

    def finding(self, rule: str, node: ast.AST, fi: Optional[FuncInfo],
                message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        # suppression anchors: the flagged line, the first line of the
        # enclosing statement (multi-line calls), and the enclosing def
        # line (whole-function suppression); each anchor also honors a
        # comment block directly above it
        stmt = node
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = self._parents.get(id(stmt))
        anchors = {line}
        if stmt is not None:
            anchors.add(stmt.lineno)
        if fi is not None:
            anchors.add(fi.def_line)
        text = (self.lines[line - 1] if 0 < line <= len(self.lines)
                else "")
        scope = fi.qualname if fi else "<module>"
        # count the occurrence BEFORE the suppression check: suppressing
        # one of two identical flagged lines must not re-key the
        # survivor's occ (baseline fingerprints stay stable)
        key = (rule, scope, text.strip())
        occ = self._occ_seen.get(key, 0)
        self._occ_seen[key] = occ + 1
        for anchor in anchors:
            if self._suppressed(rule, anchor):
                return None
        return Finding(rule=rule, path=self.rel, line=line, col=col,
                       scope=scope, message=message, line_text=text,
                       occ=occ)


def _param_names(node: ast.AST) -> Set[str]:
    a = node.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


def _mentions_jit(dec: ast.AST) -> bool:
    """Decorator expression references jit: @jit, @jax.jit,
    @partial(jax.jit, ...), @functools.partial(jit, static_argnums=...)"""
    for sub in ast.walk(dec):
        if isinstance(sub, ast.Name) and sub.id in JIT_TAILS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in JIT_TAILS:
            return True
    return False


def _collect_jit_bindings(tree: ast.Module) -> Dict[str, dict]:
    """Names/attributes bound to a ``jax.jit(...)`` result, with whether
    the binding declared static_argnums/static_argnames (JL003/JL005)."""
    bindings: Dict[str, dict] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call)):
            continue
        tail = callee_chain(node.value.func).rpartition(".")[2]
        if tail not in JIT_TAILS:
            continue
        has_static = any(kw.arg in ("static_argnums", "static_argnames")
                         for kw in node.value.keywords)
        for tgt in node.targets:
            key = None
            if isinstance(tgt, ast.Name):
                key = tgt.id
            elif (isinstance(tgt, ast.Attribute) and
                    isinstance(tgt.value, ast.Name) and
                    tgt.value.id == "self"):
                key = "self." + tgt.attr
            if key:
                bindings[key] = {"has_static": has_static,
                                 "line": node.lineno}
    return bindings


def _collect_suppressions(lines: List[str]) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, ln in enumerate(lines, 1):
        m = _SUPPRESS_RE.search(ln)
        if m:
            out[i] = {tok.strip().upper() if tok.strip().lower() != "all"
                      else "all"
                      for tok in m.group(1).split(",") if tok.strip()}
    return out


# ---------------------------------------------------------------------------
# driving: factory pre-pass, per-file lint, repo run
# ---------------------------------------------------------------------------

def collect_factory_names(trees: Dict[str, ast.Module]) -> Set[str]:
    """Pass 1: names F where ``jit(F(...))`` (or ``x = F(...); jit(x)``)
    appears anywhere — their nested defs are jit scope in every module.
    Takes pre-parsed trees so the repo pass parses each file once."""
    names: Set[str] = set()
    for rel, tree in trees.items():
        local_calls = _local_call_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if callee_chain(node.func).rpartition(".")[2] not in JIT_TAILS:
                continue
            if not node.args:
                continue
            fname = _factory_from_jit_arg(node.args[0], local_calls)
            if fname:
                names.add(fname)
    return names


def _lint_ctx(ctx: FileContext) -> List[Finding]:
    """Run every rule over an already-built FileContext."""
    findings: List[Finding] = []
    for rule_cls in ALL_RULES:
        for f in rule_cls().visit(ctx):
            if f is not None:
                findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_source(src: str, rel: str,
                factory_names: Optional[Set[str]] = None,
                extra_seeds: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one source string; ``rel`` decides kernel-file rules (JL004)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(rule="JL000", path=rel, line=e.lineno or 1, col=0,
                        scope="<module>", message=f"syntax error: {e.msg}",
                        line_text="")]
    return _lint_ctx(FileContext(
        rel, src, tree, set(factory_names) if factory_names else set(),
        extra_seeds))


def default_targets(root: str) -> List[str]:
    cands = [os.path.join(root, "lightgbm_tpu"),
             os.path.join(root, "bench.py"),
             os.path.join(root, "microbench.py"),
             os.path.join(root, "scripts")]
    return [c for c in cands if os.path.exists(c)]


def iter_py_files(paths) -> List[str]:
    files = []
    for p in paths:
        if os.path.isdir(p):
            for base, _dirs, fns in os.walk(p):
                if "__pycache__" in base:
                    continue
                for fn in sorted(fns):
                    if fn.endswith(".py"):
                        files.append(os.path.join(base, fn))
        elif p.endswith(".py"):
            files.append(p)
    return files


def run_paths(paths, root: str) -> List[Finding]:
    """Multi-pass lint over files/dirs; paths become root-relative in
    findings so fingerprints are machine-independent.

    Pass 1 collects jit-factory names globally; then jit scopes are
    resolved to a cross-module fixpoint: bare names called from traced
    code in any file seed same-named module functions everywhere (how
    ops/split.py's scan entry points — called from core/grower.py's
    jitted body — enter jit scope)."""
    import builtins
    builtin_names = set(dir(builtins))
    files = iter_py_files(paths)
    sources: Dict[str, str] = {}
    trees: Dict[str, ast.Module] = {}
    for f in files:
        rel = os.path.relpath(os.path.abspath(f),
                              os.path.abspath(root)).replace(os.sep, "/")
        try:
            with open(f, encoding="utf-8") as fh:
                sources[rel] = fh.read()
        except OSError:
            continue
    syntax_errs: Dict[str, SyntaxError] = {}
    for rel in list(sources):
        try:
            trees[rel] = ast.parse(sources[rel])
        except SyntaxError as e:
            syntax_errs[rel] = e
    factories = collect_factory_names(trees)
    seeds: Set[str] = set()
    ctxs = {rel: FileContext(rel, sources[rel], tree, set(factories))
            for rel, tree in trees.items()}  # built once; resolve() is cheap
    while True:  # cross-module fixpoint: seeds grow monotonically and are
        # bounded by the repo's function names, so this terminates
        called: Set[str] = set()
        for ctx in ctxs.values():
            called |= ctx.traced_call_names()
        called -= builtin_names | factories   # factory bodies: trace-setup
        if called <= seeds:
            break
        seeds |= called
        for ctx in ctxs.values():
            ctx.resolve(seeds)
    findings: List[Finding] = []
    for rel in sorted(sources):
        if rel in ctxs:
            findings.extend(_lint_ctx(ctxs[rel]))
        else:
            e = syntax_errs[rel]
            findings.append(Finding(
                rule="JL000", path=rel, line=e.lineno or 1, col=0,
                scope="<module>", message=f"syntax error: {e.msg}",
                line_text=""))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def default_baseline_path(root: str) -> str:
    return os.path.join(root, BASELINE_NAME)


def load_baseline_records(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return list(data.get("findings", ()))


def load_baseline(path: str) -> Set[str]:
    return {e["fingerprint"] for e in load_baseline_records(path)}


def save_baseline(path: str, findings: List[Finding],
                  keep_records: List[dict] = ()) -> None:
    """Write the accepted-findings baseline. ``keep_records`` carries
    existing entries for files OUTSIDE the linted path set, so a partial
    `--update-baseline path/...` run can't wipe the rest of the repo's
    accepted findings."""
    records = [
        {"fingerprint": f.fingerprint, "rule": f.rule, "file": f.path,
         "scope": f.scope, "line_text": f.line_text.strip()}
        for f in findings] + list(keep_records)
    records.sort(key=lambda e: (e.get("file", ""), e.get("rule", ""),
                                e.get("line_text", "")))
    data = {
        "version": 1,
        "tool": "jaxlint",
        "note": ("accepted pre-existing findings; only NEW findings gate. "
                 "Regenerate with: python scripts/jaxlint.py "
                 "--update-baseline"),
        "findings": records,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1)
        fh.write("\n")


def diff_against_baseline(findings: List[Finding], baseline: Set[str]
                          ) -> Tuple[List[Finding], List[Finding]]:
    """-> (new, known)"""
    new, known = [], []
    for f in findings:
        (known if f.fingerprint in baseline else new).append(f)
    return new, known


# ---------------------------------------------------------------------------
# CLI (scripts/jaxlint.py is a thin wrapper over this)
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None, root: Optional[str] = None
         ) -> int:
    import argparse
    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description="JAX-hazard static analysis (rules JL001-JL005; "
                    "see lightgbm_tpu/analysis/rules.py)")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to lint (default: the package + "
                             "bench/scripts)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline json (default: <root>/"
                             f"{BASELINE_NAME})")
    parser.add_argument("--update-baseline", action="store_true",
                        help="accept current findings as the new baseline")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline; report everything as new")
    parser.add_argument("--list", action="store_true", dest="list_all",
                        help="print known (baselined) findings too")
    args = parser.parse_args(argv)

    if root is None:
        root = os.getcwd()
    # explicit paths resolve against cwd first, then root — and a scan
    # that matches no files must FAIL, not report a green gate
    paths, missing = [], []
    for p in args.paths:
        if os.path.exists(p):
            paths.append(p)
        elif os.path.exists(os.path.join(root, p)):
            paths.append(os.path.join(root, p))
        else:
            missing.append(p)
    if missing:
        print(f"jaxlint: path(s) not found: {', '.join(missing)}")
        return 2
    if not args.paths:
        paths = default_targets(root)
    if not iter_py_files(paths):
        print("jaxlint: no .py files under the given path(s) — "
              "nothing was linted")
        return 2
    findings = run_paths(paths, root)
    findings_real = [f for f in findings if f.rule != "JL000"]
    syntax_errors = [f for f in findings if f.rule == "JL000"]

    bl_path = args.baseline or default_baseline_path(root)
    if args.update_baseline:
        if syntax_errors:
            for f in syntax_errors:
                print(f.format())
            print("jaxlint: refusing to update the baseline while files "
                  "fail to parse — JL000 findings are never baselined")
            return 1
        keep: List[dict] = []
        if args.paths:
            # partial update: only the scanned files' entries are
            # replaced; accepted findings elsewhere must survive
            scanned = {
                os.path.relpath(os.path.abspath(f), os.path.abspath(root))
                .replace(os.sep, "/") for f in iter_py_files(paths)}
            keep = [e for e in load_baseline_records(bl_path)
                    if e.get("file") not in scanned]
        save_baseline(bl_path, findings_real, keep)
        kept_note = f" (+{len(keep)} kept from unscanned files)" \
            if keep else ""
        print(f"jaxlint: baseline updated with {len(findings_real)} "
              f"finding(s){kept_note} -> {bl_path}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(bl_path)
    new, known = diff_against_baseline(findings_real, baseline)
    for f in syntax_errors:
        print(f.format())
    for f in new:
        print(f.format())
    if args.list_all:
        for f in known:
            print(f"{f.format()}  [known]")
    by_rule: Dict[str, int] = {}
    for f in findings_real:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    breakdown = " ".join(f"{r}={by_rule.get(r, 0)}" for r in RULE_IDS)
    print(f"jaxlint: {len(findings_real)} finding(s): {len(new)} new, "
          f"{len(known)} known (baselined) [{breakdown}]")
    if new:
        print("jaxlint: new findings — fix them, add a targeted "
              "`# jaxlint: disable=<RULE>` with a reason, or accept via "
              "--update-baseline")
    return 1 if (new or syntax_errors) else 0
