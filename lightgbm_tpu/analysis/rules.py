"""jaxlint rule visitors (JL001-JL005).

Each rule is a small class with a rule id and a ``visit(ctx)`` that walks
the pre-computed :class:`~lightgbm_tpu.analysis.jaxlint.FileContext` and
returns findings. The engine (jaxlint.py) owns jit-scope resolution,
suppression comments and the baseline diff; rules only pattern-match.

The rules encode the classic JAX performance/correctness regressions for
this codebase's hot path (SURVEY L0/L4: the tree-learner compute engine):

JL001  host-sync calls inside jit-traced code (``.item()``, ``float()`` /
       ``int()`` on arrays, ``np.asarray`` on jax values) — each one is a
       device->host round-trip (~70 ms through the tunnel) or a tracer
       concretization error.
JL002  Python ``for``/``while``/``if`` over traced values in jitted
       bodies — tracer-leak heuristic (should be ``lax.cond`` /
       ``lax.while_loop`` / ``jnp.where``).
JL003  recompile hazards at jit boundaries: dict/str arguments to a
       jitted callable without static_argnums/static_argnames, and
       ``jax.jit(...)`` created inside a loop (fresh cache every pass).
JL004  dtype-widening literals in kernel files: ``np.float64`` in traced
       code, or float literals fed to jnp constructors without an explicit
       dtype (promote to f64 under jax_enable_x64).
JL005  wall-clock timing around jax dispatch without a completion barrier
       (``block_until_ready`` / device fetch) — measures dispatch, not
       execution — and ``timer.section(...)`` without ``sync=`` (the
       utils/timer.py contract) in dispatching functions.
"""
from __future__ import annotations

import ast
from typing import List


def callee_chain(func: ast.AST) -> str:
    """Dotted name of a call target ("np.asarray", "jax.lax.cond", "float");
    empty string when the target is not a plain name/attribute chain."""
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        # rooted at a call/subscript (e.g. get_timer().section): keep the
        # attribute tail so attr-based rules still see it
        parts.append("")
    return ".".join(reversed(parts))


NUMPY_ALIASES = {"np", "numpy", "onp", "_np"}
TIMING_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "timeit.default_timer",
}
# attribute substrings that prove a completion barrier / host fetch
SYNC_ATTRS = ("block_until_ready", "device_get", "_force_sync")
# attrs of a traced array that are static at trace time (not leaks)
STATIC_ARRS = {"shape", "ndim", "dtype", "size", "aval", "weak_type"}


def _is_static_expr(node: ast.AST) -> bool:
    """Expression whose value is static at trace time: `.shape[0]`,
    `x.ndim`, `len(...)` and arithmetic over those."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Attribute):
        return node.attr in STATIC_ARRS or _is_static_expr(node.value)
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value)
    if isinstance(node, ast.BinOp):
        return _is_static_expr(node.left) and _is_static_expr(node.right)
    if isinstance(node, ast.Call):
        return callee_chain(node.func) in ("len", "min", "max") and all(
            _is_static_expr(a) for a in node.args)
    return False


def _wraps_dispatch(node: ast.Call) -> bool:
    """float(jnp.sum(x))-style: the scalar conversion IS the barrier."""
    for sub in ast.walk(node.args[0]) if node.args else ():
        if isinstance(sub, ast.Call):
            root = callee_chain(sub.func).split(".", 1)[0]
            if root in ("jnp", "jax"):
                return True
    return False


class HostSyncRule:
    """JL001: device->host syncs inside jit-traced code."""

    rule = "JL001"

    def visit(self, ctx) -> List:
        out = []
        for fi in ctx.jit_funcs:
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                # attribute each call to its innermost function only —
                # nested defs are themselves in jit_funcs, so walking
                # past them here would double-report their hazards
                if ctx.enclosing(node) is not fi:
                    continue
                chain = callee_chain(node.func)
                base, _, attr = chain.rpartition(".")
                if (isinstance(node.func, ast.Attribute) and
                        attr in ("item", "tolist") and not node.args):
                    out.append(ctx.finding(
                        self.rule, node, fi,
                        f"`.{attr}()` forces a device->host sync inside "
                        "jit-traced code"))
                elif (chain in ("float", "int", "bool", "complex") and
                        len(node.args) == 1 and
                        not _is_static_expr(node.args[0])):
                    out.append(ctx.finding(
                        self.rule, node, fi,
                        f"`{chain}()` on an array concretizes the tracer "
                        "(host sync / ConcretizationTypeError) inside "
                        "jit-traced code"))
                elif base in NUMPY_ALIASES and attr in ("asarray", "array"):
                    out.append(ctx.finding(
                        self.rule, node, fi,
                        f"`{base}.{attr}` on a jax value forces a "
                        "device->host transfer inside jit-traced code"))
                elif chain == "jax.device_get":
                    out.append(ctx.finding(
                        self.rule, node, fi,
                        "`jax.device_get` inside jit-traced code forces a "
                        "device->host round-trip"))
        return out


class TracerLeakRule:
    """JL002: Python control flow over (potentially) traced parameters.

    Static config params (``cfg``/``hp``/``backend=...``) branch at trace
    time all over the grower factories — legitimate program
    specialization. The rule therefore only fires on parameters with
    positive ARRAY evidence in the same function: passed to a jnp/lax/jax
    call or subscripted directly.
    """

    rule = "JL002"

    def visit(self, ctx) -> List:
        out = []
        for fi in ctx.jit_funcs:
            if not fi.params:
                continue
            arrayish = self._arrayish_params(fi)
            if not arrayish:
                continue
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.If, ast.While)):
                    expr, kind = node.test, type(node).__name__.lower()
                elif isinstance(node, ast.For):
                    expr, kind = node.iter, "for"
                else:
                    continue
                if ctx.enclosing(node) is not fi:  # innermost scope only
                    continue
                hits = self._traced_names(expr) & arrayish
                if hits:
                    out.append(ctx.finding(
                        self.rule, node, fi,
                        f"Python `{kind}` over traced value(s) "
                        f"{sorted(hits)} in a jitted body — use lax.cond/"
                        "lax.while_loop/jnp.where"))
        return out

    @staticmethod
    def _arrayish_params(fi) -> set:
        """Params used as arrays in the body: fed to a jnp/lax/jax call
        or subscripted (`x[...]`, not `x.attr[...]`)."""
        arrayish = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                root = callee_chain(node.func).split(".", 1)[0]
                if root not in ("jnp", "lax", "jax"):
                    continue
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    # names only reached through an attribute read
                    # (hp.lambda_l1, meta.num_bin) are config access,
                    # not array use
                    attr_roots = {id(sub.value) for sub in ast.walk(arg)
                                  if isinstance(sub, ast.Attribute)}
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and \
                                sub.id in fi.params and \
                                id(sub) not in attr_roots:
                            arrayish.add(sub.id)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in fi.params:
                arrayish.add(node.value.id)
        return arrayish

    def _traced_names(self, expr: ast.AST) -> set:
        """Bare names whose runtime VALUE the statement branches on.

        `x is None`, `isinstance(x, T)`, `x.shape[0]` and `range(x.ndim)`
        are static at trace time and excluded.
        """
        if isinstance(expr, ast.BoolOp):
            names = set()
            for v in expr.values:
                names |= self._traced_names(v)
            return names
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            return self._traced_names(expr.operand)
        if isinstance(expr, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return set()
        if isinstance(expr, ast.Call):
            chain = callee_chain(expr.func)
            if chain in ("isinstance", "callable", "hasattr", "getattr",
                         "len", "enumerate", "zip", "range"):
                names = set()
                for a in expr.args:
                    names |= self._traced_names(a)
                return names
        names = set()
        stat_parents = set()
        for sub in ast.walk(expr):
            if (isinstance(sub, ast.Attribute) and
                    sub.attr in STATIC_ARRS and
                    isinstance(sub.value, ast.Name)):
                stat_parents.add(id(sub.value))
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and id(sub) not in stat_parents:
                names.add(sub.id)
        return names


class RecompileHazardRule:
    """JL003: retrace/recompile hazards at jit boundaries."""

    rule = "JL003"

    def visit(self, ctx) -> List:
        out = []
        # (a) hazardous arguments at call sites of known jit bindings
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            key = self._binding_key(node.func)
            binding = ctx.jit_bindings.get(key)
            if binding is None or binding.get("has_static"):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                label = self._hazard_label(arg)
                if label:
                    out.append(ctx.finding(
                        self.rule, node, ctx.enclosing(node),
                        f"jitted `{key}` called with a {label} argument but "
                        "bound without static_argnums/static_argnames — "
                        "every distinct value retraces"))
                    break
        # (b) jax.jit(...) constructed inside a loop body (nested loops
        # must not multiply-report the same call site)
        seen = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Call) and id(sub) not in seen and
                        callee_chain(sub.func).split(".")[-1] in
                        ("jit", "pjit")):
                    seen.add(id(sub))
                    out.append(ctx.finding(
                        self.rule, sub, ctx.enclosing(sub),
                        "jax.jit(...) inside a loop builds a fresh "
                        "compilation cache every pass — hoist it out"))
        return out

    @staticmethod
    def _binding_key(func: ast.AST):
        if isinstance(func, ast.Name):
            return func.id
        if (isinstance(func, ast.Attribute) and
                isinstance(func.value, ast.Name) and
                func.value.id == "self"):
            return "self." + func.attr
        return None

    @staticmethod
    def _hazard_label(arg: ast.AST):
        if isinstance(arg, (ast.Dict, ast.DictComp)):
            return "dict"
        if isinstance(arg, ast.Call) and callee_chain(arg.func) == "dict":
            return "dict"
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return "str"
        return None


class WideningDtypeRule:
    """JL004: dtype-widening literals in kernel files (x64 promotion)."""

    rule = "JL004"
    # *_like constructors inherit dtype from the template array, so a
    # float fill value cannot promote — they are deliberately absent
    JNP_CTORS = {"array", "asarray", "full", "zeros", "ones", "arange",
                 "linspace"}

    def visit(self, ctx) -> List:
        if not ctx.kernel:
            return []
        out = []
        for fi in ctx.jit_funcs:
            for node in ast.walk(fi.node):
                if ctx.enclosing(node) is not fi:  # innermost scope only
                    continue
                if isinstance(node, ast.Attribute) and \
                        node.attr == "float64":
                    base = callee_chain(node).rpartition(".")[0]
                    if base in NUMPY_ALIASES | {"jnp", "jax.numpy"}:
                        out.append(ctx.finding(
                            self.rule, node, fi,
                            f"`{base}.float64` in a kernel file widens the "
                            "f32 hot path (and promotes everything it "
                            "touches under x64)"))
                elif isinstance(node, ast.Call):
                    base, _, attr = callee_chain(node.func).rpartition(".")
                    if base not in ("jnp", "jax.numpy") or \
                            attr not in self.JNP_CTORS:
                        continue
                    kwargs = {kw.arg for kw in node.keywords}
                    dtype_pos = len(node.args) > 1 and attr in (
                        "array", "asarray", "zeros", "ones")
                    has_float_lit = any(
                        isinstance(a, ast.Constant) and
                        isinstance(a.value, float) for a in node.args) or any(
                        isinstance(a, (ast.List, ast.Tuple)) and any(
                            isinstance(e, ast.Constant) and
                            isinstance(e.value, float) for e in a.elts)
                        for a in node.args)
                    if attr == "full" and len(node.args) > 1:
                        # second positional is the FILL VALUE (it decides
                        # the dtype); a positional dtype sits at index 2
                        has_float_lit = (isinstance(node.args[1],
                                                    ast.Constant) and
                                         isinstance(node.args[1].value,
                                                    float))
                        dtype_pos = len(node.args) > 2
                    if has_float_lit and "dtype" not in kwargs and \
                            not dtype_pos:
                        out.append(ctx.finding(
                            self.rule, node, fi,
                            f"`jnp.{attr}` with a float literal and no "
                            "explicit dtype promotes to f64 under "
                            "jax_enable_x64 — pass dtype=jnp.float32"))
        return out


class UnsyncedTimingRule:
    """JL005: timing around async dispatch without a completion barrier."""

    rule = "JL005"

    def visit(self, ctx) -> List:
        out = []
        for fi in ctx.all_funcs:
            if fi.is_lambda:
                continue
            timing, sections, dispatches, synced = [], [], False, False
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                # a nested def's timing/dispatch/barriers belong to the
                # nested function's own visit, not this scope's tally
                if ctx.enclosing(node) is not fi:
                    continue
                chain = callee_chain(node.func)
                base, _, attr = chain.rpartition(".")
                if chain in TIMING_CALLS:
                    timing.append(node)
                elif (attr == "section" and "timer" in base.lower() and
                        not any(kw.arg == "sync" for kw in node.keywords)):
                    sections.append(node)
                if any(s in chain for s in SYNC_ATTRS):
                    synced = True
                elif base in NUMPY_ALIASES and attr in ("asarray", "array"):
                    synced = True  # host conversion IS a barrier
                elif (chain in ("float", "int") and len(node.args) == 1 and
                        _wraps_dispatch(node)):
                    synced = True  # float(jnp.sum(x)) — the bench barrier
                elif (isinstance(node.func, ast.Attribute) and
                        attr in ("item", "tolist")):
                    synced = True
                if not dispatches:
                    root = chain.split(".", 1)[0]
                    if root == "jnp" or chain.startswith("jax.numpy"):
                        dispatches = True
                    elif root == "jax" and not any(
                            s in chain for s in SYNC_ATTRS) and \
                            ".config" not in chain:
                        dispatches = True
                    elif self._calls_jitted(ctx, node.func):
                        dispatches = True
            if not dispatches:
                continue
            if len(timing) >= 2 and not synced:
                out.append(ctx.finding(
                    self.rule, timing[1], fi,
                    "wall-clock timing around jax dispatch without "
                    "block_until_ready/device fetch — this measures "
                    "dispatch, not execution (utils/timer.py contract)"))
            for sec in sections:
                if not synced:
                    out.append(ctx.finding(
                        self.rule, sec, fi,
                        "timer.section(...) around jax dispatch without "
                        "sync= — the section charges dispatch time only "
                        "(utils/timer.py contract)"))
        return out

    @staticmethod
    def _calls_jitted(ctx, func: ast.AST) -> bool:
        key = RecompileHazardRule._binding_key(func)
        return key is not None and key in ctx.jit_bindings


ALL_RULES = (HostSyncRule, TracerLeakRule, RecompileHazardRule,
             WideningDtypeRule, UnsyncedTimingRule)
RULE_IDS = tuple(r.rule for r in ALL_RULES)
