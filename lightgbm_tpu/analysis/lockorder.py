"""Opt-in runtime lock-order tracker (``LGBM_TPU_GUARDS=lockorder``).

The static side of this subsystem (:mod:`.concurrency`, rule CL001)
proves per-module lock order from the AST; this module proves it at
runtime across *threads*, where the AST cannot see. It monkeypatches
the ``threading.Lock`` / ``threading.RLock`` / ``threading.Condition``
factories so that locks **created by the instrumented modules** (the
conlint TARGET_MODULES — serving/service/robustness/native) come back
wrapped in a tracking proxy. Every acquisition attempt records an edge
"top-of-held-stack -> this lock" into a process-global
:class:`~.concurrency.LockGraph`; the moment an edge closes a cycle —
i.e. two threads have demonstrably acquired the same locks in opposite
orders — :class:`LockOrderViolation` is raised **at the attempt, before
blocking**, so a seeded deadlock trips the guard instead of hanging the
process.

Key properties:

- **Pure stdlib, no jax import.** Safe to install from
  ``lightgbm_tpu/__init__`` before any submodule creates its locks
  (guards install precedes the ``.basic`` import there, so module-level
  locks like ``native._lock`` are created post-patch and get wrapped).
- **Frame-filtered.** The patched factories inspect the *caller's*
  frame: only call sites inside the instrumented files get a tracked
  lock; CPython's own threading internals (Event/Timer/Thread
  machinery) and third-party code get the original primitives.
- **Cycle check precedes the blocking acquire.** Detection needs only
  inconsistent *order*, not an actual contention window: if thread 1
  ever did A->B, thread 2 merely attempting B->A raises — determinism a
  TSan-style happened-to-interleave detector cannot offer.
- **Reentrancy-aware.** Re-acquiring a lock already on the thread's
  held stack (RLock, or Condition re-entry via ``_acquire_restore``)
  records no edge. ``Condition.wait`` is handled by giving the proxy
  ``_release_save`` / ``_acquire_restore`` / ``_is_owned``, so a plain
  ``threading.Condition`` drives the tracked lock natively.

Test/fixture surface: :func:`wrap` instruments an existing lock by
name; :func:`tracking` is a context manager that installs a private
tracker and restores everything on exit.
"""
from __future__ import annotations

import os
import sys
import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from .concurrency import LockGraph, TARGET_MODULES

__all__ = [
    "LockOrderViolation", "LockOrderTracker", "TrackedLock",
    "install", "uninstall", "installed", "current_tracker",
    "wrap", "tracking",
]

# the unpatched factories, captured at import (install() may rebind the
# threading module's names; these always denote the real primitives)
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock
_ORIG_CONDITION = threading.Condition


class LockOrderViolation(RuntimeError):
    """Two threads acquired tracked locks in incompatible orders.

    Carries ``cycle`` (the lock-name path ``[b, ..., a, b]``) and
    ``sites`` (one "thread/file:line" string per recorded edge on the
    cycle) so the failure message names both ends of the inversion.
    """

    def __init__(self, msg: str, cycle: List[str], sites: List[str]):
        super().__init__(msg)
        self.cycle = cycle
        self.sites = sites


def _call_site(depth: int) -> str:
    """thread-name@file:line of the nearest frame above ``depth`` that
    is OUTSIDE this module (skips the proxy's own acquire/__enter__)."""
    try:
        f = sys._getframe(depth)
        while f is not None and f.f_code.co_filename == __file__:
            f = f.f_back
        if f is None:
            return threading.current_thread().name
        return (f"{threading.current_thread().name}@"
                f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}")
    except Exception:
        return threading.current_thread().name


class LockOrderTracker:
    """Process-global acquisition-order graph over tracked locks."""

    def __init__(self, raise_on_cycle: bool = True):
        self.graph = LockGraph()
        self.raise_on_cycle = raise_on_cycle
        self.violations: List[LockOrderViolation] = []
        self.n_tracked = 0          # locks wrapped so far
        self._tls = threading.local()
        self._mu = _ORIG_LOCK()     # guards graph + violations
        self._names: Dict[str, int] = {}  # name -> count, for uniquing

    # -- naming ------------------------------------------------------
    def unique_name(self, base: str) -> str:
        with self._mu:
            n = self._names.get(base, 0)
            self._names[base] = n + 1
            self.n_tracked += 1
            return base if n == 0 else f"{base}#{n}"

    # -- per-thread held stack ---------------------------------------
    def _stack(self) -> List["TrackedLock"]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def held_names(self) -> List[str]:
        """Names of locks the CURRENT thread holds (innermost last)."""
        return [lk.name for lk in self._stack()]

    # -- the protocol the proxies call -------------------------------
    def note_attempt(self, lk: "TrackedLock") -> None:
        """Record the order edge BEFORE blocking; raise on a cycle."""
        st = self._stack()
        if not st or any(x is lk for x in st):
            return              # outermost, or reentrant: no new edge
        prev = st[-1]
        site = _call_site(3)    # caller of acquire()
        with self._mu:
            cycle = self.graph.add_edge(prev.name, lk.name, site)
            if cycle is None:
                return
            sites = [f"{a}->{b} at {self.graph.site(a, b)}"
                     for a, b in zip(cycle, cycle[1:])]
            v = LockOrderViolation(
                "lock-order inversion: acquiring "
                f"{lk.name!r} while holding {prev.name!r} closes the "
                f"cycle {' -> '.join(cycle)} (edges: {'; '.join(sites)})"
                " — two threads entering from different ends deadlock",
                cycle, sites)
            self.violations.append(v)
        if self.raise_on_cycle:
            raise v

    def note_acquired(self, lk: "TrackedLock") -> None:
        self._stack().append(lk)

    def note_released(self, lk: "TrackedLock") -> None:
        st = self._stack()
        # innermost matching entry: releases may be out of LIFO order
        # (contextlib.ExitStack, hand-over-hand), track whatever happens
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lk:
                del st[i]
                return

    def drop_all(self, lk: "TrackedLock") -> int:
        """Remove every stack entry for ``lk`` (Condition._release_save
        on a reentrantly-held RLock); returns how many were held."""
        st = self._stack()
        n = sum(1 for x in st if x is lk)
        st[:] = [x for x in st if x is not lk]
        return n

    def restore_all(self, lk: "TrackedLock", n: int) -> None:
        self.note_attempt(lk)
        self._stack().extend([lk] * max(n, 1))


class TrackedLock:
    """Order-tracking proxy around a Lock/RLock.

    Duck-types the full lock protocol plus the three private hooks
    ``threading.Condition`` probes for (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``), so ``Condition(TrackedLock)``
    works natively — including reentrant-RLock ``wait()``.
    """

    __slots__ = ("_inner", "name", "_tracker")

    def __init__(self, inner, name: str, tracker: LockOrderTracker):
        self._inner = inner
        self.name = name
        self._tracker = tracker

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._tracker.note_attempt(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tracker.note_acquired(self)
        return got

    def release(self) -> None:
        self._tracker.note_released(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TrackedLock {self.name!r} over {self._inner!r}>"

    # -- Condition integration ---------------------------------------
    def _release_save(self):
        n = self._tracker.drop_all(self)
        inner = self._inner
        if hasattr(inner, "_release_save"):     # RLock: full release
            return (inner._release_save(), n)
        inner.release()
        return (None, n)

    def _acquire_restore(self, state) -> None:
        saved, n = state
        inner = self._inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(saved)
        else:
            inner.acquire()
        self._tracker.restore_all(self, n)

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # primitive-lock heuristic, same as threading.Condition's
        if inner.acquire(False):
            inner.release()
            return False
        return True


# ---------------------------------------------------------------------------
# installation: factory monkeypatching, frame-filtered
# ---------------------------------------------------------------------------

_tracker: Optional[LockOrderTracker] = None


def _instrumented_files() -> Tuple[str, ...]:
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return tuple(os.path.join(pkg_root, *rel.split("/")[1:])
                 for rel in TARGET_MODULES)


def _caller_is_instrumented(files: Tuple[str, ...]) -> Tuple[bool, str]:
    """(instrumented?, name-base) for the factory's caller frame."""
    try:
        f = sys._getframe(2)    # factory wrapper -> its caller
    except Exception:
        return False, ""
    fn = f.f_code.co_filename
    if fn not in files:
        # co_filename follows however the module was imported and may
        # be non-normalized (e.g. tests/../lightgbm_tpu/...): one
        # normpath on the miss path keeps the hit path allocation-free
        fn = os.path.normpath(fn)
        if fn not in files:
            return False, ""
    mod = os.path.splitext(os.path.basename(fn))[0]
    if mod == "__init__":
        mod = os.path.basename(os.path.dirname(fn))
    return True, f"{mod}:{f.f_lineno}"


def current_tracker() -> Optional[LockOrderTracker]:
    return _tracker


def installed() -> bool:
    return _tracker is not None


def wrap(lock, name: str, tracker: Optional[LockOrderTracker] = None
         ) -> TrackedLock:
    """Instrument an existing lock under ``name`` (fixtures/tests).

    Uses the installed tracker by default; with none installed a
    private one is created on the fly (edges recorded, cycles raise).
    """
    global _tracker
    t = tracker or _tracker
    if t is None:
        t = LockOrderTracker()
    return TrackedLock(lock, t.unique_name(name), t)


def install(tracker: Optional[LockOrderTracker] = None) -> LockOrderTracker:
    """Patch the threading factories; idempotent. Returns the tracker.

    Must run BEFORE the instrumented modules create their locks —
    lightgbm_tpu/__init__ guarantees this by installing guards ahead of
    every submodule import.
    """
    global _tracker
    if _tracker is not None:
        return _tracker
    t = tracker or LockOrderTracker()
    files = _instrumented_files()

    def Lock():
        hit, base = _caller_is_instrumented(files)
        if not hit:
            return _ORIG_LOCK()
        return TrackedLock(_ORIG_LOCK(), t.unique_name(f"{base}/Lock"), t)

    def RLock():
        hit, base = _caller_is_instrumented(files)
        if not hit:
            return _ORIG_RLOCK()
        return TrackedLock(_ORIG_RLOCK(), t.unique_name(f"{base}/RLock"), t)

    def Condition(lock=None):
        hit, base = _caller_is_instrumented(files)
        if not hit:
            return _ORIG_CONDITION(lock)
        if lock is None:
            lock = TrackedLock(_ORIG_RLOCK(),
                               t.unique_name(f"{base}/Condition"), t)
        elif not isinstance(lock, TrackedLock):
            lock = TrackedLock(lock, t.unique_name(f"{base}/Condition"), t)
        # a REAL threading.Condition driving the tracked lock: wait()
        # goes through _release_save/_acquire_restore on the proxy, so
        # held-stack bookkeeping survives the release-reacquire dance
        return _ORIG_CONDITION(lock)

    threading.Lock = Lock
    threading.RLock = RLock
    threading.Condition = Condition
    _tracker = t
    return t


def uninstall() -> None:
    """Restore the original factories (already-wrapped locks keep
    tracking into the now-detached tracker; they stay functional)."""
    global _tracker
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    threading.Condition = _ORIG_CONDITION
    _tracker = None


@contextmanager
def tracking(raise_on_cycle: bool = True):
    """Install a private tracker for the block; restore on exit.

        with lockorder.tracking() as t:
            ... spin up threads over instrumented modules ...
        assert not t.violations
    """
    prev = _tracker
    if prev is not None:
        uninstall()
    t = install(LockOrderTracker(raise_on_cycle=raise_on_cycle))
    try:
        yield t
    finally:
        uninstall()
        if prev is not None:
            install(prev)
