"""Collective-traffic inspection over compiled HLO text (ISSUE 12).

The reduce-scatter histogram contract makes a measurable wire claim —
collective bytes per reduction drop from allreduce's 2(N-1)/N·|H| to
(N-1)/N·|H| — and the claim must be checkable WITHOUT a device: the
compiled program names its collectives (``all-reduce`` /
``reduce-scatter`` / ``all-gather`` HLO ops with result shapes), so the
bytes-on-the-wire of each program are a pure function of its text.
``scripts/comms_smoke.py`` and the tier-1 bit-identity suite assert on
these numbers; a regression that silently reintroduces a full-histogram
broadcast (an all-reduce at the histogram shape in the reduce_scatter
program) fails here instead of shipping 2x the ICI traffic.

Wire-cost model (ring algorithms, the standard N-device lower bounds):

- all-reduce of S result bytes      -> 2 * (N-1)/N * S
- reduce-scatter of S result bytes  -> (N-1) * S   (input is N*S)
- all-gather of S result bytes      -> (N-1)/N * S (input is S/N)
"""
from __future__ import annotations

import re
from typing import Dict, List, Tuple

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_COLLECTIVES = ("all-reduce", "reduce-scatter", "all-gather")

# `f32[28,256,3]{...}` (tuple results repeat the token per element)
_SHAPE_RE = re.compile(r"\b([a-z]+\d*)\[([\d,]*)\]")
_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.+?)\s+"
    r"(all-reduce|reduce-scatter|all-gather)(?:-start)?\(")


def _shape_bytes(type_text: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_text):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def collective_ops(hlo_text: str) -> List[Tuple[str, int]]:
    """[(op_kind, result_bytes)] for every collective in the program
    (``-start`` async forms fold into their base op; ``-done`` and
    constant/metadata lines don't match)."""
    out = []
    for line in hlo_text.splitlines():
        m = _OP_RE.match(line)
        if m:
            out.append((m.group(2), _shape_bytes(m.group(1))))
    return out


def collective_wire_bytes(hlo_text: str, n_dev: int) -> Dict[str, float]:
    """Ring-model wire bytes per collective kind plus their sum.

    Returns ``{"all-reduce": b, "reduce-scatter": b, "all-gather": b,
    "total": b, "max_allreduce_result": bytes}`` — the last is the
    largest single all-reduce result in the program (the "is a full
    histogram still being broadcast?" probe).
    """
    per = {k: 0.0 for k in _COLLECTIVES}
    max_ar = 0
    for kind, size in collective_ops(hlo_text):
        if kind == "all-reduce":
            per[kind] += 2.0 * (n_dev - 1) / n_dev * size
            max_ar = max(max_ar, size)
        elif kind == "reduce-scatter":
            per[kind] += float(n_dev - 1) * size
        elif kind == "all-gather":
            per[kind] += (n_dev - 1) / n_dev * size
    per["total"] = sum(per[k] for k in _COLLECTIVES)
    per["max_allreduce_result"] = float(max_ar)
    return per
