"""Static + runtime hazard analysis for the hot path and threaded tier.

Four pieces, one contract:

- :mod:`.jaxlint` — pure-stdlib AST pass (rules JL001-JL005, suppression
  comments, baseline diff). CLI: ``python scripts/jaxlint.py``.
- :mod:`.concurrency` — the concurrency analogue ("conlint", rules
  CL001-CL005) over the lock-bearing serving/service/robustness/native
  modules: lock-order inversions, blocking calls under locks,
  shared-state escapes, Condition.wait discipline, thread lifecycle.
  CLI: ``python scripts/jaxlint.py --pass concurrency``.
- :mod:`.guards` — opt-in runtime guards (compile-count budgets, transfer
  guards, ``LGBM_TPU_GUARDS`` env toggle). Imports jax lazily; import it
  explicitly where needed so the lint CLI never initializes a backend.
- :mod:`.lockorder` — opt-in runtime lock-order tracker
  (``LGBM_TPU_GUARDS=lockorder``): wraps Lock/RLock/Condition creation
  in the instrumented modules, records the cross-thread acquisition
  graph, raises on a cycle. Pure stdlib.

See README "Static analysis & dispatch guards" for the workflow.
"""
from .jaxlint import (  # noqa: F401
    Finding,
    default_baseline_path,
    default_targets,
    diff_against_baseline,
    lint_source,
    load_baseline,
    run_paths,
    save_baseline,
)
from .rules import ALL_RULES, RULE_IDS  # noqa: F401
from .concurrency import (  # noqa: F401
    CONCURRENCY_RULE_IDS,
    CONCURRENCY_RULES,
    LockGraph,
    TARGET_MODULES,
)
