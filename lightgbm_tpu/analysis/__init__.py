"""Static + runtime JAX-hazard analysis ("jaxlint") for the hot path.

Two halves, one contract:

- :mod:`.jaxlint` — pure-stdlib AST pass (rules JL001-JL005, suppression
  comments, baseline diff). CLI: ``python scripts/jaxlint.py``.
- :mod:`.guards` — opt-in runtime guards (compile-count budgets, transfer
  guards, ``LGBM_TPU_GUARDS`` env toggle). Imports jax lazily; import it
  explicitly where needed so the lint CLI never initializes a backend.

See README "Static analysis & dispatch guards" for the workflow.
"""
from .jaxlint import (  # noqa: F401
    Finding,
    default_baseline_path,
    default_targets,
    diff_against_baseline,
    lint_source,
    load_baseline,
    run_paths,
    save_baseline,
)
from .rules import ALL_RULES, RULE_IDS  # noqa: F401
