"""`python -m lightgbm_tpu` — the CLI entry point (ref: src/main.cpp)."""
from .cli import main

main()
