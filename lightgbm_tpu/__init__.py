"""LightGBM-TPU: TPU-native gradient boosting framework."""
__version__ = "0.1.0"
