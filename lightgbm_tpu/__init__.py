"""LightGBM-TPU: TPU-native gradient boosting framework.

Public surface mirrors python-package/lightgbm/__init__.py of the reference:
Dataset, Booster, train, cv, callbacks, sklearn estimators, plotting.

``LIGHTGBM_TPU_PLATFORM=cpu|tpu`` pins the jax backend before first use
(useful to run CLI/examples on a CPU host or to opt out of a busy
accelerator); unset, jax picks its default platform.

``LGBM_TPU_GUARDS=1|log|strict`` (alias ``LIGHTGBM_TPU_GUARDS``) turns
on the dispatch guards — transfer-guard + compile logging — for ANY
process that imports the package (bench, scripts, tests); see
``lightgbm_tpu/analysis/guards.py`` and README "Static analysis &
dispatch guards".

``LIGHTGBM_TPU_DEBUG_CHECKS=1`` turns on the runtime sanitizers — the
XLA-world analogue of the reference's ASan/TSan CI builds (SURVEY §5):
``jax_debug_nans`` (every jitted op re-checked for NaN/Inf production,
failing loudly at the producing op instead of corrupting training
downstream) and ``jax_check_tracer_leaks`` (leaked tracers — the jit
purity violations that stand in for data races in a functional
runtime — raise instead of silently capturing stale values). Orders of
magnitude slower; for debugging, like the sanitizers it mirrors.
"""
import os as _os

if _os.environ.get("LIGHTGBM_TPU_PLATFORM"):
    import jax as _jax

    _jax.config.update("jax_platforms",
                       _os.environ["LIGHTGBM_TPU_PLATFORM"])

if _os.environ.get("LIGHTGBM_TPU_DEBUG_CHECKS", "").lower() not in \
        ("", "0", "false", "off"):
    import jax as _jax

    _jax.config.update("jax_debug_nans", True)
    _jax.config.update("jax_check_tracer_leaks", True)

# opt-in dispatch guards (no-op, and no jax import, when the env is
# unset) — hooked here so LGBM_TPU_GUARDS audits any run, not just pytest
from .analysis import guards as _guards

_guards.install_from_env()

# opt-in fault injection (LGBM_TPU_FAULTS, robustness/faults.py) — the
# chaos counterpart of the guards: any importing process (bench, CLI,
# tests, worker subprocesses) runs under the injected fault plan
from .robustness import faults as _faults

_faults.install_from_env()

from .basic import Booster, Dataset, LightGBMError
from .io.sequence import Sequence
from .callback import (EarlyStopException, checkpoint_callback,
                       early_stopping, log_evaluation,
                       record_evaluation, reset_parameter)
from .engine import CVBooster, cv, train
from .utils.log import register_logger

__version__ = "0.1.0"

__all__ = [
    "Dataset", "Booster", "LightGBMError", "Sequence",
    "train", "cv", "CVBooster",
    "early_stopping", "log_evaluation", "record_evaluation",
    "reset_parameter", "EarlyStopException", "checkpoint_callback",
    "register_logger",
]


def __getattr__(name):
    # lazy imports for the heavier optional surfaces
    if name in ("LGBMModel", "LGBMRegressor", "LGBMClassifier", "LGBMRanker"):
        from . import sklearn as _sk
        return getattr(_sk, name)
    if name in ("FleetServer", "ModelServer", "TenantHandle",
                "serve_fleet"):
        from . import serving as _srv
        return getattr(_srv, name)
    if name in ("ContinualService", "FrontDoor", "ServerGateway",
                "serve_continual"):
        from . import service as _svc
        return getattr(_svc, name)
    if name in ("plot_importance", "plot_metric", "plot_tree",
                "create_tree_digraph", "plot_split_value_histogram"):
        from . import plotting as _pl
        return getattr(_pl, name)
    raise AttributeError(f"module 'lightgbm_tpu' has no attribute {name!r}")
