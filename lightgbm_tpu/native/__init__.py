"""On-demand build + ctypes bindings for the native runtime kernels.

The parsing hot path (CSV/TSV/LibSVM byte scanning) runs as C++
(parser.cpp) compiled once per machine into ``_build/lgbm_native.so``;
every entry point has a pure-numpy fallback so the package works without
a compiler (``LIGHTGBM_TPU_NO_NATIVE=1`` forces the fallback).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils import log

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO_PATH = os.path.join(_BUILD_DIR, "lgbm_native.so")
_SRCS = [os.path.join(_HERE, "parser.cpp"),
         os.path.join(_HERE, "c_api.cpp"),
         os.path.join(_HERE, "c_api_train.cpp"),
         os.path.join(_HERE, "shap.cpp"),
         os.path.join(_HERE, "arrow_ingest.cpp")]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if (os.path.exists(_SO_PATH) and
            os.path.getmtime(_SO_PATH) >= max(os.path.getmtime(s)
                                              for s in _SRCS)):
        return _SO_PATH
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           *_SRCS, "-ldl", "-o", _SO_PATH + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO_PATH + ".tmp", _SO_PATH)
        return _SO_PATH
    except (OSError, subprocess.SubprocessError) as e:
        log.debug(f"native build failed ({e}); using numpy fallbacks")
        return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (fallback mode)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("LIGHTGBM_TPU_NO_NATIVE"):
            return None
        # conlint: disable=CL002 — deliberate: double-checked one-time
        # build; holding _lock across the g++ run is the point (every
        # other thread needs the built .so before it can do anything)
        so = _build()
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        lib.lgbm_count_cols.restype = ctypes.c_int64
        lib.lgbm_count_cols.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char]
        lib.lgbm_parse_dense.restype = ctypes.c_int64
        lib.lgbm_parse_dense.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
        lib.lgbm_parse_libsvm.restype = ctypes.c_int64
        lib.lgbm_parse_libsvm.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)]
        lib.lgbm_tree_shap_batch.restype = ctypes.c_int
        lib.lgbm_tree_shap_batch.argtypes = [
            ctypes.POINTER(ctypes.c_int32),   # split_feature
            ctypes.POINTER(ctypes.c_double),  # threshold_real
            ctypes.POINTER(ctypes.c_int32),   # decision_type
            ctypes.POINTER(ctypes.c_int32),   # left_child
            ctypes.POINTER(ctypes.c_int32),   # right_child
            ctypes.POINTER(ctypes.c_double),  # leaf_value
            ctypes.POINTER(ctypes.c_double),  # leaf_count
            ctypes.POINTER(ctypes.c_double),  # internal_count
            ctypes.c_int32,                   # n_int
            ctypes.POINTER(ctypes.c_int32),   # cat_boundaries
            ctypes.POINTER(ctypes.c_uint32),  # cat_threshold
            ctypes.c_int32,                   # num_cat
            ctypes.c_int32,                   # n_cat_words
            ctypes.POINTER(ctypes.c_double),  # X
            ctypes.c_int64,                   # nrow
            ctypes.c_int32,                   # ncol
            ctypes.POINTER(ctypes.c_double),  # out
            ctypes.c_int64,                   # out_stride
            ctypes.c_int32]                   # nthreads
        _lib = lib
        return _lib


def _count_rows(chunk: bytes) -> int:
    return sum(1 for ln in chunk.split(b"\n") if ln.strip())


def parse_dense_chunk(chunk: bytes, sep: str, n_cols: int) -> np.ndarray:
    """Parse a newline-aligned CSV/TSV byte chunk -> float64 [rows, n_cols]."""
    lib = get_lib()
    if lib is not None:
        max_rows = chunk.count(b"\n") + 1
        out = np.empty((max_rows, n_cols), np.float64)
        buf = chunk + b"\0"
        n = lib.lgbm_parse_dense(
            buf, len(chunk), sep.encode()[0], n_cols,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_rows)
        return out[:n]
    # numpy fallback
    rows = [ln for ln in chunk.decode("utf-8", "replace").split("\n")
            if ln.strip()]
    out = np.full((len(rows), n_cols), np.nan)
    for i, ln in enumerate(rows):
        for j, tok in enumerate(ln.split(sep)[:n_cols]):
            tok = tok.strip()
            if tok == "" or tok.lower() in ("na", "nan", "null", "?"):
                continue
            try:
                out[i, j] = float(tok)
            except ValueError:
                pass
    return out


def parse_libsvm_chunk(chunk: bytes) -> Tuple[np.ndarray, np.ndarray,
                                              np.ndarray, np.ndarray, int]:
    """Parse a LibSVM byte chunk -> (labels, rows, cols, vals, max_col)."""
    lib = get_lib()
    if lib is not None:
        max_rows = chunk.count(b"\n") + 1
        max_nnz = max(chunk.count(b":"), 1)
        labels = np.empty(max_rows, np.float64)
        rows = np.empty(max_nnz, np.int32)
        cols = np.empty(max_nnz, np.int32)
        vals = np.empty(max_nnz, np.float64)
        nnz = ctypes.c_int64()
        max_col = ctypes.c_int32()
        buf = chunk + b"\0"
        n = lib.lgbm_parse_libsvm(
            buf, len(chunk),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_rows,
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), max_nnz,
            ctypes.byref(nnz), ctypes.byref(max_col))
        k = nnz.value
        return labels[:n], rows[:k], cols[:k], vals[:k], int(max_col.value)
    # numpy fallback
    lines = [ln for ln in chunk.decode("utf-8", "replace").split("\n")
             if ln.strip()]
    labels = np.zeros(len(lines))
    r_l, c_l, v_l = [], [], []
    max_col = -1
    for i, ln in enumerate(lines):
        toks = ln.split()
        if toks:
            try:
                labels[i] = float(toks[0])
            except ValueError:
                labels[i] = np.nan
        for t in toks[1:]:
            if ":" not in t:
                continue
            k, _, v = t.partition(":")
            try:
                idx = int(k)
                val = float(v)
            except ValueError:
                continue
            r_l.append(i)
            c_l.append(idx)
            v_l.append(val)
            max_col = max(max_col, idx)
    return (labels, np.asarray(r_l, np.int32), np.asarray(c_l, np.int32),
            np.asarray(v_l, np.float64), max_col)


def iter_file_chunks(path: str, skip_lines: int = 0,
                     chunk_bytes: int = 32 << 20):
    """Yield newline-aligned byte chunks of a text file."""
    with open(path, "rb") as f:
        for _ in range(skip_lines):
            f.readline()
        carry = b""
        while True:
            block = f.read(chunk_bytes)
            if not block:
                if carry.strip():
                    yield carry
                return
            block = carry + block
            cut = block.rfind(b"\n")
            if cut < 0:
                carry = block
                continue
            yield block[:cut + 1]
            carry = block[cut + 1:]
