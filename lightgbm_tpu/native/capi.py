"""ctypes convenience wrapper over the native C API (c_api.cpp).

``NativeBooster`` serves a saved model.txt through the LGBM_* ABI with no
JAX in the loop — the deployment path for C/C++/FFI hosts; these bindings
exist for tests and for Python users who want interpreter-light serving.
"""
from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from . import get_lib

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1
C_API_PREDICT_LEAF_INDEX = 2


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    if getattr(lib, "_capi_declared", False):
        return lib
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    lib.LGBM_BoosterCreateFromModelfile.restype = ctypes.c_int
    lib.LGBM_BoosterCreateFromModelfile.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterLoadModelFromString.restype = ctypes.c_int
    lib.LGBM_BoosterLoadModelFromString.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_void_p)]
    lib.LGBM_BoosterFree.argtypes = [ctypes.c_void_p]
    for name in ("LGBM_BoosterGetNumClasses", "LGBM_BoosterGetNumFeature",
                 "LGBM_BoosterGetCurrentIteration",
                 "LGBM_BoosterNumModelPerIteration"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    lib.LGBM_BoosterPredictForMat.restype = ctypes.c_int
    lib.LGBM_BoosterPredictForMat.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double)]
    lib._capi_declared = True
    return lib


class NativeBooster:
    """Model served by the native library (prediction only)."""

    def __init__(self, model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable (no compiler?)")
        self._lib = _declare(lib)
        self._handle = ctypes.c_void_p()
        n_iter = ctypes.c_int()
        if model_file is not None:
            rc = self._lib.LGBM_BoosterCreateFromModelfile(
                str(model_file).encode(), ctypes.byref(n_iter),
                ctypes.byref(self._handle))
        elif model_str is not None:
            rc = self._lib.LGBM_BoosterLoadModelFromString(
                model_str.encode(), ctypes.byref(n_iter),
                ctypes.byref(self._handle))
        else:
            raise ValueError("model_file or model_str required")
        if rc != 0:
            raise RuntimeError(self._lib.LGBM_GetLastError().decode())
        self.num_iterations = n_iter.value

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.LGBM_BoosterFree(self._handle)
            self._handle = None

    def _get_int(self, fn_name: str) -> int:
        out = ctypes.c_int()
        getattr(self._lib, fn_name)(self._handle, ctypes.byref(out))
        return out.value

    @property
    def num_classes(self) -> int:
        return self._get_int("LGBM_BoosterGetNumClasses")

    @property
    def num_features(self) -> int:
        return self._get_int("LGBM_BoosterGetNumFeature")

    @property
    def num_model_per_iteration(self) -> int:
        return self._get_int("LGBM_BoosterNumModelPerIteration")

    def predict(self, X: np.ndarray, raw_score: bool = False,
                pred_leaf: bool = False, start_iteration: int = 0,
                num_iteration: int = -1) -> np.ndarray:
        X = np.ascontiguousarray(X, dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        nrow, ncol = X.shape
        K = self.num_model_per_iteration
        if pred_leaf:
            ptype = C_API_PREDICT_LEAF_INDEX
            total = self.num_iterations if num_iteration <= 0 else \
                min(self.num_iterations, start_iteration + num_iteration)
            width = (total - start_iteration) * K
        else:
            ptype = (C_API_PREDICT_RAW_SCORE if raw_score
                     else C_API_PREDICT_NORMAL)
            width = K
        out = np.empty((nrow, width), np.float64)
        out_len = ctypes.c_int64()
        rc = self._lib.LGBM_BoosterPredictForMat(
            self._handle, X.ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, nrow, ncol, 1, ptype, start_iteration,
            num_iteration, b"",
            ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        if rc != 0:
            raise RuntimeError(self._lib.LGBM_GetLastError().decode())
        assert out_len.value == nrow * width
        if pred_leaf:
            return out.astype(np.int32)
        return out[:, 0] if width == 1 else out
